"""paddle.distributed.spawn parity.

Reference: ``python/paddle/distributed/spawn.py`` — fork N worker processes,
set per-rank PADDLE_* env, run ``func`` in each, join and re-raise failures.

TPU-native shape: a real TPU pod is driven one-process-per-HOST via
``paddle_tpu.distributed.launch`` (single-controller per host), so spawn's
job here is the single-host multi-process development path: N CPU-backend
``jax.distributed`` processes on one machine — the same world the reference
builds with one GPU per process. Each child gets PADDLE_TRAINER_ID /
PADDLE_TRAINERS_NUM / PADDLE_TPU_COORDINATOR so ``init_parallel_env()``
inside ``func`` forms the collective world.
"""
from __future__ import annotations

import multiprocessing as mp
import os
import socket
import sys


def _free_port() -> int:
    """Probe for a free port. TOCTOU by construction: the socket closes
    before the child coordinator binds, so on busy hosts another process can
    grab the port in between — ``spawn`` retries the whole launch with a
    fresh port when a worker dies on a bind failure (exit ``_BIND_EXIT``)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# Distinctive exit code for "coordinator port was taken" (EADDRINUSE=98):
# the parent's join maps it to a retry-with-fresh-port instead of a failure.
_BIND_EXIT = 98


def _is_bind_error(e: BaseException) -> bool:
    s = str(e).lower()
    return ("address already in use" in s or "eaddrinuse" in s
            or "failed to bind" in s or "errno 98" in s
            or "error binding" in s)


def _worker(func, args):
    # env is inherited from the parent's per-rank os.environ snapshot (set
    # around p.start()): it must be in place BEFORE this function body runs,
    # because unpickling the target itself imports paddle_tpu (and jax).
    try:
        func(*args)
    except Exception as e:
        if _is_bind_error(e):
            sys.stderr.write(
                f"paddle_tpu.distributed.spawn worker: coordinator bind "
                f"failed ({e}); exiting {_BIND_EXIT} for port retry\n"
            )
            sys.exit(_BIND_EXIT)
        raise


def _default_join_timeout():
    """Default SpawnContext.join deadline in seconds (env-overridable via
    PADDLE_TPU_SPAWN_JOIN_TIMEOUT_S; 0 or unset-able to ``none`` disables).
    A wedged child must surface as a reaped, reported failure — never as a
    parent blocked forever."""
    raw = os.environ.get("PADDLE_TPU_SPAWN_JOIN_TIMEOUT_S", "")
    if not raw:
        return 3600.0
    try:
        t = float(raw)
    except ValueError:
        return 3600.0
    return t if t > 0 else None


def _last_progress(ranks, pdir=None):
    """Each rank's last watchdog progress record, read from the launch's
    PADDLE_TPU_PROGRESS_DIR (set by _launch for its children). The wedged-
    child report: WHERE each rank was when the parent gave up on it."""
    pdir = pdir or os.environ.get("PADDLE_TPU_PROGRESS_DIR")
    if not pdir:
        return {}
    try:
        from .watchdog import _read_progress_dir

        table = _read_progress_dir(pdir)
    except Exception:
        return {}
    return {r: table[r] for r in ranks if r in table}


class SpawnContext:
    def __init__(self, procs, progress_dir=None):
        self.processes = procs
        self.progress_dir = progress_dir
        # ranks that exited with the preemption drain's RESUMABLE_EXIT_CODE
        # (75) in the last join(): the world checkpointed cleanly and asked
        # for a restart — spawn() honors it the way launch_mod does
        self.resumable_ranks = []

    def join(self, timeout="default"):
        """Wait for all workers, POLLING so one crashed rank is detected even
        while its peers sit blocked in a collective waiting for it — the rest
        are then terminated and the failure raised (the reference's
        watch-and-kill loop in spawn.py).

        ``timeout="default"`` applies the env-overridable deadline
        (PADDLE_TPU_SPAWN_JOIN_TIMEOUT_S, 3600s unset): past it the parent
        REAPS the remaining children and raises a report carrying each
        wedged rank's last progress record instead of blocking forever.
        ``timeout=None`` waits indefinitely; a number is an explicit
        deadline past which join returns False (legacy polling contract).

        Exit code 75 (RESUMABLE_EXIT_CODE) is NOT a failure: those ranks are
        recorded in ``resumable_ranks`` and join returns True — the caller
        (``spawn``) relaunches the world, same as launch_mod."""
        import time

        from ..fault.preemption import RESUMABLE_EXIT_CODE

        reap_on_deadline = timeout == "default"
        if reap_on_deadline:
            timeout = _default_join_timeout()
        deadline = None if timeout is None else time.monotonic() + timeout
        self.resumable_ranks = []
        while True:
            bad = [(r, p.exitcode) for r, p in enumerate(self.processes)
                   if p.exitcode not in (0, RESUMABLE_EXIT_CODE, None)]
            if bad:
                for p in self.processes:  # one failure sinks the job
                    if p.is_alive():
                        p.terminate()
                for p in self.processes:
                    p.join(5)
                rank, code = bad[0]
                err = RuntimeError(
                    f"spawn worker rank {rank} exited with code {code} "
                    f"({len(bad)} of {len(self.processes)} workers failed)"
                )
                # a _BIND_EXIT rank means the probed coordinator port was
                # taken before the child bound it (TOCTOU) — spawn() retries
                err.bind_failure = any(c == _BIND_EXIT for _, c in bad)
                raise err
            alive = [p for p in self.processes if p.exitcode is None]
            if not alive:
                self.resumable_ranks = [
                    r for r, p in enumerate(self.processes)
                    if p.exitcode == RESUMABLE_EXIT_CODE
                ]
                return True
            if deadline is not None and time.monotonic() >= deadline:
                if not reap_on_deadline:
                    return False
                wedged = [r for r, p in enumerate(self.processes)
                          if p.exitcode is None]
                progress = _last_progress(wedged, self.progress_dir)
                for p in self.processes:
                    if p.is_alive():
                        p.terminate()
                for p in self.processes:
                    p.join(5)
                detail = "; ".join(
                    f"rank {r}: last progress "
                    + (f"step {progress[r].get('step')} phase "
                       f"{progress[r].get('phase')!r}" if r in progress
                       else "never published")
                    for r in wedged
                )
                raise RuntimeError(
                    f"spawn: workers {wedged} still running after "
                    f"{timeout:.0f}s join deadline — reaped ({detail}). "
                    "Raise PADDLE_TPU_SPAWN_JOIN_TIMEOUT_S for longer jobs."
                )
            alive[0].join(0.2)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, backend=None,
          **options):
    """Run ``func(*args)`` in ``nprocs`` processes forming one collective
    world. ``nprocs<=1`` runs inline (single-controller fast path). Children
    default to the CPU backend: one host has one TPU client, and N processes
    contending for it is never what a multi-process dev run means — multihost
    TPU launches go through ``paddle_tpu.distributed.launch`` instead."""
    if nprocs in (-1, 0):
        nprocs = 1
    if nprocs <= 1:
        func(*args)
        return None if join else SpawnContext([])
    if backend is None:
        backend = "cpu"
    bind_retries = max(int(options.pop("bind_retries", 3)), 1)
    max_resumes = max(int(options.pop("max_resumes", 32)), 0)
    bind_attempt = 0
    resumes = 0
    while True:
        context = _launch(func, args, nprocs, backend, daemon, options)
        if not join:
            # caller owns the join — no bind-retry possible past this point
            return context
        try:
            context.join()
        except RuntimeError as e:
            if not getattr(e, "bind_failure", False) \
                    or bind_attempt >= bind_retries - 1:
                raise
            # coordinator port raced away (classic TOCTOU on busy hosts):
            # relaunch the whole world on a fresh probe port
            bind_attempt += 1
            continue
        if not context.resumable_ranks:
            return None
        # RESUMABLE_EXIT_CODE (75): the world drained + checkpointed and
        # wants a restart — honor it exactly like launch_mod, on a separate
        # (larger) budget than real failures
        resumes += 1
        if resumes > max_resumes:
            raise RuntimeError(
                f"spawn: workers asked for more than max_resumes="
                f"{max_resumes} restarts (ranks {context.resumable_ranks} "
                "exited resumably again)"
            )


def _launch(func, args, nprocs, backend, daemon, options):
    import tempfile

    coordinator = f"127.0.0.1:{_free_port()}"
    ctx = mp.get_context("spawn")
    procs = []
    # distributed-supervision substrate for the children: a shared progress
    # dir (watchdog publications — the parent's wedged-child report reads
    # it) and a FileStore dir (coordinated checkpoint commit barrier).
    # An env-provided dir (chaos harness, nested launches) wins.
    progress_dir = os.environ.get("PADDLE_TPU_PROGRESS_DIR") or tempfile.mkdtemp(
        prefix="paddle_tpu_progress_"
    )
    store_dir = os.environ.get("PADDLE_TPU_STORE_DIR") or tempfile.mkdtemp(
        prefix="paddle_tpu_store_"
    )
    # Children must see the worker env BEFORE their first import: unpickling
    # the process target imports paddle_tpu (and thus jax), so env set inside
    # the child function body is too late. Mutate os.environ around each
    # p.start() (children snapshot it at exec) and restore after.
    child_env = {
        "PADDLE_TRAINERS_NUM": str(nprocs),
        "PADDLE_TPU_COORDINATOR": coordinator,
        "JAX_PLATFORMS": backend,
        "PADDLE_TPU_PROGRESS_DIR": progress_dir,
        "PADDLE_TPU_STORE_DIR": store_dir,
    }
    child_env.update(options.get("env", {}))
    # strip sitecustomize dirs from the children's PYTHONPATH: a
    # sitecustomize that eagerly imports jax (TPU tunnel images) creates the
    # backend client at interpreter startup, turning the worker's
    # jax.distributed.initialize into a no-op (world collapses to 1). Module
    # imports in children are unaffected — multiprocessing ships the parent's
    # sys.path explicitly.
    old_pp = os.environ.get("PYTHONPATH")
    if old_pp is not None and "PYTHONPATH" not in child_env:
        # an explicit env={'PYTHONPATH': ...} override wins over the strip
        child_env["PYTHONPATH"] = os.pathsep.join(
            p for p in old_pp.split(os.pathsep)
            if p and not os.path.exists(os.path.join(p, "sitecustomize.py"))
        )
    saved = {k: os.environ.get(k) for k in (*child_env, "PADDLE_TRAINER_ID",
                                            "PADDLE_LOCAL_RANK")}
    try:
        os.environ.update(child_env)
        for rank in range(nprocs):
            os.environ["PADDLE_TRAINER_ID"] = str(rank)
            os.environ["PADDLE_LOCAL_RANK"] = str(rank)
            p = ctx.Process(target=_worker, args=(func, args), daemon=daemon)
            p.start()
            procs.append(p)
    except BaseException:
        # a failed start() mid-loop must not orphan earlier ranks — they sit
        # blocked in the jax.distributed rendezvous for a world that will
        # never form
        for p in procs:
            if p.is_alive():
                p.terminate()
        for p in procs:
            p.join(5)
        raise
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return SpawnContext(procs, progress_dir=progress_dir)
