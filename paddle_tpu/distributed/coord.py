"""Coordination substrate: store abstraction + two-phase commit barrier.

The watchdog and the coordinated checkpoint protocol both need a small KV
store shared by every rank. Production launches have the native C++ TCPStore
(runtime_cpp/tcp_store.cc, the etcd analogue); this module adds a
**FileStore** with the same ``set/get/add/delete_key`` surface over a shared
directory, so single-host multi-process jobs (``spawn``) and the chaos tests
coordinate without the native lib — and so a dead store can never be the
reason recovery itself hangs: every wait here carries a deadline.

``CommitBarrier`` is the store-mediated two-phase barrier behind
checkpoint.CoordinatedCheckpoint: phase 1 collects one ack per rank (each
rank's shard is serialized, CRC'd and durable), phase 2 publishes a single
commit record observed by every rank. A crash at ANY point before phase 2
leaves the step uncommitted on every rank — resume can never mix steps.
"""
from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Callable, Dict, Optional

__all__ = [
    "FileStore", "CommitBarrier", "DeadlineExceeded", "wait_for",
    "store_from_env",
]


class DeadlineExceeded(TimeoutError):
    """A coordinated wait ran past its deadline. Carries ``what`` (which
    wait) and ``waited_s`` so the flight dump / error names the stall."""

    def __init__(self, what: str, waited_s: float, detail: str = ""):
        super().__init__(
            f"deadline exceeded after {waited_s:.1f}s waiting for {what}"
            + (f" ({detail})" if detail else "")
        )
        self.what = what
        self.waited_s = waited_s


def wait_for(
    poll: Callable[[], bool],
    what: str,
    timeout_s: float,
    interval_s: float = 0.05,
    on_timeout: Optional[Callable[[], None]] = None,
) -> None:
    """Poll ``poll()`` until truthy or ``timeout_s`` elapses. The
    interruptible-wait analogue of watchdog.guard for store round-trips:
    polling loops need no monitor thread — the loop itself owns the clock.
    ``timeout_s<=0`` means no deadline (poll forever)."""
    t0 = time.monotonic()
    while not poll():
        if timeout_s > 0 and time.monotonic() - t0 > timeout_s:
            if on_timeout is not None:
                on_timeout()
            raise DeadlineExceeded(what, time.monotonic() - t0)
        time.sleep(interval_s)


class FileStore:
    """TCPStore-shaped KV over a shared directory (single host / shared fs).

    Writes are atomic (tmp + ``os.replace``); ``add`` uses a lock directory
    (``os.mkdir`` is atomic on POSIX) so concurrent increments from N ranks
    serialize. Keys map to files with ``/`` escaped, so the store survives
    arbitrary key grammars without creating directory trees.
    """

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        os.makedirs(self.path, exist_ok=True)

    def _file(self, key: str) -> str:
        return os.path.join(self.path, key.replace("/", "%2f"))

    def set(self, key: str, value) -> None:
        if isinstance(value, str):
            value = value.encode()
        f = self._file(key)
        fd, tmp = tempfile.mkstemp(dir=self.path, prefix=".tmp_")
        try:
            with os.fdopen(fd, "wb") as fh:
                fh.write(value)
            os.replace(tmp, f)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise

    def get(self, key: str):
        try:
            with open(self._file(key), "rb") as fh:
                return fh.read()
        except OSError:
            return None

    def add(self, key: str, amount: int = 1) -> int:
        lock = self._file(key) + ".lock"
        deadline = time.monotonic() + 30.0
        while True:
            try:
                os.mkdir(lock)
                break
            except FileExistsError:
                if time.monotonic() > deadline:
                    raise TimeoutError(f"FileStore.add: lock stuck for {key!r}")
                time.sleep(0.002)
        try:
            raw = self.get(key)
            cur = int(raw) if raw else 0
            cur += int(amount)
            self.set(key, str(cur))
            return cur
        finally:
            os.rmdir(lock)

    def delete_key(self, key: str) -> None:
        try:
            os.remove(self._file(key))
        except OSError:
            pass

    def keys(self):
        """All keys currently present (FileStore extension, used by the
        progress table to enumerate ranks)."""
        out = []
        for name in os.listdir(self.path):
            if name.startswith(".tmp_") or name.endswith(".lock"):
                continue
            out.append(name.replace("%2f", "/"))
        return out

    def close(self) -> None:
        pass


def store_from_env() -> Optional[FileStore]:
    """The rank-shared store named by ``PADDLE_TPU_STORE_DIR`` (set by spawn
    / the chaos harness for its children), or None."""
    d = os.environ.get("PADDLE_TPU_STORE_DIR")
    return FileStore(d) if d else None


class CommitBarrier:
    """Two-phase commit over a store (TCPStore or FileStore).

    Phase 1 — ``ack(tag)``: this rank's local work for ``tag`` (a checkpoint
    step) is durable. Phase 2 — rank 0 waits for ``world_size`` acks and
    publishes the commit record; every other rank waits for it. Distinct
    tags are independent, so a retried save at a later step never collides
    with litter from a crashed earlier attempt.
    """

    def __init__(self, store, world_size: int, rank: int, prefix: str = "commit"):
        self.store = store
        self.world_size = int(world_size)
        self.rank = int(rank)
        self.prefix = prefix

    def _ack_key(self, tag) -> str:
        return f"{self.prefix}/{tag}/acks"

    def _commit_key(self, tag) -> str:
        return f"{self.prefix}/{tag}/commit"

    def ack(self, tag) -> int:
        return self.store.add(self._ack_key(tag), 1)

    def reset(self, tag) -> None:
        """Clear litter a crashed earlier attempt left behind for ``tag``
        (stale acks / commit record). Rank 0 calls this when it ENTERS a
        save attempt, before serializing: without it, a relaunched job
        replaying to the same step would find the dead attempt's acks and
        commit before every rank of the new attempt has written durably —
        a torn checkpoint with a valid marker. Peers ack only after their
        own serialize+write completes, so in a lockstep world the reset
        strictly precedes this attempt's acks; losing that race merely
        times the save out (uncommitted, safe, retried next interval)."""
        self.store.delete_key(self._ack_key(tag))
        self.store.delete_key(self._commit_key(tag))

    def acks(self, tag) -> int:
        raw = self.store.get(self._ack_key(tag))
        return int(raw) if raw else 0

    def committed(self, tag) -> bool:
        return self.store.get(self._commit_key(tag)) is not None

    def commit(self, tag, timeout_s: float, payload: Optional[dict] = None) -> dict:
        """Run this rank's side of the two-phase commit for ``tag``. Returns
        the commit record. Raises :class:`DeadlineExceeded` when the other
        ranks never arrive — the caller (coordinated save) treats that as a
        failed, UNcommitted save and walks on."""
        if self.rank == 0:
            wait_for(
                lambda: self.acks(tag) >= self.world_size,
                f"commit barrier acks ({self.prefix}/{tag})",
                timeout_s,
            )
            rec = {"tag": str(tag), "ts": time.time(),
                   "world_size": self.world_size, **(payload or {})}
            self.store.set(self._commit_key(tag), json.dumps(rec))
            return rec
        wait_for(
            lambda: self.committed(tag),
            f"commit marker ({self.prefix}/{tag})",
            timeout_s,
        )
        return json.loads(self.store.get(self._commit_key(tag)))
