"""paddle.distributed parity — TPU-native.

Parity: reference ``python/paddle/distributed/`` (collective.py op wrappers,
fleet, launch/spawn) over NCCL rings (§2.4 of SURVEY.md). TPU-native design:
ONE global ``jax.sharding.Mesh`` over all chips; collectives are either
 (a) eager host-visible ops executed via pmap-style shard_map on demand, or
 (b) compiler-inserted HLO collectives when running inside pjit/shard_map —
the idiomatic path. Process bootstrap maps to ``jax.distributed.initialize``
(coordination service) instead of TCP ncclUniqueId plumbing
(``paddle/fluid/platform/gen_comm_id_helper.cc:348``).
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np
import jax

from ..core.tensor import Tensor
from .collective import (  # noqa: F401
    all_reduce, all_gather, all_to_all, alltoall, broadcast, reduce, scatter,
    reduce_scatter, send, recv, barrier, split as _dist_split, new_group,
    get_group, ReduceOp, wait,
)
from .parallel_env import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, ParallelEnv,
)
from . import fleet  # noqa: F401
from .mesh import (  # noqa: F401
    global_mesh, set_global_mesh, build_mesh, mesh_axis_size,
)
from .sharding_api import shard_tensor, shard_op  # noqa: F401
from .parallel import DataParallel  # noqa: F401
from . import utils  # noqa: F401
from .spawn import spawn  # noqa: F401
from .launch_mod import launch  # noqa: F401


def get_backend():
    return "xla"


def TCPStore(host="127.0.0.1", port=23456, world_size=None, is_master=False, timeout=30):
    """Native KV rendezvous store (reference distributed/store/tcp_store.h,
    C++ impl in runtime_cpp/tcp_store.cc)."""
    from ..core.native import TCPStore as _Store

    return _Store(host=host, port=port, is_master=is_master, timeout=timeout)


def is_initialized():
    from .parallel_env import _initialized

    return _initialized()
from . import checkpoint  # noqa: F401
from . import auto_parallel  # noqa: F401
from .checkpoint import save_state_dict, load_state_dict  # noqa: F401
