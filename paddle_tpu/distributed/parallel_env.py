"""Parallel environment bootstrap.

Parity: reference ``python/paddle/distributed/parallel.py``
(init_parallel_env: NCCL id TCP bootstrap + ParallelEnv from PADDLE_* env).
TPU-native: ``jax.distributed.initialize`` (coordination service) replaces
comm-id plumbing; rank/world come from the PJRT process topology.
"""
from __future__ import annotations

import os
import time
import warnings

import jax

_init_done = False
_last_hb_warn = 0.0
_HB_WARN_INTERVAL_S = 60.0


def _initialized():
    return _init_done


def _warn_heartbeat_failure(e: Exception) -> None:
    """Advisory degradation made VISIBLE: heartbeat registration failing
    means the elastic watcher will see this worker as dead even while it
    trains — rate-limited warning + counter instead of a silent pass."""
    global _last_hb_warn
    try:
        from .. import profiler

        profiler.counter_inc("heartbeat_failures")
    except Exception:
        pass
    now = time.monotonic()
    if now - _last_hb_warn >= _HB_WARN_INTERVAL_S:
        _last_hb_warn = now
        warnings.warn(
            f"elastic heartbeat registration failed ({e!r}); training "
            "proceeds but the elastic watcher cannot see this worker — it "
            "may be declared dead and the job relaunched",
            RuntimeWarning,
        )


def init_parallel_env():
    global _init_done
    if _init_done:
        return ParallelEnv()
    coord = os.environ.get("PADDLE_TPU_COORDINATOR") or os.environ.get("COORDINATOR_ADDRESS")
    n = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if coord and n > 1:
        # must run BEFORE any backend touch (jax.process_count() would
        # initialize the client and make distributed init a no-op)
        already = False
        if hasattr(jax.distributed, "is_initialized"):
            already = jax.distributed.is_initialized()
        else:  # fallback for older jax without the public probe
            try:
                from jax._src import distributed as _jdist

                already = getattr(_jdist.global_state, "coordinator_address", None) is not None
            except ImportError:
                pass
        if not already:
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=n,
                process_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")),
            )
    # distributed supervision: bind the watchdog session (rank/world from
    # the launcher env; progress store/dir when provided). Progress-aware
    # heartbeats + guarded collectives need this; with no launcher env it
    # is a 1-rank session that never publishes anywhere.
    from . import watchdog

    watchdog.configure(
        rank=int(os.environ.get("PADDLE_TRAINER_ID", "0")), world_size=n
    )
    # elastic mode: register this worker's heartbeat on the elastic store
    est = os.environ.get("PADDLE_ELASTIC_STORE")
    wid = os.environ.get("PADDLE_ELASTIC_WORKER_ID")
    if est and wid:
        try:
            from . import TCPStore
            from .fleet.elastic import ElasticManager

            host, _, port = est.partition(":")
            store = TCPStore(host=host, port=int(port), is_master=False)
            ElasticManager(store, n, worker_id=wid).register()
        except Exception as e:
            # heartbeat is advisory — training proceeds — but the
            # degradation must be visible (rate-limited warning +
            # heartbeat_failures counter), not a silent pass
            _warn_heartbeat_failure(e)
    _init_done = True
    return ParallelEnv()


def get_rank(group=None):
    if group is not None:
        return 0
    return jax.process_index()


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    return jax.process_count()


class ParallelEnv:
    """Reference: fluid/dygraph/parallel.py ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_LOCAL_RANK", get_rank()))

    @property
    def nranks(self):
        return get_world_size()

    @property
    def dev_id(self):
        return self.local_rank

    @property
    def device_type(self):
        return jax.default_backend()

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "127.0.0.1:6170")
        return eps.split(",")
