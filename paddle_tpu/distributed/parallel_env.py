"""Parallel environment bootstrap.

Parity: reference ``python/paddle/distributed/parallel.py``
(init_parallel_env: NCCL id TCP bootstrap + ParallelEnv from PADDLE_* env).
TPU-native: ``jax.distributed.initialize`` (coordination service) replaces
comm-id plumbing; rank/world come from the PJRT process topology.
"""
from __future__ import annotations

import os

import jax

_init_done = False


def _initialized():
    return _init_done


def init_parallel_env():
    global _init_done
    if _init_done:
        return ParallelEnv()
    coord = os.environ.get("PADDLE_TPU_COORDINATOR") or os.environ.get("COORDINATOR_ADDRESS")
    if coord and jax.process_count() == 1:
        try:
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=int(os.environ.get("PADDLE_TRAINERS_NUM", "1")),
                process_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")),
            )
        except Exception:
            pass
    _init_done = True
    return ParallelEnv()


def get_rank(group=None):
    if group is not None:
        return 0
    return jax.process_index()


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    return jax.process_count()


class ParallelEnv:
    """Reference: fluid/dygraph/parallel.py ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_LOCAL_RANK", get_rank()))

    @property
    def nranks(self):
        return get_world_size()

    @property
    def dev_id(self):
        return self.local_rank

    @property
    def device_type(self):
        return jax.default_backend()

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "127.0.0.1:6170")
        return eps.split(",")
