"""DataParallel wrapper.

Parity: reference ``paddle.DataParallel``
(``python/paddle/fluid/dygraph/parallel.py:397``) + C++ Reducer bucketing
(``paddle/fluid/imperative/reducer.cc``). TPU-native: gradient averaging is
compiler-inserted when the train step runs under pjit with the batch sharded
on the dp axis — no bucket/fusion machinery is needed (XLA fuses and
schedules the all-reduces). Inside a shard_map trace, backward hooks psum
grads over the dp axis to give the same semantics op-for-op.

``apply_collective_grads`` is the explicit reducer path: per-param grads are
coalesced into reverse-backward-order flat buckets (fleet/grad_buckets.py)
and synced with a few large collectives — inside a shard_map trace these are
real pmean/quantized all-reduces over the dp axis; under the eager lazy
engine the bucketed sync is RECORDED into the pending graph with the bucket
layout in the node key, so the fused train-step executable keeps a stable
signature (warm cache) and the displaced full-grad buffers become lazy-flush
donation candidates.
"""
from __future__ import annotations

import jax
from jax import lax

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25, last_comm_buffer_size=1, find_unused_parameters=False, group=None):
        super().__init__()
        self._layers = layers
        self._group = group
        self.add_sublayer("_layers", layers)
        self.find_unused_parameters = find_unused_parameters
        self._comm_buffer_bytes = int(comm_buffer_size) * 1024 * 1024
        self._bucket_plan = None
        self._bucket_params = None

    def forward(self, *inputs, **kwargs):
        out = self._layers(*inputs, **kwargs)
        return out

    def _psum_grads_hook(self):
        """Register per-param grad psum for explicit shard_map DP training."""
        axis = self._group.axis_name if self._group is not None else "dp"

        def make_hook():
            def hook(grad_arr):
                if isinstance(grad_arr, jax.core.Tracer):
                    from .mesh import mesh_axis_size

                    return Tensor(lax.pmean(grad_arr._data if isinstance(grad_arr, Tensor) else grad_arr, axis))
                return grad_arr

            return hook

        for p in self._layers.parameters():
            p.register_hook(make_hook())

    def scale_loss(self, loss):
        return loss

    def _plan_for(self, params, nranks, block):
        from .fleet.grad_buckets import build_bucket_plan

        sig = (tuple(id(p) for p in params), int(nranks), int(block))
        if self._bucket_plan is None or self._bucket_params != sig:
            from ..framework import flags as _flags

            self._bucket_plan = build_bucket_plan(
                params,
                nranks=nranks,  # pad so quantized shards divide evenly
                bucket_bytes=self._comm_buffer_bytes
                or _flags.flag("FLAGS_dp_bucket_bytes"),
                block=block,
            )
            self._bucket_params = sig
        return self._bucket_plan

    def apply_collective_grads(self):
        """Bucketed gradient sync (the reference Reducer's fused
        all-reduce). Buckets go out in reverse-backward order; inside a
        shard_map trace each bucket is one pmean (or EQuARX int8 all-reduce
        under ``FLAGS_quantized_allreduce``) over the dp axis. Eagerly on a
        single controller the collective is the identity, but grads are
        still rebound through the bucketed nodes so the lazy flush donates
        the dead pre-sync grad buffers."""
        from ..framework import flags as _flags
        from .collective import quantized_all_reduce_mean

        params = [
            p for p in self._layers.parameters()
            if not p.stop_gradient and p.grad is not None
        ]
        if not params:
            return
        axis = self._group.axis_name if self._group is not None else "dp"
        quant = bool(_flags.flag("FLAGS_quantized_allreduce", False))
        block = int(_flags.flag("FLAGS_quantized_allreduce_block", 128))

        grads = [p.grad._data if isinstance(p.grad, Tensor) else p.grad
                 for p in params]
        traced = any(isinstance(g, jax.core.Tracer) for g in grads)
        from .collective import _axis_bound

        live_axis = traced and _axis_bound(axis)
        if live_axis:
            from ..core.compat import axis_size

            n = int(axis_size(axis))
        else:
            n = 1
        plan = self._plan_for(params, n, block)
        if quant and _flags.flag("FLAGS_quantized_allreduce_error_feedback", False):
            import warnings

            warnings.warn(
                "FLAGS_quantized_allreduce_error_feedback has no effect on "
                "DataParallel.apply_collective_grads — the residual needs "
                "cross-step state, which only the distributed engine's "
                "sharded-weight-update path carries",
                stacklevel=2,
            )

        from ..core import lazy as lazy_mod

        def sync_bucket(b, *arrs):
            flat = plan.flatten(b, arrs)
            if live_axis:
                if quant:
                    out, _ = quantized_all_reduce_mean(flat, axis, n, block)
                    out = out.astype(flat.dtype)
                else:
                    out = lax.pmean(flat, axis)
            else:
                out = flat  # single participant: identity, still coalesced
            return tuple(plan.unflatten(b, out))

        from .. import profiler
        from ..profiler import spans as _spans

        record_lazy = not live_axis and (
            lazy_mod.lazy_enabled() or any(lazy_mod.is_lazy(g) for g in grads)
        )
        with _spans.span(
            "dp_sync", buckets=len(plan.buckets), world=n, quantized=quant,
            lazy=record_lazy,
        ) as ssp:
            for b in plan.buckets:
                b_params = [params[i] for i in b.indices]
                b_grads = [grads[i] for i in b.indices]
                # per-bucket collective span: under the lazy engine this times
                # the RECORD (the collective itself runs inside the fused
                # flush); in a live shard_map trace it times the real launch
                with _spans.span(
                    "dp_bucket", bytes=b.padded * b.itemsize,
                    params=len(b.indices), dtype=str(b.dtype),
                ):
                    if record_lazy:
                        outs, _ = lazy_mod.record(
                            "dp_bucket_sync",
                            lambda *a, _b=b: sync_bucket(_b, *a),
                            list(b_grads),
                            key=("dp_bucket_sync", plan.signature, b.key(), quant),
                        )
                        synced = outs
                    else:
                        synced = sync_bucket(b, *b_grads)
                for p, g in zip(b_params, synced):
                    # rebind through the sync: _set_data marks the old grad
                    # buffer as a lazy-flush donation candidate
                    if isinstance(p.grad, Tensor):
                        p.grad._set_data(g)
                    else:
                        p.grad = Tensor(g, stop_gradient=True)
            # dp_buckets counts bucketed sync operations (coalescing ran even
            # at world 1); collective-launch/wire counters only count real ones
            profiler.counter_inc("dp_buckets", len(plan.buckets))
            if n > 1:
                sync_bytes = plan.sync_bytes("all_reduce", quant)
                profiler.counter_inc("dp_all_reduces", len(plan.buckets))
                profiler.counter_inc("dp_sync_bytes", sync_bytes)
                ssp.set(sync_bytes=sync_bytes)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)
