"""DataParallel wrapper.

Parity: reference ``paddle.DataParallel``
(``python/paddle/fluid/dygraph/parallel.py:397``) + C++ Reducer bucketing
(``paddle/fluid/imperative/reducer.cc``). TPU-native: gradient averaging is
compiler-inserted when the train step runs under pjit with the batch sharded
on the dp axis — no bucket/fusion machinery is needed (XLA fuses and
schedules the all-reduces). Inside a shard_map trace, backward hooks psum
grads over the dp axis to give the same semantics op-for-op.
"""
from __future__ import annotations

import jax
from jax import lax

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25, last_comm_buffer_size=1, find_unused_parameters=False, group=None):
        super().__init__()
        self._layers = layers
        self._group = group
        self.add_sublayer("_layers", layers)
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        out = self._layers(*inputs, **kwargs)
        return out

    def _psum_grads_hook(self):
        """Register per-param grad psum for explicit shard_map DP training."""
        axis = self._group.axis_name if self._group is not None else "dp"

        def make_hook():
            def hook(grad_arr):
                if isinstance(grad_arr, jax.core.Tracer):
                    from .mesh import mesh_axis_size

                    return Tensor(lax.pmean(grad_arr._data if isinstance(grad_arr, Tensor) else grad_arr, axis))
                return grad_arr

            return hook

        for p in self._layers.parameters():
            p.register_hook(make_hook())

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)
