"""Semi-automatic parallelization — annotation completion + engine.

Parity: reference ``python/paddle/distributed/auto_parallel/`` —
``engine.py:64`` (Engine: prepare/fit over a cluster+strategy),
``completion.py:111`` (complete distributed attributes from partial user
annotations), ``cost_model.py``. TPU-native split of labor: GSPMD already
propagates shardings through every op, so completion here only has to pick
PARAMETER placements; XLA's compiled ``cost_analysis`` is the cost model
that validates a plan (flops/bytes-accessed per candidate).
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np
import jax
from jax.sharding import Mesh, PartitionSpec as P

from ...core.tensor import Tensor
from ..mesh import global_mesh


def _axis_size(mesh, name):
    return dict(zip(mesh.axis_names, mesh.devices.shape)).get(name, 1)


# -- sharding plans ----------------------------------------------------------

class ShardingPlan:
    """A complete parameter placement: {param_name: PartitionSpec|None}.
    The TPU-native analogue of the reference's per-op dist-attr assignment
    (completion.py:111 output) — GSPMD propagates through ops, so a plan
    only has to pin the parameters (+ optional activation constraints)."""

    def __init__(self, name: str, param_specs: dict):
        self.name = name
        self.param_specs = dict(param_specs)
        self.score = None  # filled by select_plan
        self.report = None

    def apply(self, model):
        for pname, p in model.named_parameters():
            if getattr(p, "pspec", None) is not None and pname not in self.param_specs:
                continue  # keep user annotations not covered by the plan
            p.pspec = self.param_specs.get(pname)
        return model

    def __repr__(self):
        n = sum(1 for s in self.param_specs.values() if s is not None)
        return f"ShardingPlan({self.name!r}, {n} sharded params, score={self.score})"


# Name hints for the Megatron pairing (reference dist_matmul.py column/row
# variants are chosen per op; these cover the transformer naming conventions).
# Col hints include the full HF/Llama forms (gate_proj/up_proj) — they must
# win before the generic 'proj' row hint matches them.
_COL_HINTS = ("qkv", "q_proj", "k_proj", "v_proj", "query", "key", "value",
              "up", "up_proj", "gate", "gate_proj", "fc1", "w1", "wi",
              "in_proj")
_ROW_HINTS = ("down", "down_proj", "o_proj", "out_proj", "fc2", "w2", "wo",
              "proj", "dense")


def _classify(name: str):
    last = name.split(".")[-2] if name.endswith((".weight", ".bias")) else name
    last = last.lower()
    if any(h == last or last.endswith("_" + h) or last.endswith("." + h) for h in _COL_HINTS):
        return "col"
    if any(h == last or last.endswith("_" + h) or last.endswith("." + h) for h in _ROW_HINTS):
        return "row"
    return None


def _megatron_specs(model, mp: int, mp_axis: str) -> dict:
    """Structure-aware Megatron placement. Per PARENT module, 2-D weights
    pair up column→row in order (fixes the order-fragility of a global
    alternation counter: interleaved 1-D params or sibling modules can't
    desynchronize the pairing); explicit name hints win over position."""
    specs = {}
    shapes = {}
    by_parent: dict = {}
    for name, p in model.named_parameters():
        shape = tuple(p.shape)
        shapes[name] = shape
        # vocab/position table: tall (≥4x) AND genuinely table-sized — the
        # row floor keeps small tall Linears (e.g. 64x16) out of the branch
        if (len(shape) == 2 and shape[0] >= 4 * shape[1] and shape[0] >= 256
                and shape[0] % mp == 0):
            specs[name] = P(mp_axis, None)
            continue
        if len(shape) != 2:
            specs[name] = None
            continue
        parent = name.rsplit(".", 2)[0] if name.count(".") >= 2 else ""
        by_parent.setdefault(parent, []).append((name, shape))
    for parent, entries in by_parent.items():
        # hint-classified weights shard unconditionally; UNclassified ones
        # only pair col→row when the parent holds an even number of them
        # (a lone unpaired weight sharded one way would force a gather with
        # no matching partner — conservative default: replicate)
        unclassified = [n for n, _ in entries if _classify(n) is None]
        pair_ok = len(unclassified) >= 2 and len(unclassified) % 2 == 0
        flip = 0
        for name, shape in entries:
            kind = _classify(name)
            if kind is None:
                if not pair_ok:
                    specs[name] = None
                    continue
                kind = "col" if flip % 2 == 0 else "row"
                flip += 1
            if kind == "col" and shape[1] % mp == 0:
                specs[name] = P(None, mp_axis)
                # Megatron pairs the column weight with a SHARDED bias
                # (mp_layers.py ColumnParallelLinear bias pspec)
                bias = name[: -len("weight")] + "bias" if name.endswith(".weight") else None
                if bias in shapes and len(shapes[bias]) == 1 and shapes[bias][0] % mp == 0:
                    specs[bias] = P(mp_axis)
            elif shape[0] % mp == 0:
                specs[name] = P(mp_axis, None)
            else:
                specs[name] = None
    return specs


def derive_candidate_plans(model, mesh: Optional[Mesh] = None, mp_axis="mp", dp_axis="dp"):
    """Candidate placements for an unannotated model (the plan-search space
    the reference explores via completion+cost_model). Returns plans in
    preference order; select_plan scores them on the actual compiled step."""
    mesh = mesh or global_mesh()
    mp = _axis_size(mesh, mp_axis)
    names = [n for n, _ in model.named_parameters()]
    # user shard_tensor annotations overlay EVERY candidate (they are
    # constraints on the search, exactly like reference completion treats
    # partial user dist-attrs)
    user = {n: p.pspec for n, p in model.named_parameters()
            if getattr(p, "pspec", None) is not None}

    def with_user(specs):
        specs = dict(specs)
        specs.update(user)
        return specs

    plans = [ShardingPlan("replicated", with_user({n: None for n in names}))]
    if mp > 1:
        plans.insert(0, ShardingPlan(
            "megatron", with_user(_megatron_specs(model, mp, mp_axis))
        ))
        # embedding-only: shard just the big tables (bandwidth-bound models)
        emb = {}
        for n, p in model.named_parameters():
            shape = tuple(p.shape)
            if (len(shape) == 2 and shape[0] >= 4 * shape[1]
                    and shape[0] >= 256 and shape[0] % mp == 0):
                emb[n] = P(mp_axis, None)
            else:
                emb[n] = None
        if any(s is not None for s in emb.values()):
            plans.append(ShardingPlan("embedding-only", with_user(emb)))
    return plans


def complete_annotations(model, mesh: Optional[Mesh] = None, mp_axis="mp", dp_axis="dp"):
    """Assign PartitionSpecs to every un-annotated parameter (reference
    completion.py:111 — a placement pass instead of per-op dist-attr
    inference, because GSPMD owns op propagation). Applies the structure-
    aware Megatron plan; Engine.prepare(auto=True) additionally scores the
    candidate plans on the compiled step and keeps the cheapest."""
    mesh = mesh or global_mesh()
    mp = _axis_size(mesh, mp_axis)
    if mp <= 1:
        return model
    user = {n: p.pspec for n, p in model.named_parameters()
            if getattr(p, "pspec", None) is not None}
    specs = _megatron_specs(model, mp, mp_axis)
    specs.update(user)  # user annotations always win
    ShardingPlan("megatron", specs).apply(model)
    return model


# -- reshard -----------------------------------------------------------------

def reshard(x, placement, mesh: Optional[Mesh] = None):
    """Pin a value (Tensor or array, eager or traced) to a sharding — the
    reference's reshard pass (reshard.py:1) inserts send/recv between
    incompatibly-sharded producer/consumer; under GSPMD the same capability
    is a sharding constraint and XLA inserts the collective."""
    mesh = mesh or global_mesh()
    spec = placement if isinstance(placement, P) else P(*placement)
    sharding = jax.sharding.NamedSharding(mesh, spec)
    if isinstance(x, Tensor):
        from ...core.lazy import concrete as _conc

        arr = x._data
        if isinstance(arr, jax.core.Tracer):
            return Tensor(jax.lax.with_sharding_constraint(arr, sharding),
                          stop_gradient=x.stop_gradient)
        return Tensor(jax.device_put(_conc(arr), sharding),
                      stop_gradient=x.stop_gradient)
    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x, sharding)
    return jax.device_put(x, sharding)


# -- cost model / plan selection ---------------------------------------------

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all")


def analyze_collectives(hlo_text: str):
    """Count communication ops and bytes in a compiled (post-SPMD) HLO
    module. The comm half of the cost model the reference builds op tables
    for (auto_parallel/cost_model.py)."""
    import re

    counts = {c: 0 for c in _COLLECTIVES}
    total_bytes = 0.0
    shape_re = re.compile(r"([a-z]+[0-9]+|pred)\[([0-9,]*)\]")
    for line in hlo_text.splitlines():
        m = re.search(r"=\s*(\([^)]*\)|\S+)\s+([a-z\-]+)", line)
        if not m:
            continue
        op = m.group(2)
        base = op[:-6] if op.endswith("-start") else op
        if base not in counts:
            continue
        if op.endswith("-done"):
            continue
        counts[base] += 1
        out_part = line.split("=", 1)[1].split(base)[0]
        for dt, dims in shape_re.findall(out_part):
            numel = 1
            for d in dims.split(","):
                if d:
                    numel *= int(d)
            total_bytes += numel * _DTYPE_BYTES.get(dt, 4)
    counts = {k: v for k, v in counts.items() if v}
    return {"counts": counts, "bytes": total_bytes}


# Roofline constants (v5e class) — only RATIOS matter for ranking plans.
_PEAK_FLOPS = 197e12
_HBM_BW = 819e9
_ICI_BW = 90e9


def plan_cost(compiled) -> dict:
    """Roofline score of one compiled per-device program: compute time +
    HBM time + ICI time (+ peak memory for budget checks)."""
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    flops = float(cost.get("flops", 0.0))
    bytes_acc = float(cost.get("bytes accessed", cost.get("bytes_accessed", 0.0)))
    comm = analyze_collectives(compiled.as_text())
    peak = 0
    try:
        mem = compiled.memory_analysis()
        peak = int(getattr(mem, "temp_size_in_bytes", 0)) + int(
            getattr(mem, "output_size_in_bytes", 0)
        ) + int(getattr(mem, "argument_size_in_bytes", 0))
    except Exception:
        pass
    t = flops / _PEAK_FLOPS + bytes_acc / _HBM_BW + comm["bytes"] / _ICI_BW
    return {
        "time_proxy": t, "flops": flops, "bytes_accessed": bytes_acc,
        "comm_bytes": comm["bytes"], "comm_counts": comm["counts"],
        "peak_memory_bytes": peak,
    }


def select_plan(model, plans, build_compiled, memory_budget: Optional[int] = None):
    """Score each candidate plan on its ACTUAL compiled train step and apply
    the best (reference: completion candidates ranked by cost_model).

    ``build_compiled()`` must compile the current placement and return the
    jax Compiled object (e.g. engine._jit.lower(*args).compile()).
    Plans over the memory budget are rejected; ties break on comm bytes."""
    original = {n: getattr(p, "pspec", None) for n, p in model.named_parameters()}
    best = None
    for plan in plans:
        plan.apply(model)
        try:
            compiled = build_compiled()
            rep = plan_cost(compiled)
        except Exception as e:  # unshardable plan (bad divisibility, …)
            plan.report = {"error": str(e)[:200]}
            continue
        plan.report = rep
        over = memory_budget is not None and rep["peak_memory_bytes"] > memory_budget
        plan.score = (1 if over else 0, rep["time_proxy"], rep["comm_bytes"])
        if best is None or plan.score < best.score:
            best = plan
    if best is None:
        # leave the model exactly as the caller annotated it, not with the
        # last failed candidate's pspecs
        for n, p in model.named_parameters():
            p.pspec = original[n]
        raise RuntimeError("no candidate sharding plan compiled successfully")
    best.apply(model)
    return best


def estimate_cost(fn: Callable, *example_args, mesh: Optional[Mesh] = None):
    """XLA-backed cost model (reference python/paddle/cost_model/ — op-level
    cost tables; here the compiler's own analysis): returns
    {'flops', 'bytes_accessed', 'peak_memory_bytes?'} for the jitted fn."""
    compiled = jax.jit(fn).lower(*example_args).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    out = {
        "flops": float(cost.get("flops", 0.0)),
        "bytes_accessed": float(cost.get("bytes accessed", cost.get("bytes_accessed", 0.0))),
    }
    try:
        mem = compiled.memory_analysis()
        out["peak_memory_bytes"] = int(getattr(mem, "temp_size_in_bytes", 0)) + int(
            getattr(mem, "output_size_in_bytes", 0)
        )
    except Exception:
        pass
    return out


class Engine:
    """Auto-parallel engine (reference auto_parallel/engine.py:64): give it a
    model + loss + optimizer and a mesh; it completes placements and builds
    the one-program hybrid step."""

    def __init__(self, model, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None, mesh: Optional[Mesh] = None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.mesh = mesh or global_mesh()
        self._engine = None

    def prepare(self, *a, **k):
        from ..engine import HybridParallelEngine

        complete_annotations(self.model, self.mesh)

        loss_fn = self.loss

        def wrapped(model, *batch):
            out = loss_fn(model(*batch[:-1]), batch[-1]) if loss_fn else model(*batch)
            return out

        self._engine = HybridParallelEngine(self.model, self.optimizer, wrapped, mesh=self.mesh)
        return self

    def auto_parallelize(self, *example_batch, memory_budget=None):
        """Full auto-parallel: derive candidate plans, compile each one's
        train step, score (roofline compute + HBM + ICI comm, peak memory
        budget), apply the winner (reference completion+partitioner+reshard
        +cost_model loop, GSPMD-first). Returns the winning ShardingPlan."""
        from ..engine import HybridParallelEngine
        from ...core import random as random_state

        loss_fn = self.loss

        def wrapped(model, *batch):
            out = loss_fn(model(*batch[:-1]), batch[-1]) if loss_fn else model(*batch)
            return out

        plans = derive_candidate_plans(self.model, self.mesh)
        batch_t = [b if isinstance(b, Tensor) else Tensor(np.asarray(b)) for b in example_batch]

        def build_compiled():
            st = random_state._get()
            saved_key = st.key
            try:
                eng = HybridParallelEngine(
                    self.model, self.optimizer, wrapped, mesh=self.mesh, donate=False
                )
                args = eng._prepare(*batch_t)
                return eng._jit.lower(*args).compile()
            finally:
                st.key = saved_key

        best = select_plan(self.model, plans, build_compiled, memory_budget)
        self._engine = HybridParallelEngine(
            self.model, self.optimizer, wrapped, mesh=self.mesh
        )
        self.plan = best
        return best

    def fit(self, train_data, epochs=1, batch_size=None, steps_per_epoch=None, **k):
        if self._engine is None:
            self.prepare()
        history = []
        for _ in range(epochs):
            for step, batch in enumerate(train_data):
                if steps_per_epoch and step >= steps_per_epoch:
                    break
                batch = batch if isinstance(batch, (list, tuple)) else (batch,)
                loss = self._engine.train_step(*batch)
                history.append(float(loss.item()))
        return history

    def cost(self, *example_batch):
        """Estimated cost of one training step under the current plan.
        Read-only: the global RNG stream is restored (same discipline as
        HybridParallelEngine.lower_text) so the query can't perturb training."""
        from ...core import random as random_state

        if self._engine is None:
            self.prepare()
        st = random_state._get()
        saved_key = st.key
        try:
            args = self._engine._prepare(*example_batch)
            compiled = self._engine._jit.lower(*args).compile()
        finally:
            st.key = saved_key
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return {"flops": float(cost.get("flops", 0.0))}


__all__ = [
    "Engine", "ShardingPlan", "analyze_collectives", "complete_annotations",
    "derive_candidate_plans", "estimate_cost", "plan_cost", "reshard",
    "select_plan",
]
