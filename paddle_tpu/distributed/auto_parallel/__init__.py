"""Semi-automatic parallelization — annotation completion + engine.

Parity: reference ``python/paddle/distributed/auto_parallel/`` —
``engine.py:64`` (Engine: prepare/fit over a cluster+strategy),
``completion.py:111`` (complete distributed attributes from partial user
annotations), ``cost_model.py``. TPU-native split of labor: GSPMD already
propagates shardings through every op, so completion here only has to pick
PARAMETER placements; XLA's compiled ``cost_analysis`` is the cost model
that validates a plan (flops/bytes-accessed per candidate).
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np
import jax
from jax.sharding import Mesh, PartitionSpec as P

from ...core.tensor import Tensor
from ..mesh import global_mesh


def _axis_size(mesh, name):
    return dict(zip(mesh.axis_names, mesh.devices.shape)).get(name, 1)


def complete_annotations(model, mesh: Optional[Mesh] = None, mp_axis="mp", dp_axis="dp"):
    """Assign PartitionSpecs to every un-annotated parameter (reference
    completion.py:111 — here a placement pass instead of per-op dist-attr
    inference, because GSPMD owns op propagation).

    Heuristic (the Megatron pattern the reference's completion converges to):
      * embeddings (first dim = vocab-like, >= 4x second) -> shard dim 0;
      * consecutive 2-D weights alternate column/row sharding over ``mp``;
      * 1-D params (bias/scale) stay replicated;
      * anything already annotated (user ``shard_tensor``) is kept.
    """
    mesh = mesh or global_mesh()
    mp = _axis_size(mesh, mp_axis)
    if mp <= 1:
        return model
    flip = 0
    for name, p in model.named_parameters():
        if getattr(p, "pspec", None) is not None:
            continue
        shape = tuple(p.shape)
        if len(shape) < 2:
            continue
        if shape[0] >= 4 * shape[1] and shape[0] % mp == 0:  # embedding-like
            p.pspec = P(mp_axis, None)
            continue
        if len(shape) == 2:
            # alternate column (out-dim) / row (in-dim) sharding
            if flip % 2 == 0 and shape[1] % mp == 0:
                p.pspec = P(None, mp_axis)
            elif shape[0] % mp == 0:
                p.pspec = P(mp_axis, None)
            flip += 1
    return model


def estimate_cost(fn: Callable, *example_args, mesh: Optional[Mesh] = None):
    """XLA-backed cost model (reference python/paddle/cost_model/ — op-level
    cost tables; here the compiler's own analysis): returns
    {'flops', 'bytes_accessed', 'peak_memory_bytes?'} for the jitted fn."""
    compiled = jax.jit(fn).lower(*example_args).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    out = {
        "flops": float(cost.get("flops", 0.0)),
        "bytes_accessed": float(cost.get("bytes accessed", cost.get("bytes_accessed", 0.0))),
    }
    try:
        mem = compiled.memory_analysis()
        out["peak_memory_bytes"] = int(getattr(mem, "temp_size_in_bytes", 0)) + int(
            getattr(mem, "output_size_in_bytes", 0)
        )
    except Exception:
        pass
    return out


class Engine:
    """Auto-parallel engine (reference auto_parallel/engine.py:64): give it a
    model + loss + optimizer and a mesh; it completes placements and builds
    the one-program hybrid step."""

    def __init__(self, model, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None, mesh: Optional[Mesh] = None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.mesh = mesh or global_mesh()
        self._engine = None

    def prepare(self, *a, **k):
        from ..engine import HybridParallelEngine

        complete_annotations(self.model, self.mesh)

        loss_fn = self.loss

        def wrapped(model, *batch):
            out = loss_fn(model(*batch[:-1]), batch[-1]) if loss_fn else model(*batch)
            return out

        self._engine = HybridParallelEngine(self.model, self.optimizer, wrapped, mesh=self.mesh)
        return self

    def fit(self, train_data, epochs=1, batch_size=None, steps_per_epoch=None, **k):
        if self._engine is None:
            self.prepare()
        history = []
        for _ in range(epochs):
            for step, batch in enumerate(train_data):
                if steps_per_epoch and step >= steps_per_epoch:
                    break
                batch = batch if isinstance(batch, (list, tuple)) else (batch,)
                loss = self._engine.train_step(*batch)
                history.append(float(loss.item()))
        return history

    def cost(self, *example_batch):
        """Estimated cost of one training step under the current plan.
        Read-only: the global RNG stream is restored (same discipline as
        HybridParallelEngine.lower_text) so the query can't perturb training."""
        from ...core import random as random_state

        if self._engine is None:
            self.prepare()
        st = random_state._get()
        saved_key = st.key
        try:
            args = self._engine._prepare(*example_batch)
            compiled = self._engine._jit.lower(*args).compile()
        finally:
            st.key = saved_key
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return {"flops": float(cost.get("flops", 0.0))}


__all__ = ["Engine", "complete_annotations", "estimate_cost"]
