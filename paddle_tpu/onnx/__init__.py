"""paddle.onnx — export gate.

Parity target: reference ``python/paddle/onnx/export.py`` (paddle2onnx).
This build's portable AOT format is StableHLO via ``paddle.jit.save`` (runs
anywhere XLA runs, incl. CPU serving — see paddle_tpu.inference). ONNX
emission from StableHLO requires an external converter that is not part of
this environment, so export() raises with that guidance rather than writing
a file that silently isn't ONNX.
"""
from __future__ import annotations


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "ONNX export is not available in this build. Use paddle.jit.save() "
        "to produce a portable StableHLO artifact (loadable on CPU/TPU via "
        "paddle_tpu.inference.Predictor), or convert that artifact with an "
        "external StableHLO->ONNX tool."
    )


__all__ = ["export"]
