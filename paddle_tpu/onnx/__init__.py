"""paddle.onnx — portable-interchange export.

Parity target: reference ``python/paddle/onnx/export.py``, which delegates to
the external paddle2onnx converter; the CAPABILITY is "one artifact, loadable
by other runtimes/hosts". This build's portable interchange format is
serialized StableHLO (``jax.export``): ``export()`` writes
``{path}.pdmodel`` (multi-platform StableHLO: compiled for cpu AND tpu
whenever every op has a multi-platform lowering) + ``{path}.pdiparams``
(named weights), the same artifact ``paddle.jit.save`` produces. A CPU-only
process with no TPU access loads and runs it via ``paddle.jit.load`` or
``paddle_tpu.inference.Predictor`` — the deployment property ONNX provides
in the reference stack.

Actual .onnx protobuf emission needs the external onnx package /
StableHLO→ONNX converter, neither present in this environment; when
``format="onnx"`` is requested explicitly, export() raises with that
guidance instead of writing a file that silently isn't ONNX.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, format="stablehlo",
           **configs):
    """Write a portable inference artifact for ``layer``.

    ``format="stablehlo"`` (default): multi-platform StableHLO + params at
    ``{path}`` (".onnx" suffix is dropped); returns the artifact prefix.
    ``format="onnx"``: not available in this build — raises with guidance.
    """
    if format == "onnx":
        raise NotImplementedError(
            "ONNX protobuf emission is not available in this build (no "
            "paddle2onnx / StableHLO->ONNX converter in the environment). "
            "The default format='stablehlo' writes the portable artifact "
            "this framework deploys with (CPU and TPU hosts)."
        )
    if format != "stablehlo":
        raise ValueError(f"unknown export format: {format!r}")
    from .. import jit as _jit

    prefix = path[:-5] if path.endswith(".onnx") else path
    _jit.save(layer, prefix, input_spec=input_spec)
    return prefix
