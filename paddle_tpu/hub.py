"""paddle.hub — local-source model hub.

Parity: reference ``python/paddle/hapi/hub.py`` (list/help/load from github/
local sources). This environment has no network egress, so only the
``source='local'`` path is functional; remote sources raise with guidance.
"""
from __future__ import annotations

import importlib.util
import os
import sys

_HUB_CONF = "hubconf.py"


def _load_local(repo_dir):
    path = os.path.join(repo_dir, _HUB_CONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {_HUB_CONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["hubconf"] = mod
    spec.loader.exec_module(mod)
    return mod


def _check_source(source):
    if source != "local":
        raise NotImplementedError(
            "paddle.hub: only source='local' is available in this build "
            "(no network egress); point repo_dir at a local checkout with a "
            "hubconf.py"
        )


def list(repo_dir, source="local", force_reload=False):
    _check_source(source)
    mod = _load_local(repo_dir)
    return [n for n in dir(mod) if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):
    _check_source(source)
    return getattr(_load_local(repo_dir), model).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    _check_source(source)
    return getattr(_load_local(repo_dir), model)(**kwargs)


__all__ = ["list", "help", "load"]
