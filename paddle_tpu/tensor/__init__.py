"""paddle.tensor — the tensor-function namespace (reference
python/paddle/tensor/): the same op surface that is attached to
``paddle.*`` and as Tensor methods, re-exported under the module paths v1
code imports from (paddle.tensor.math / creation / manipulation / linalg /
search / logic / random / attribute / stat)."""
from ..ops import creation, linalg, manipulation, math, misc  # noqa: F401
from ..ops.creation import *  # noqa: F401,F403
from ..ops.math import *  # noqa: F401,F403
from ..ops.manipulation import *  # noqa: F401,F403
from ..ops.linalg import *  # noqa: F401,F403

# reference sub-module aliases (paddle.tensor.math.add etc.)
import types as _types

random = creation
attribute = math
stat = math
logic = math
# search spans both modules in the reference (argmax/argmin live with math
# here; sort/searchsorted with manipulation) — expose the union
search = _types.SimpleNamespace(
    **{n: getattr(manipulation, n) for n in dir(manipulation) if not n.startswith("_")},
    **{n: getattr(math, n) for n in ("argmax", "argmin") if hasattr(math, n)},
)
