"""paddle.device parity (reference python/paddle/device/__init__.py)."""
from ..core.place import (  # noqa: F401
    set_device, get_device, current_place, Place, CPUPlace, TPUPlace, CUDAPlace,
    is_compiled_with_cuda, is_compiled_with_tpu,
)
import jax


def device_count(device_type=None):
    devs = jax.devices()
    if device_type:
        devs = [d for d in devs if d.platform == device_type]
    return len(devs)


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


class cuda:  # namespace parity: paddle.device.cuda
    @staticmethod
    def device_count():
        return device_count("gpu")

    @staticmethod
    def synchronize(device=None):
        pass


def synchronize(device=None):
    """Block until all pending device work completes (reference: device sync)."""
    # JAX dispatch is async; a trivial transfer forces a sync point.
    jax.effects_barrier()
