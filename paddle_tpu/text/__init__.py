"""paddle.text — text domain API.

Parity: reference ``python/paddle/text/`` (datasets + viterbi_decode op
``paddle/fluid/operators/viterbi_decode_op.h``). Decode is a lax.scan DP —
compiled, batch-vectorized.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import as_tensor, eager_call

from .datasets import Imdb, Imikolov, UCIHousing, Conll05st, Movielens, WMT14, WMT16  # noqa: F401,E402
from .faster_tokenizer import FasterTokenizer  # noqa: F401,E402

__all__ = ["viterbi_decode", "ViterbiDecoder", "FasterTokenizer", "Imdb", "Imikolov", "UCIHousing", "Conll05st", "Movielens", "WMT14", "WMT16"]


def viterbi_decode(potentials, transition_params, lengths, include_bos_eos_tag=True, name=None):
    """Batched Viterbi (reference text/viterbi_decode.py -> viterbi_decode_op).

    potentials: (B, T, N) emission scores; transition_params: (N, N);
    lengths: (B,). Returns (scores (B,), paths (B, T)).
    """
    pt, tt, lt = as_tensor(potentials), as_tensor(transition_params), as_tensor(lengths)

    def fn(emis, trans, lens, include=True):
        B, T, N = emis.shape
        start = emis[:, 0]
        if include:
            start = start + trans[-2, :N][None, :]  # BOS row

        def step(carry, t):
            alpha = carry  # (B, N)
            scores = alpha[:, :, None] + trans[None, :N, :N] + emis[:, t][:, None, :]
            best = jnp.max(scores, axis=1)
            back = jnp.argmax(scores, axis=1)
            # positions beyond each sequence's length keep their alpha
            live = (t < lens)[:, None]
            return jnp.where(live, best, alpha), back

        alpha, backs = jax.lax.scan(step, start, jnp.arange(1, T))
        if include:
            alpha = alpha + trans[:N, -1][None, :]  # EOS column
        last = jnp.argmax(alpha, axis=-1)
        score = jnp.max(alpha, axis=-1)

        def walk(carry, back_t):
            tag, t = carry
            live = (t < lens)
            prev = jnp.take_along_axis(back_t, tag[:, None], axis=1)[:, 0]
            tag = jnp.where(live, prev, tag)
            return (tag, t - 1), tag

        (_, _), path_rev = jax.lax.scan(walk, (last, jnp.full((), T - 1)), backs[::-1])
        paths = jnp.concatenate([path_rev[::-1].T, last[:, None]], axis=1)
        return score, paths

    return eager_call(
        "viterbi_decode", fn, [pt, tt, lt],
        attrs={"include": bool(include_bos_eos_tag)}, differentiable=False,
    )


class ViterbiDecoder:
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths, self.include)
