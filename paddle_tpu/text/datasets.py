"""Text datasets (reference python/paddle/text/datasets/ — Imdb, Imikolov,
UCIHousing, Conll05st, Movielens, WMT14, WMT16).

Zero-egress environment: UCIHousing loads ``housing.data`` from the shared
dataset cache (``paddle.io.data_home()``, override with
``PADDLE_TPU_DATA_HOME``) when present; all other datasets generate a
deterministic synthetic corpus with the reference record shapes/vocab
structure so text pipelines run end-to-end without downloads.
"""
from __future__ import annotations

import os

import numpy as np

from ..io import Dataset, data_home


class Imdb(Dataset):
    """Sentiment classification: (token_ids int64[seq], label {0,1}).
    ``cutoff`` is the word-frequency cutoff (reference semantics): it bounds
    the synthetic vocabulary, not the document length."""

    VOCAB = 5000

    def __init__(self, data_file=None, mode="train", cutoff=150, download=True):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = 512 if mode == "train" else 128
        vocab = max(64, self.VOCAB - int(cutoff))  # higher cutoff -> smaller vocab
        self.docs = []
        self.labels = []
        for _ in range(n):
            length = int(rng.randint(20, 150))
            label = int(rng.randint(0, 2))
            # label-correlated token distribution so models can actually learn
            bias = 0 if label == 0 else vocab // 2
            toks = rng.randint(bias, bias + vocab // 2, length)
            self.docs.append(toks.astype(np.int64))
            self.labels.append(np.int64(label))
        self.word_idx = {f"w{i}": i for i in range(vocab)}

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """LM dataset. data_type='NGRAM': fixed window_size tuples;
    data_type='SEQ': variable-length token sequences (reference semantics)."""

    VOCAB = 2000

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True):
        rng = np.random.RandomState(2 if mode == "train" else 3)
        n = 2048 if mode == "train" else 256
        vocab = max(64, self.VOCAB - int(min_word_freq))
        self.data_type = data_type
        self.window_size = window_size
        if data_type == "SEQ":
            self.data = [
                rng.randint(0, vocab, int(rng.randint(5, 40))).astype(np.int64)
                for _ in range(n)
            ]
        else:
            self.data = list(rng.randint(0, vocab, (n, window_size)).astype(np.int64))
        self.word_idx = {f"w{i}": i for i in range(vocab)}

    def __getitem__(self, idx):
        row = self.data[idx]
        return row if self.data_type == "SEQ" else tuple(row)

    def __len__(self):
        return len(self.data)


class UCIHousing(Dataset):
    """Regression: (13 standardized float features, raw target).
    Reference semantics: features are normalized, the target is not."""

    def __init__(self, data_file=None, mode="train", download=True):
        path = os.path.join(data_home(), "uci_housing", "housing.data")
        if os.path.exists(path):
            raw = np.loadtxt(path).astype(np.float32)
        else:
            rng = np.random.RandomState(4)
            X = rng.rand(506, 13).astype(np.float32)
            w = rng.rand(13, 1).astype(np.float32)
            y = X @ w + 0.1 * rng.randn(506, 1).astype(np.float32)
            raw = np.concatenate([X, y], axis=1)
        feats, target = raw[:, :-1], raw[:, -1:]
        mean, std = feats.mean(axis=0), feats.std(axis=0) + 1e-8
        feats = (feats - mean) / std
        data = np.concatenate([feats, target], axis=1)
        split = int(len(data) * 0.8)
        self.data = data[:split] if mode == "train" else data[split:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class Conll05st(Dataset):
    """SRL: (word_ids, predicate, label_ids) per token."""

    WORD_VOCAB, LABEL_VOCAB = 3000, 60

    def __init__(self, data_file=None, word_dict_file=None, verb_dict_file=None,
                 target_dict_file=None, emb_file=None, mode="train", download=True):
        rng = np.random.RandomState(5 if mode == "train" else 6)
        n = 256 if mode == "train" else 64
        self.samples = []
        for _ in range(n):
            length = int(rng.randint(5, 40))
            words = rng.randint(0, self.WORD_VOCAB, length).astype(np.int64)
            pred = rng.randint(0, self.WORD_VOCAB, length).astype(np.int64)
            labels = rng.randint(0, self.LABEL_VOCAB, length).astype(np.int64)
            self.samples.append((words, pred, labels))

    def get_dict(self):
        return (
            {f"w{i}": i for i in range(self.WORD_VOCAB)},
            {f"v{i}": i for i in range(200)},
            {f"l{i}": i for i in range(self.LABEL_VOCAB)},
        )

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class Movielens(Dataset):
    """Rating prediction: (user feats, movie feats, rating)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1, rand_seed=0, download=True):
        rng = np.random.RandomState(7 if mode == "train" else 8)
        n = 1024 if mode == "train" else 128
        self.rows = [
            (
                np.int64(rng.randint(0, 6040)),    # user id
                np.int64(rng.randint(0, 2)),       # gender
                np.int64(rng.randint(0, 7)),       # age bucket
                np.int64(rng.randint(0, 21)),      # occupation
                np.int64(rng.randint(0, 3952)),    # movie id
                rng.randint(0, 19, 3).astype(np.int64),  # categories
                np.float32(rng.randint(1, 6)),     # rating
            )
            for _ in range(n)
        ]

    def __getitem__(self, idx):
        return self.rows[idx]

    def __len__(self):
        return len(self.rows)


class _WMTBase(Dataset):
    """Synthetic translation pairs: (src_ids, trg_ids, trg_next_ids)."""

    def __init__(self, seed, src_vocab, trg_vocab, mode="train"):
        rng = np.random.RandomState(seed if mode == "train" else seed + 1)
        n = 512 if mode == "train" else 64
        self.src_vocab, self.trg_vocab = src_vocab, trg_vocab
        self.pairs = []
        for _ in range(n):
            ls = int(rng.randint(4, 30))
            lt = int(rng.randint(4, 30))
            src = rng.randint(2, src_vocab, ls).astype(np.int64)
            trg = rng.randint(2, trg_vocab, lt).astype(np.int64)
            trg_next = np.concatenate([trg[1:], [1]]).astype(np.int64)  # 1 = <eos>
            self.pairs.append((src, trg, trg_next))

    def __getitem__(self, idx):
        return self.pairs[idx]

    def __len__(self):
        return len(self.pairs)


class WMT14(_WMTBase):
    def __init__(self, data_file=None, mode="train", dict_size=30000, download=True):
        super().__init__(9, dict_size, dict_size, mode)


class WMT16(_WMTBase):
    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en", download=True):
        super().__init__(11, src_dict_size, trg_dict_size, mode)


__all__ = ["Imdb", "Imikolov", "UCIHousing", "Conll05st", "Movielens", "WMT14", "WMT16"]
