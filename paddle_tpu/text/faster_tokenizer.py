"""FasterTokenizer — text → padded id tensors inside the framework.

Reference: ``paddle/fluid/operators/string/faster_tokenizer_op.cc`` (native
BERT BasicTokenizer + WordPiece op feeding ERNIE/BERT serving graphs) and
its python driver ``test_faster_tokenizer_op.py``. Tokenization is
host-side string work, so it stays NATIVE here too — C++
(``runtime_cpp/tokenizer.cc``) behind ctypes — with a pure-Python fallback
implementing the IDENTICAL algorithm (parity-tested) so the layer works
before the first `make`.

TPU-first output discipline: fixed ``max_seq_len`` padded int64 tensors
(ids + token_type_ids), so downstream encoders compile once per length.
"""
from __future__ import annotations

import ctypes
import os
import tempfile
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["FasterTokenizer"]


def _native_lib():
    from ..core.native import lib

    L = lib()
    if L is None or not hasattr(L, "ptk_create"):
        return None
    L.ptk_create.restype = ctypes.c_void_p
    L.ptk_create.argtypes = [ctypes.c_char_p, ctypes.c_int]
    L.ptk_destroy.argtypes = [ctypes.c_void_p]
    L.ptk_vocab_size.restype = ctypes.c_int64
    L.ptk_vocab_size.argtypes = [ctypes.c_void_p]
    L.ptk_token_id.restype = ctypes.c_int64
    L.ptk_token_id.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    L.ptk_encode.restype = ctypes.c_int64
    L.ptk_encode.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                             ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
    return L


# -- pure-python twin of runtime_cpp/tokenizer.cc ----------------------------

def _is_cjk(cp: int) -> bool:
    return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
            or 0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2B73F
            or 0x2B740 <= cp <= 0x2B81F or 0x2B820 <= cp <= 0x2CEAF
            or 0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F)


def _is_punct(cp: int) -> bool:
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    return (0x2000 <= cp <= 0x206F or 0x3000 <= cp <= 0x303F
            or 0xFF00 <= cp <= 0xFF0F or 0xFF1A <= cp <= 0xFF20
            or 0xFF3B <= cp <= 0xFF40 or 0xFF5B <= cp <= 0xFF65)


def _basic_tokenize(text: str, lower: bool) -> List[str]:
    out = []
    for ch in text:
        cp = ord(ch)
        # same control-char rule as the C++ twin (ASCII controls only — the
        # deliberate simplification both sides share)
        if cp == 0 or cp == 0xFFFD or (
                (cp < 0x20 or cp == 0x7F) and ch not in "\t\n\r"):
            continue
        if ch in " \t\n\r":
            out.append(" ")
            continue
        if lower and "A" <= ch <= "Z":
            ch = ch.lower()
            cp = ord(ch)
        if _is_cjk(cp) or _is_punct(cp):
            out.append(f" {ch} ")
            continue
        out.append(ch)
    return "".join(out).split()


def _wordpiece(word: str, vocab: Dict[str, int], unk: int) -> List[int]:
    if len(word.encode("utf-8")) > 100:
        return [unk]
    pieces: List[int] = []
    start = 0
    b = word
    while start < len(b):
        end = len(b)
        cur = -1
        while end > start:
            sub = b[start:end]
            if start > 0:
                sub = "##" + sub
            if sub in vocab:
                cur = vocab[sub]
                break
            end -= 1
        if cur < 0:
            return [unk]
        pieces.append(cur)
        start = end
    return pieces


class FasterTokenizer(Layer):
    """BERT-style tokenizer layer: list-of-strings → (input_ids,
    token_type_ids) int64 tensors padded to ``max_seq_len``.

    ``vocab`` is a token→id dict or a vocab-file path (one token per line,
    id = line number). Uses the native C++ tokenizer when built; otherwise
    the pure-Python twin (identical output, parity-tested)."""

    def __init__(self, vocab: Union[str, Dict[str, int]], do_lower_case=True):
        super().__init__()
        self.do_lower_case = bool(do_lower_case)
        if isinstance(vocab, str):
            self._vocab_path = vocab
            self.vocab = {}
            with open(vocab) as f:
                for i, line in enumerate(f):
                    # first occurrence wins (matches the C++ loader) — real
                    # released vocabs do contain duplicate lines
                    self.vocab.setdefault(line.rstrip("\r\n"), i)
        else:
            self.vocab = dict(vocab)
            self._vocab_path = None
        for tok in ("[UNK]", "[CLS]", "[SEP]", "[PAD]"):
            if tok not in self.vocab:
                raise ValueError(f"vocab is missing the special token {tok}")
        self._unk = self.vocab["[UNK]"]
        self._cls = self.vocab["[CLS]"]
        self._sep = self.vocab["[SEP]"]
        self._pad = self.vocab["[PAD]"]
        self._native = None
        self._handle = None
        self._tmp_vocab = None
        # the native loader assigns ids by line number, so it can only be
        # used when the vocab ids are exactly 0..N-1 (dense); otherwise the
        # python twin (which honors arbitrary ids) serves
        dense = sorted(self.vocab.values()) == list(range(len(self.vocab)))
        L = _native_lib() if dense else None
        if L is not None:
            path = self._vocab_path
            if path is None:
                fd, path = tempfile.mkstemp(suffix=".vocab")
                with os.fdopen(fd, "w") as f:
                    for tok, _ in sorted(self.vocab.items(), key=lambda kv: kv[1]):
                        f.write(tok + "\n")
                self._tmp_vocab = path  # unlinked in __del__
            h = L.ptk_create(path.encode(), 1 if self.do_lower_case else 0)
            if h:
                self._native, self._handle = L, h
        self.is_native = self._handle is not None

    def __del__(self):
        try:
            if self._handle:
                self._native.ptk_destroy(self._handle)
            if getattr(self, "_tmp_vocab", None):
                os.unlink(self._tmp_vocab)
        except Exception:
            pass

    def _encode_one(self, text: str) -> List[int]:
        # C strings stop at NUL; the python twin matches that semantic so the
        # two backends cannot diverge on embedded NULs
        if "\x00" in text:
            text = text.split("\x00", 1)[0]
        if self._handle:
            cap = max(16, 2 * len(text) + 8)
            buf = (ctypes.c_int64 * cap)()
            n = self._native.ptk_encode(self._handle, text.encode(), buf, cap)
            return list(buf[:n])
        ids: List[int] = []
        for w in _basic_tokenize(text, self.do_lower_case):
            ids.extend(_wordpiece(w, self.vocab, self._unk))
        return ids

    def forward(self, text: Union[str, Sequence[str]],
                text_pair: Optional[Union[str, Sequence[str]]] = None,
                max_seq_len: int = 128, pad_to_max_seq_len: bool = True):
        texts = [text] if isinstance(text, str) else list(text)
        pairs = None
        if text_pair is not None:
            pairs = [text_pair] if isinstance(text_pair, str) else list(text_pair)
            if len(pairs) != len(texts):
                raise ValueError("text and text_pair must have equal lengths")
        rows, segs = [], []
        for i, t in enumerate(texts):
            a = self._encode_one(t)
            b = self._encode_one(pairs[i]) if pairs else []
            # [CLS] a [SEP] (+ b [SEP]); truncate a-then-b to fit
            budget = max_seq_len - 2 - (1 if b else 0)
            if budget < 1:
                raise ValueError(
                    f"max_seq_len={max_seq_len} leaves no room for content "
                    "after the special tokens")
            if b:
                # longest-first truncation (reference truncate_seq_pair)
                while len(a) + len(b) > budget:
                    (a if len(a) >= len(b) else b).pop()
            else:
                a = a[:budget]
            ids = [self._cls] + a + [self._sep]
            seg = [0] * len(ids)
            if b:
                ids += b + [self._sep]
                seg += [1] * (len(b) + 1)
            if pad_to_max_seq_len:
                ids += [self._pad] * (max_seq_len - len(ids))
                seg += [0] * (max_seq_len - len(seg))
            rows.append(ids)
            segs.append(seg)
        if not pad_to_max_seq_len:
            width = max(len(r) for r in rows)
            rows = [r + [self._pad] * (width - len(r)) for r in rows]
            segs = [s + [0] * (width - len(s)) for s in segs]
        return (Tensor(np.asarray(rows, np.int64)),
                Tensor(np.asarray(segs, np.int64)))
