"""Quantization — QAT (fake-quant with STE) and post-training quantization.

Parity: reference ``python/paddle/fluid/contrib/slim/quantization/``
(imperative/qat.py:41 ImperativeQuantAware — swaps Linear/Conv2D for
fake-quant wrappers; post_training_quantization.py:125 — calibration-based
scale search). TPU-native: int8 fake-quant runs INSIDE the jit program
(AQT-style), so XLA folds the quantize-dequantize pair into the surrounding
matmul schedule; the straight-through estimator is a ``jax.custom_vjp``.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.lazy import concrete as _concrete

from ..core.dispatch import as_tensor, eager_call
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer


# -- fake quant primitives ---------------------------------------------------

@jax.custom_vjp
def _fake_quant_ste(x, scale):
    """Quantize-dequantize to int8 grid; gradient passes straight through."""
    q = jnp.clip(jnp.round(x / scale * 127.0), -127.0, 127.0)
    return q * scale / 127.0


def _fq_fwd(x, scale):
    return _fake_quant_ste(x, scale), (x, scale)


def _fq_bwd(res, g):
    x, scale = res
    # STE inside the clip range, zero outside (reference fake_quantize op grad)
    mask = (jnp.abs(x) <= scale).astype(g.dtype)
    return g * mask, jnp.zeros_like(scale)


_fake_quant_ste.defvjp(_fq_fwd, _fq_bwd)


def fake_quantize_dequantize_abs_max(x, bit_length=8, name=None):
    """One-shot abs-max fake quant (reference fake_quantize_dequantize ops)."""
    t = as_tensor(x)

    def fn(a):
        scale = jnp.maximum(jnp.max(jnp.abs(a)), 1e-8)
        return _fake_quant_ste(a, scale)

    return eager_call("fake_quant_abs_max", fn, [t])


def quantize_to_int8(x):
    """Real int8 quantization: returns (int8 values, fp scale)."""
    t = as_tensor(x)
    arr = t._data
    scale = float(jnp.maximum(jnp.max(jnp.abs(arr)), 1e-8))
    q = jnp.clip(jnp.round(arr / scale * 127.0), -127, 127).astype(jnp.int8)
    return Tensor(q, stop_gradient=True), scale


# -- QAT layer wrappers ------------------------------------------------------

class FakeQuantAbsMax(Layer):
    """Weight quantizer: per-tensor abs-max, recomputed each step."""

    def forward(self, x):
        def fn(a):
            scale = jnp.maximum(jnp.max(jnp.abs(a)), 1e-8)
            return _fake_quant_ste(a, scale)

        return eager_call("fq_weight_abs_max", fn, [as_tensor(x)])


class FakeQuantMovingAverageAbsMax(Layer):
    """Activation quantizer with EMA scale (reference
    MovingAverageAbsMaxScale op; rate 0.9 default)."""

    def __init__(self, rate=0.9):
        super().__init__()
        self._rate = rate
        self.register_buffer("scale", Tensor(jnp.ones(()), stop_gradient=True))
        self._initialized = False

    def forward(self, x):
        t = as_tensor(x)
        if self.training and isinstance(t._data, jax.core.Tracer):
            # compiled train path: the activation is a tracer, so the EMA
            # buffer cannot be updated host-side. Quantize with an in-graph
            # per-batch abs-max scale instead; the persistent EMA state only
            # advances on eager steps.
            def fn_traced(a):
                scale = jnp.maximum(jnp.max(jnp.abs(a)), 1e-8).astype(a.dtype)
                return _fake_quant_ste(a, jax.lax.stop_gradient(scale))

            return eager_call("fq_act_batch_absmax", fn_traced, [t])
        if self.training:

            cur = float(jnp.max(jnp.abs(_concrete(t._data))))
            prev = float(np.asarray(self.scale._data))
            new = cur if not self._initialized else self._rate * prev + (1 - self._rate) * cur
            self._initialized = True
            self.scale._set_data(jnp.asarray(new))
        s = self.scale._data

        def fn(a, s):
            return _fake_quant_ste(a, jnp.maximum(s, 1e-8).astype(a.dtype))

        return eager_call("fq_act_ema", fn, [t, Tensor(s, stop_gradient=True)])


class QuantedLayer(Layer):
    """Wraps a Linear/Conv2D: fake-quant weight + input, then run the
    original layer's math with the quantized values (reference
    imperative/qat.py QuantizedLinear/QuantizedConv2D)."""

    def __init__(self, inner, weight_quantizer=None, act_quantizer=None):
        super().__init__()
        # plain attribute assignment auto-registers sublayers (Layer.__setattr__)
        self.inner = inner
        self.weight_quantizer = weight_quantizer or FakeQuantAbsMax()
        self.act_quantizer = act_quantizer or FakeQuantMovingAverageAbsMax()

    def forward(self, x):
        xq = self.act_quantizer(x)
        w = self.inner.weight
        wq = self.weight_quantizer(w)
        saved = w._data
        try:
            w._data = wq._data if isinstance(wq, Tensor) else wq
            return self.inner(xq)
        finally:
            w._data = saved


class ImperativeQuantAware:
    """QAT driver (reference imperative/qat.py:41)."""

    QUANTIZABLE = ("Linear", "Conv2D", "Conv1D")

    def __init__(self, quantizable_layer_type=None, weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max", weight_bits=8,
                 activation_bits=8, moving_rate=0.9, **kw):
        self.types = tuple(quantizable_layer_type or self.QUANTIZABLE)
        self.moving_rate = moving_rate

    def quantize(self, model: Layer):
        """Swap quantizable sublayers for QuantedLayer wrappers, in place.
        MUST go through setattr: Layer.__setattr__ mirrors sublayers into the
        instance __dict__, so writing only _sub_layers would leave forward()
        (attribute access) on the old float layer."""
        for name, child in list(model._sub_layers.items()):
            if type(child).__name__ in self.types and hasattr(child, "weight"):
                setattr(model, name, QuantedLayer(
                    child,
                    FakeQuantAbsMax(),
                    FakeQuantMovingAverageAbsMax(self.moving_rate),
                ))
            else:
                self.quantize(child)
        return model


class PostTrainingQuantization:
    """PTQ (reference post_training_quantization.py:125): calibrate
    activation scales over sample batches, quantize weights to int8+scale."""

    def __init__(self, model: Layer, data_loader=None, algo="abs_max",
                 quantizable_layer_type=None, batch_nums=10, **kw):
        self.model = model
        self.data_loader = data_loader
        self.algo = algo
        self.types = tuple(quantizable_layer_type or ImperativeQuantAware.QUANTIZABLE)
        self.batch_nums = batch_nums
        self.act_scales = {}
        self.in_scales = {}
        self.weight_scales = {}

    def _collect(self, layer_name):
        def hook(layer, inputs, output):
            arr = _concrete(output._data if isinstance(output, Tensor) else output)
            cur = float(jnp.max(jnp.abs(arr)))
            if self.algo == "avg":
                prev = self.act_scales.get(layer_name)
                self.act_scales[layer_name] = cur if prev is None else 0.5 * (prev + cur)
            else:  # abs_max
                self.act_scales[layer_name] = max(self.act_scales.get(layer_name, 0.0), cur)
            # INPUT scale too: the int8 serving path quantizes activations
            # entering the layer (x_int8 @ w_int8 -> int32 on the MXU)
            x0 = inputs[0] if isinstance(inputs, (list, tuple)) else inputs
            xin = _concrete(x0._data if isinstance(x0, Tensor) else x0)
            cin = float(jnp.max(jnp.abs(xin)))
            self.in_scales[layer_name] = max(self.in_scales.get(layer_name, 0.0), cin)
        return hook

    def quantize(self):
        """Run calibration then fold int8 weights; returns the model with
        per-layer scales in .act_scales/.weight_scales."""
        handles = []
        for name, sub in self.model.named_sublayers():
            if type(sub).__name__ in self.types and hasattr(sub, "weight"):
                handles.append(sub.register_forward_post_hook(self._collect(name)))
        if self.data_loader is not None:
            self.model.eval()
            for i, batch in enumerate(self.data_loader):
                if i >= self.batch_nums:
                    break
                x = batch[0] if isinstance(batch, (list, tuple)) else batch
                self.model(x)
        for h in handles:
            h.remove()
        # weight quantization: int8 + scale, dequantized in place (the AOT
        # export then folds the q/dq pair; scales kept for int8 serving)
        for name, sub in self.model.named_sublayers():
            if type(sub).__name__ in self.types and hasattr(sub, "weight"):
                q, scale = quantize_to_int8(sub.weight)
                self.weight_scales[name] = scale
                sub.weight._set_data(
                    (q._data.astype(jnp.float32) * scale / 127.0).astype(sub.weight._data.dtype)
                )
        return self.model


# -- int8 serving path -------------------------------------------------------

class Int8Linear(Layer):
    """Serving-time int8 linear: x and W quantize to int8, the matmul
    accumulates in int32 on the MXU, one fp rescale at the end. The role of
    the reference's int8 pass pipeline feeding AnalysisPredictor
    (``contrib/slim/quantization/quantization_pass.py:269`` →
    quantized conv/mul kernels); here the int8 weights export as int8
    StableHLO constants, so the AOT artifact is int8 end to end."""

    def __init__(self, weight_q, bias, in_scale: float, w_scale: float):
        super().__init__()
        self.register_buffer("weight_q", Tensor(weight_q, stop_gradient=True))
        self.bias = bias
        self._sx = float(in_scale) / 127.0
        self._sw = float(w_scale) / 127.0

    def forward(self, x):
        xt = as_tensor(x)
        args = [xt, self.weight_q] + ([self.bias] if self.bias is not None else [])

        def fn(a, wq, *rest, sx=self._sx, sw=self._sw):
            aq = jnp.clip(jnp.round(a / sx), -127, 127).astype(jnp.int8)
            acc = jax.lax.dot_general(
                aq, wq, (((a.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            y = acc.astype(jnp.float32) * (sx * sw)
            if rest:
                y = y + rest[0]
            return y.astype(a.dtype)

        return eager_call("int8_linear", fn, args, differentiable=False)


class Int8Conv2D(Layer):
    """Serving-time int8 conv2d (NCHW): int8 feature/filter, int32 MXU
    accumulation, single fp rescale."""

    def __init__(self, weight_q, bias, in_scale: float, w_scale: float,
                 stride, padding, dilation, groups, data_format="NCHW"):
        super().__init__()
        self.register_buffer("weight_q", Tensor(weight_q, stop_gradient=True))
        self.bias = bias
        self._sx = float(in_scale) / 127.0
        self._sw = float(w_scale) / 127.0
        def _pair(v):
            return tuple(v) if isinstance(v, (list, tuple)) else (int(v), int(v))

        self._cfg = (_pair(stride), padding, _pair(dilation), int(groups),
                     str(data_format))

    def forward(self, x):
        xt = as_tensor(x)
        args = [xt, self.weight_q] + ([self.bias] if self.bias is not None else [])
        stride, padding, dilation, groups, data_format = self._cfg
        # weights stay OIHW (framework convention) for either activation layout
        dnums = (data_format, "OIHW", data_format)

        def fn(a, wq, *rest, sx=self._sx, sw=self._sw):
            aq = jnp.clip(jnp.round(a / sx), -127, 127).astype(jnp.int8)
            if isinstance(padding, str):
                pad = padding.upper()  # 'SAME'/'VALID' pass through to XLA
            elif isinstance(padding, tuple):
                pad = [(int(p), int(p)) for p in padding]
            else:
                pad = padding
            acc = jax.lax.conv_general_dilated(
                aq, wq, window_strides=stride, padding=pad,
                rhs_dilation=dilation, feature_group_count=groups,
                dimension_numbers=dnums,
                preferred_element_type=jnp.int32,
            )
            y = acc.astype(jnp.float32) * (sx * sw)
            if rest:
                bshape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
                y = y + rest[0].reshape(bshape)
            return y.astype(a.dtype)

        return eager_call("int8_conv2d", fn, args, differentiable=False)


def convert_to_int8_inference(model: Layer, ptq: "PostTrainingQuantization"):
    """Swap calibrated Linear/Conv2D sublayers for int8 serving layers, in
    place. ``paddle.jit.save`` of the result emits an int8-weight StableHLO
    artifact that ``paddle_tpu.inference.create_predictor`` runs as-is —
    the slim → AnalysisPredictor integration of the reference."""
    from ..core.lazy import concrete as _conc

    def swap(parent, prefix=""):
        # swaps MUST go through setattr — Layer.__setattr__ mirrors sublayers
        # into the instance __dict__, and forward() resolves attributes there
        for name, child in list(parent._sub_layers.items()):
            full = f"{prefix}.{name}" if prefix else name
            tname = type(child).__name__
            scale_key = _match_scale(ptq, full)
            if tname == "Linear" and scale_key is not None:
                w = np.asarray(_conc(child.weight._data), np.float32)
                s_w = float(np.maximum(np.abs(w).max(), 1e-8))
                wq = np.clip(np.round(w / s_w * 127.0), -127, 127).astype(np.int8)
                setattr(parent, name, Int8Linear(
                    jnp.asarray(wq), child.bias, ptq.in_scales[scale_key], s_w
                ))
            elif tname == "Conv2D" and scale_key is not None:
                w = np.asarray(_conc(child.weight._data), np.float32)
                s_w = float(np.maximum(np.abs(w).max(), 1e-8))
                wq = np.clip(np.round(w / s_w * 127.0), -127, 127).astype(np.int8)
                pad = child._padding
                if isinstance(pad, str):
                    pad_t = pad
                elif isinstance(pad, (list, tuple)):
                    pad_t = tuple(pad)
                else:
                    pad_t = (int(pad),) * 2
                setattr(parent, name, Int8Conv2D(
                    jnp.asarray(wq), child.bias, ptq.in_scales[scale_key], s_w,
                    child._stride, pad_t, child._dilation, child._groups,
                    getattr(child, "_data_format", "NCHW"),
                ))
            else:
                swap(child, full)
    swap(model)
    return model


def _match_scale(ptq, full_name):
    if full_name in ptq.in_scales:
        return full_name
    # named_sublayers prefixes may differ by a leading module name; only a
    # dot-boundary suffix is unambiguous ('fc1' must never bind 'myfc1')
    hits = [
        k for k in ptq.in_scales
        if k.endswith("." + full_name) or full_name.endswith("." + k)
    ]
    if len(hits) == 1:
        return hits[0]
    if len(hits) > 1:
        import warnings

        warnings.warn(
            f"ambiguous calibration scales {sorted(hits)} for layer "
            f"'{full_name}'; leaving it unquantized"
        )
    return None


__all__ = [
    "fake_quantize_dequantize_abs_max", "quantize_to_int8",
    "FakeQuantAbsMax", "FakeQuantMovingAverageAbsMax", "QuantedLayer",
    "ImperativeQuantAware", "PostTrainingQuantization",
    "Int8Linear", "Int8Conv2D", "convert_to_int8_inference",
]
