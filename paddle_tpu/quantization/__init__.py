"""Quantization — QAT (fake-quant with STE) and post-training quantization.

Parity: reference ``python/paddle/fluid/contrib/slim/quantization/``
(imperative/qat.py:41 ImperativeQuantAware — swaps Linear/Conv2D for
fake-quant wrappers; post_training_quantization.py:125 — calibration-based
scale search). TPU-native: int8 fake-quant runs INSIDE the jit program
(AQT-style), so XLA folds the quantize-dequantize pair into the surrounding
matmul schedule; the straight-through estimator is a ``jax.custom_vjp``.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.lazy import concrete as _concrete

from ..core.dispatch import as_tensor, eager_call
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer


# -- fake quant primitives ---------------------------------------------------

@jax.custom_vjp
def _fake_quant_ste(x, scale):
    """Quantize-dequantize to int8 grid; gradient passes straight through."""
    q = jnp.clip(jnp.round(x / scale * 127.0), -127.0, 127.0)
    return q * scale / 127.0


def _fq_fwd(x, scale):
    return _fake_quant_ste(x, scale), (x, scale)


def _fq_bwd(res, g):
    x, scale = res
    # STE inside the clip range, zero outside (reference fake_quantize op grad)
    mask = (jnp.abs(x) <= scale).astype(g.dtype)
    return g * mask, jnp.zeros_like(scale)


_fake_quant_ste.defvjp(_fq_fwd, _fq_bwd)


def fake_quantize_dequantize_abs_max(x, bit_length=8, name=None):
    """One-shot abs-max fake quant (reference fake_quantize_dequantize ops)."""
    t = as_tensor(x)

    def fn(a):
        scale = jnp.maximum(jnp.max(jnp.abs(a)), 1e-8)
        return _fake_quant_ste(a, scale)

    return eager_call("fake_quant_abs_max", fn, [t])


def quantize_to_int8(x):
    """Real int8 quantization: returns (int8 values, fp scale)."""
    t = as_tensor(x)
    arr = t._data
    scale = float(jnp.maximum(jnp.max(jnp.abs(arr)), 1e-8))
    q = jnp.clip(jnp.round(arr / scale * 127.0), -127, 127).astype(jnp.int8)
    return Tensor(q, stop_gradient=True), scale


# -- QAT layer wrappers ------------------------------------------------------

class FakeQuantAbsMax(Layer):
    """Weight quantizer: per-tensor abs-max, recomputed each step."""

    def forward(self, x):
        def fn(a):
            scale = jnp.maximum(jnp.max(jnp.abs(a)), 1e-8)
            return _fake_quant_ste(a, scale)

        return eager_call("fq_weight_abs_max", fn, [as_tensor(x)])


class FakeQuantMovingAverageAbsMax(Layer):
    """Activation quantizer with EMA scale (reference
    MovingAverageAbsMaxScale op; rate 0.9 default)."""

    def __init__(self, rate=0.9):
        super().__init__()
        self._rate = rate
        self.register_buffer("scale", Tensor(jnp.ones(()), stop_gradient=True))
        self._initialized = False

    def forward(self, x):
        t = as_tensor(x)
        if self.training and isinstance(t._data, jax.core.Tracer):
            # compiled train path: the activation is a tracer, so the EMA
            # buffer cannot be updated host-side. Quantize with an in-graph
            # per-batch abs-max scale instead; the persistent EMA state only
            # advances on eager steps.
            def fn_traced(a):
                scale = jnp.maximum(jnp.max(jnp.abs(a)), 1e-8).astype(a.dtype)
                return _fake_quant_ste(a, jax.lax.stop_gradient(scale))

            return eager_call("fq_act_batch_absmax", fn_traced, [t])
        if self.training:

            cur = float(jnp.max(jnp.abs(_concrete(t._data))))
            prev = float(np.asarray(self.scale._data))
            new = cur if not self._initialized else self._rate * prev + (1 - self._rate) * cur
            self._initialized = True
            self.scale._set_data(jnp.asarray(new))
        s = self.scale._data

        def fn(a, s):
            return _fake_quant_ste(a, jnp.maximum(s, 1e-8).astype(a.dtype))

        return eager_call("fq_act_ema", fn, [t, Tensor(s, stop_gradient=True)])


class QuantedLayer(Layer):
    """Wraps a Linear/Conv2D: fake-quant weight + input, then run the
    original layer's math with the quantized values (reference
    imperative/qat.py QuantizedLinear/QuantizedConv2D)."""

    def __init__(self, inner, weight_quantizer=None, act_quantizer=None):
        super().__init__()
        # plain attribute assignment auto-registers sublayers (Layer.__setattr__)
        self.inner = inner
        self.weight_quantizer = weight_quantizer or FakeQuantAbsMax()
        self.act_quantizer = act_quantizer or FakeQuantMovingAverageAbsMax()

    def forward(self, x):
        xq = self.act_quantizer(x)
        w = self.inner.weight
        wq = self.weight_quantizer(w)
        saved = w._data
        try:
            w._data = wq._data if isinstance(wq, Tensor) else wq
            return self.inner(xq)
        finally:
            w._data = saved


class ImperativeQuantAware:
    """QAT driver (reference imperative/qat.py:41)."""

    QUANTIZABLE = ("Linear", "Conv2D", "Conv1D")

    def __init__(self, quantizable_layer_type=None, weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max", weight_bits=8,
                 activation_bits=8, moving_rate=0.9, **kw):
        self.types = tuple(quantizable_layer_type or self.QUANTIZABLE)
        self.moving_rate = moving_rate

    def quantize(self, model: Layer):
        """Swap quantizable sublayers for QuantedLayer wrappers, in place."""
        for name, child in list(model._sub_layers.items()):
            if type(child).__name__ in self.types and hasattr(child, "weight"):
                model._sub_layers[name] = QuantedLayer(
                    child,
                    FakeQuantAbsMax(),
                    FakeQuantMovingAverageAbsMax(self.moving_rate),
                )
            else:
                self.quantize(child)
        return model


class PostTrainingQuantization:
    """PTQ (reference post_training_quantization.py:125): calibrate
    activation scales over sample batches, quantize weights to int8+scale."""

    def __init__(self, model: Layer, data_loader=None, algo="abs_max",
                 quantizable_layer_type=None, batch_nums=10, **kw):
        self.model = model
        self.data_loader = data_loader
        self.algo = algo
        self.types = tuple(quantizable_layer_type or ImperativeQuantAware.QUANTIZABLE)
        self.batch_nums = batch_nums
        self.act_scales = {}
        self.weight_scales = {}

    def _collect(self, layer_name):
        def hook(layer, inputs, output):

            arr = _concrete(output._data if isinstance(output, Tensor) else output)
            cur = float(jnp.max(jnp.abs(arr)))
            if self.algo == "avg":
                prev = self.act_scales.get(layer_name)
                self.act_scales[layer_name] = cur if prev is None else 0.5 * (prev + cur)
            else:  # abs_max
                self.act_scales[layer_name] = max(self.act_scales.get(layer_name, 0.0), cur)
        return hook

    def quantize(self):
        """Run calibration then fold int8 weights; returns the model with
        per-layer scales in .act_scales/.weight_scales."""
        handles = []
        for name, sub in self.model.named_sublayers():
            if type(sub).__name__ in self.types and hasattr(sub, "weight"):
                handles.append(sub.register_forward_post_hook(self._collect(name)))
        if self.data_loader is not None:
            self.model.eval()
            for i, batch in enumerate(self.data_loader):
                if i >= self.batch_nums:
                    break
                x = batch[0] if isinstance(batch, (list, tuple)) else batch
                self.model(x)
        for h in handles:
            h.remove()
        # weight quantization: int8 + scale, dequantized in place (the AOT
        # export then folds the q/dq pair; scales kept for int8 serving)
        for name, sub in self.model.named_sublayers():
            if type(sub).__name__ in self.types and hasattr(sub, "weight"):
                q, scale = quantize_to_int8(sub.weight)
                self.weight_scales[name] = scale
                sub.weight._set_data(
                    (q._data.astype(jnp.float32) * scale / 127.0).astype(sub.weight._data.dtype)
                )
        return self.model


__all__ = [
    "fake_quantize_dequantize_abs_max", "quantize_to_int8",
    "FakeQuantAbsMax", "FakeQuantMovingAverageAbsMax", "QuantedLayer",
    "ImperativeQuantAware", "PostTrainingQuantization",
]
