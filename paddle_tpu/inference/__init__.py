"""paddle.inference — the deployment path.

Parity: reference ``paddle/fluid/inference/api/analysis_predictor.h:87``
(AnalysisPredictor), ``paddle_inference_api.h`` (Config/Predictor/Tensor
handles), ``paddle_pass_builder.cc`` (pass strategies).

TPU-native design: the "analysis + IR pass pipeline" of the reference is the
XLA compiler here — the saved artifact (``jit.save``: StableHLO bytes +
params) is AOT-compiled by PJRT at load, so there is no pass zoo to
configure. What remains is the deployment API surface: Config describing the
artifact + device, a Predictor with named input/output handles (zero-copy
into device buffers), ``clone()`` sharing the compiled executable between
threads (the reference clones predictors per thread over one program,
analysis_predictor.cc AnalysisPredictor::Clone), and batched Run.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

import numpy as np


class Config:
    """AnalysisConfig equivalent (reference paddle_analysis_config.h).

    GPU/TRT/MKLDNN toggles are accepted for API compatibility and recorded;
    on TPU the compiler covers what the reference's IR passes did, so they
    do not change execution.
    """

    def __init__(self, model_dir_or_file: Optional[str] = None, params_file: Optional[str] = None):
        if model_dir_or_file is not None and model_dir_or_file.endswith(".pdmodel"):
            self._prefix = model_dir_or_file[: -len(".pdmodel")]
        else:
            self._prefix = model_dir_or_file
        self._params_file = params_file
        self._device = "tpu"
        self._device_id = 0
        self._memory_optim = True
        self._ir_optim = True
        self._glog_info = False
        self._cpu_math_threads = 1

    # -- model location ---------------------------------------------------
    def set_model(self, model_dir_or_file, params_file=None):
        if model_dir_or_file.endswith(".pdmodel"):
            model_dir_or_file = model_dir_or_file[: -len(".pdmodel")]
        self._prefix = model_dir_or_file
        self._params_file = params_file

    def model_dir(self):
        return self._prefix

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def params_file(self):
        return self._params_file or ((self._prefix or "") + ".pdiparams")

    # -- device -----------------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        # GPU request maps to the accelerator backend (TPU here)
        self._device = "tpu"
        self._device_id = device_id

    def disable_gpu(self):
        self._device = "cpu"

    def enable_tpu(self, device_id=0):
        self._device = "tpu"
        self._device_id = device_id

    def use_gpu(self):
        return self._device == "tpu"

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_math_threads = int(n)

    # -- optimization toggles (XLA subsumes these; recorded for parity) ----
    def switch_ir_optim(self, flag=True):
        self._ir_optim = bool(flag)

    def enable_memory_optim(self, flag=True):
        self._memory_optim = bool(flag)

    def switch_use_feed_fetch_ops(self, flag=False):
        pass

    def switch_specify_input_names(self, flag=True):
        pass

    def enable_tensorrt_engine(self, *a, **k):
        pass  # TRT is CUDA-only; XLA AOT covers the role

    def enable_mkldnn(self):
        pass

    def disable_glog_info(self):
        self._glog_info = False

    def summary(self):
        return (
            f"Config(model={self.prog_file()}, params={self.params_file()}, "
            f"device={self._device}:{self._device_id})"
        )


class PredictorTensor:
    """Named zero-copy I/O handle (reference paddle_tensor.h ZeroCopyTensor)."""

    def __init__(self, name: str, owner: "Predictor", is_input: bool, index: int):
        self.name = name
        self._owner = owner
        self._is_input = is_input
        self._index = index

    def reshape(self, shape):
        # shapes are fixed (or symbolic) in the AOT artifact; accepted for
        # API parity — actual shape comes from copy_from_cpu
        self._shape_hint = tuple(shape)

    def copy_from_cpu(self, data: np.ndarray):
        if not self._is_input:
            raise RuntimeError(f"'{self.name}' is an output handle")
        with self._owner._lock:
            self._owner._inputs[self._index] = np.ascontiguousarray(data)

    def share_external_data(self, data):
        # zero-copy: a device-resident (jax) array is used as-is — no
        # host staging (reference ZeroCopyTensor::ShareExternalData)
        if not self._is_input:
            raise RuntimeError(f"'{self.name}' is an output handle")
        if hasattr(data, "devices") or hasattr(data, "_data"):
            with self._owner._lock:
                self._owner._inputs[self._index] = getattr(data, "_data", data)
        else:
            self.copy_from_cpu(np.asarray(data))

    def copy_to_cpu(self) -> np.ndarray:
        if self._is_input:
            raise RuntimeError(f"'{self.name}' is an input handle")
        outs = self._owner._outputs
        if outs is None:
            raise RuntimeError("run() has not been called")
        return np.asarray(outs[self._index])

    def shape(self):
        if self._is_input:
            a = self._owner._inputs[self._index]
            return list(a.shape) if a is not None else list(self._owner._input_specs[self._index][0])
        outs = self._owner._outputs
        return list(outs[self._index].shape) if outs is not None else []

    def type(self):
        if self._is_input:
            return str(self._owner._input_specs[self._index][1])
        outs = self._owner._outputs
        return str(outs[self._index].dtype) if outs is not None else "float32"


class Predictor:
    """AnalysisPredictor equivalent: AOT module + named handles + clone.

    The compiled executable (PJRT) is shared by reference across clones; each
    clone has its own input/output slots, so per-thread use is race-free —
    the same contract as AnalysisPredictor::Clone (analysis_predictor.cc).

    A SINGLE predictor instance shared by concurrent callers is also safe
    for the list API (``run(inputs)`` stages, executes and returns under one
    ``_lock`` hold, serializing callers); the named-handle protocol
    (``copy_from_cpu`` → ``run()`` → ``copy_to_cpu``) spans multiple calls,
    so interleaved threads can still overwrite each other's slots — use
    ``clone()`` per thread (or the list API) for concurrency.
    """

    def __init__(self, config: Config, _shared=None):
        self._config = config
        if _shared is not None:
            (self._exported, self._call, self._input_specs, self._input_names,
             self._output_names, self._n_outputs) = _shared
        else:
            self._load(config)
        self._lock = threading.Lock()
        self._inputs: List[Optional[np.ndarray]] = [None] * len(self._input_names)  # guarded_by: _lock
        self._outputs = None  # guarded_by: _lock

    def _load(self, config: Config):
        import jax

        from ..framework.io import load as fload

        prefix = config._prefix
        if prefix is None or not os.path.exists(prefix + ".pdmodel"):
            raise ValueError(f"model file not found: {prefix}.pdmodel")
        with open(prefix + ".pdmodel", "rb") as f:
            from ..core.compat import jax_export
            exported = jax_export().deserialize(f.read())
        meta = fload(config.params_file()) if os.path.exists(config.params_file()) else {}
        specs = meta.get("specs") or []
        self._exported = exported
        self._input_specs = [(tuple(s[0]), s[1]) for s in specs] or [
            (tuple(t.shape), str(t.dtype)) for t in exported.in_avals
        ]
        self._input_names = [
            (s[2] if len(s) > 2 and s[2] else f"input_{i}") for i, s in enumerate(specs)
        ] or [f"input_{i}" for i in range(len(self._input_specs))]
        out_avals = exported.out_avals
        self._n_outputs = len(out_avals) if isinstance(out_avals, (list, tuple)) else 1
        self._output_names = [f"output_{i}" for i in range(self._n_outputs)]

        # exported.call re-traces per invocation — wrap in jit so the PJRT
        # executable is compiled once and cached (this is the predictor's
        # whole job; without it every run() recompiles)
        if config._device == "cpu":
            cpu = jax.devices("cpu")[0]
            self._call = jax.jit(exported.call, device=cpu)
        else:
            self._call = jax.jit(exported.call)

    # -- handle API --------------------------------------------------------
    def get_input_names(self):
        return list(self._input_names)

    def get_output_names(self):
        return list(self._output_names)

    def get_input_handle(self, name):
        return PredictorTensor(name, self, True, self._input_names.index(name))

    def get_input_tensor(self, name):
        return self.get_input_handle(name)

    def get_output_handle(self, name):
        return PredictorTensor(name, self, False, self._output_names.index(name))

    def get_output_tensor(self, name):
        return self.get_output_handle(name)

    # -- execution ---------------------------------------------------------
    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """Run the AOT program. With ``inputs``, returns outputs directly
        (list API, reference predictor.run(inputs)); otherwise uses the
        copy_from_cpu'd handle slots."""
        with self._lock:
            if inputs is not None:
                if len(inputs) != len(self._input_names):
                    raise ValueError(
                        f"predictor expects {len(self._input_names)} inputs "
                        f"{self._input_names}, got {len(inputs)}"
                    )
                for i, a in enumerate(inputs):
                    self._inputs[i] = np.ascontiguousarray(np.asarray(a))
            missing = [n for n, a in zip(self._input_names, self._inputs) if a is None]
            if missing:
                raise RuntimeError(f"inputs not set: {missing}")
            outs = self._call(*self._inputs)
            if not isinstance(outs, (list, tuple)):
                outs = (outs,)
            # keep device-resident; copy_to_cpu does the D2H transfer
            self._outputs = list(outs)
            if inputs is not None:
                return [np.asarray(o) for o in self._outputs]
        return True

    def clone(self):
        shared = (self._exported, self._call, self._input_specs,
                  self._input_names, self._output_names, self._n_outputs)
        return Predictor(self._config, _shared=shared)

    def clear_intermediate_tensor(self):
        with self._lock:
            self._outputs = None

    def try_shrink_memory(self):
        pass


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


def create_serving_engine(model, **kw):
    """Autoregressive serving front door (continuous batching + paged KV
    cache): a thin re-export of :class:`paddle_tpu.serving.Engine`, imported
    lazily so the deployment namespace stays cheap for Predictor-only use."""
    from ..serving import Engine

    return Engine(model, **kw)


# Legacy aliases (reference paddle.inference exports)
AnalysisConfig = Config
create_paddle_predictor = create_predictor


def get_version():
    from .. import __version__

    return __version__
