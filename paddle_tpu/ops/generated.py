"""Yaml-driven op generation.

The TPU answer to the reference's operator codegen pipeline
(``python/paddle/utils/code_gen/api.yaml`` + ``api_gen.py`` emitting C++
kernels and Python wrappers; ~913 op registrations): each ``ops.yaml`` entry
compiles its ``expr`` into a jnp builder and wraps it with
``core.dispatch.eager_call``, so every generated op carries autograd, AMP
casting, per-op jit caching and the nan/inf debug scan — the services the
reference's OperatorBase/PreparedOp machinery provides per kernel.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
import yaml

from ..core.dispatch import as_tensor, eager_call
from ..core.tensor import Tensor

_SPEC_PATH = os.path.join(os.path.dirname(__file__), "ops.yaml")
_ENV = {"jax": jax, "jnp": jnp, "lax": lax, "np": np, "__builtins__": {
    "len": len, "range": range, "tuple": tuple, "list": list, "sum": sum,
    "int": int, "float": float, "bool": bool, "min": min, "max": max,
    "hasattr": hasattr, "isinstance": isinstance, "zip": zip,
    "enumerate": enumerate,
}}


def load_specs() -> List[Dict[str, Any]]:
    with open(_SPEC_PATH) as f:
        data = yaml.safe_load(f)
    specs = []
    for section, entries in (data or {}).items():
        for e in entries or []:
            e = dict(e)
            e["section"] = section
            specs.append(e)
    return specs


SPECS: Dict[str, Dict[str, Any]] = {e["name"]: e for e in load_specs()}


def _compile_impl(spec):
    args = spec.get("args", ["x"])
    attrs = spec.get("attrs") or {}
    sig_attrs = ", ".join(f"{k}={v!r}" for k, v in attrs.items())
    if spec.get("variadic"):
        sig = "*xs" + (", " + sig_attrs if sig_attrs else "")
    else:
        sig = ", ".join(args + ([sig_attrs] if sig_attrs else []))
    return eval(f"lambda {sig}: ({spec['expr']})", dict(_ENV))


def _make_op(spec):
    name = spec["name"]
    arg_names = spec.get("args", ["x"])
    attr_names = list((spec.get("attrs") or {}).keys())
    variadic = bool(spec.get("variadic"))
    grad = spec.get("grad", True)
    nondiff = tuple(spec.get("nondiff", ()))
    impl = _compile_impl(spec)

    def op(*inputs, **kwargs):
        kwargs.pop("name", None)  # paddle API convention
        if variadic:
            if len(inputs) == 1 and isinstance(inputs[0], (list, tuple)):
                inputs = tuple(inputs[0])
            tensors = [as_tensor(t) for t in inputs]
        else:
            tensors = [as_tensor(t) for t in inputs[: len(arg_names)]]
            for aname, val in zip(attr_names, inputs[len(arg_names):]):
                kwargs.setdefault(aname, val)
        call_attrs = {k: kwargs[k] for k in attr_names if k in kwargs}
        unknown = set(kwargs) - set(attr_names)
        if unknown:
            raise TypeError(f"{name}() got unexpected arguments {sorted(unknown)}")
        return eager_call(
            name, impl, tensors, attrs=call_attrs,
            differentiable=grad, nondiff_outputs=nondiff,
        )

    op.__name__ = name
    op.__qualname__ = name
    op.__doc__ = (
        f"Generated op `{name}` (ops.yaml:{spec['section']}). "
        f"Reference parity: yaml-codegen op surface (api.yaml / api_gen.py)."
    )
    op._op_spec = spec
    return op


def _build_all():
    ops = {}
    aliases = {}
    for name, spec in SPECS.items():
        if spec.get("alias_of"):
            aliases[name] = spec["alias_of"]
            continue
        ops[name] = _make_op(spec)
    # resolve aliases: generated first, then the hand-written op modules
    from . import creation, linalg, manipulation, math

    hand = {}
    for mod in (math, manipulation, creation, linalg):
        hand.update({k: v for k, v in vars(mod).items() if callable(v) and not k.startswith("_")})
    for name, target in aliases.items():
        fn = ops.get(target) or hand.get(target)
        if fn is None:
            raise KeyError(f"ops.yaml alias {name} -> unknown op {target}")
        ops[name] = fn
    return ops


GENERATED = _build_all()
globals().update(GENERATED)
__all__ = sorted(GENERATED)


def attach_tensor_methods():
    for name, spec in SPECS.items():
        if not spec.get("method", True) or name not in GENERATED:
            continue
        if not hasattr(Tensor, name):
            setattr(Tensor, name, GENERATED[name])


attach_tensor_methods()
