"""Control-flow ops.

Parity: reference ``paddle/fluid/operators/controlflow/`` —
``conditional_block_op.cc`` (paddle.static.nn.cond), ``while_op.cc``
(while_loop), plus ``case``/``switch_case``
(``python/paddle/fluid/layers/control_flow.py``). TPU-native semantics:

* eager (concrete predicate): plain Python branch/loop — what the reference's
  dygraph does;
* traced (jit / to_static / inside an engine): lowered to ``lax.cond`` /
  ``lax.switch`` / ``lax.while_loop`` so the compiled program carries real
  XLA control flow instead of unrolled or host-synced branches.
"""
from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..core.dispatch import as_tensor
from ..core.tensor import Tensor


def _is_traced(x) -> bool:
    return isinstance(getattr(x, "_data", x), jax.core.Tracer)


def _to_arrays(tree):
    return jax.tree_util.tree_map(
        lambda t: t._data if isinstance(t, Tensor) else t, tree,
        is_leaf=lambda t: isinstance(t, Tensor),
    )


def _to_tensors(tree):
    return jax.tree_util.tree_map(
        lambda a: Tensor(a) if hasattr(a, "dtype") else a, tree
    )


def cond(pred, true_fn: Callable, false_fn: Callable, name=None):
    """paddle.static.nn.cond — reference conditional_block_op.cc.

    ``true_fn``/``false_fn`` take no arguments (closures over tensors) and
    must return the same structure.
    """
    p = as_tensor(pred)
    if not _is_traced(p):
        return true_fn() if bool(p._data) else false_fn()
    pa = p._data.reshape(())

    def wrap(fn):
        def run(_):
            return _to_arrays(fn())
        return run

    out = lax.cond(pa.astype(bool), wrap(true_fn), wrap(false_fn), 0)
    return _to_tensors(out)


def case(pred_fn_pairs: Sequence, default: Callable = None, name=None):
    """First pair whose predicate is true wins (reference layers.case)."""
    if not pred_fn_pairs:
        raise ValueError("case() needs at least one (pred, fn) pair")
    pred, fn = pred_fn_pairs[0]
    rest = pred_fn_pairs[1:]
    if not rest:
        if default is None:
            return cond(pred, fn, fn)
        return cond(pred, fn, default)
    return cond(pred, fn, lambda: case(rest, default))


def switch_case(branch_index, branch_fns, default: Callable = None, name=None):
    """Index-selected branch (reference layers.switch_case → lax.switch)."""
    idx = as_tensor(branch_index)
    if isinstance(branch_fns, dict):
        keys = sorted(branch_fns)
        fns = [branch_fns[k] for k in keys]
        # map arbitrary keys onto dense switch indices
        def to_dense(i):
            d = jnp.zeros((), jnp.int32) + len(fns)  # default slot
            for j, k in enumerate(keys):
                d = jnp.where(i == k, j, d)
            return d
        dense = to_dense(idx._data.astype(jnp.int32))
    else:
        fns = list(branch_fns)
        i = idx._data.astype(jnp.int32)
        # out-of-range (either side) selects the default slot, per reference
        dense = jnp.where((i < 0) | (i >= len(fns)), len(fns), i)
    if default is not None:
        fns = fns + [default]
    else:
        fns = fns + [fns[-1]]
    if not _is_traced(idx):
        return fns[min(int(dense), len(fns) - 1)]()

    def wrap(fn):
        def run(_):
            return _to_arrays(fn())
        return run

    out = lax.switch(jnp.minimum(dense, len(fns) - 1), [wrap(f) for f in fns], 0)
    return _to_tensors(out)


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars, is_test=False, name=None):
    """paddle.static.nn.while_loop — reference while_op.cc.

    ``cond_fn(*vars) -> bool tensor``, ``body_fn(*vars) -> vars'``. Eagerly a
    Python loop; traced, a ``lax.while_loop`` (shape-stable carry required,
    as the reference's while_op requires stable var shapes across steps).
    """
    vars_t = [as_tensor(v) if not isinstance(v, (list, tuple)) else v for v in loop_vars]
    traced = any(_is_traced(v) for v in vars_t if isinstance(v, Tensor))
    if not traced:
        state = list(vars_t)
        while bool(as_tensor(cond_fn(*state))._data):
            out = body_fn(*state)
            state = list(out) if isinstance(out, (list, tuple)) else [out]
        return state

    def carry_cond(arrays):
        ts = [Tensor(a) for a in arrays]
        c = cond_fn(*ts)
        return as_tensor(c)._data.reshape(()).astype(bool)

    def carry_body(arrays):
        ts = [Tensor(a) for a in arrays]
        out = body_fn(*ts)
        out = list(out) if isinstance(out, (list, tuple)) else [out]
        return tuple(as_tensor(o)._data for o in out)

    init = tuple(as_tensor(v)._data for v in vars_t)
    final = lax.while_loop(carry_cond, carry_body, init)
    return [Tensor(a) for a in final]


__all__ = ["cond", "case", "switch_case", "while_loop"]
