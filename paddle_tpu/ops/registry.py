"""Op registry — enumerates every registered op endpoint.

The analogue of the reference's OpInfoMap (``paddle/fluid/framework/op_info.h``;
`op_registry.h` registrations, ~913 incl. grad kernels). Grad ops need no
separate registration here — every differentiable op's vjp comes from the
tape — so the count below is of *forward* endpoints.
"""
from __future__ import annotations

import inspect
from typing import Callable, Dict


def _module_fns(mod, prefix=""):
    out = {}
    for n in dir(mod):
        if n.startswith("_"):
            continue
        fn = getattr(mod, n)
        if callable(fn) and not inspect.isclass(fn) and inspect.getmodule(fn) in (mod, None):
            out[prefix + n] = fn
    return out


def all_ops() -> Dict[str, Callable]:
    """name -> callable for every registered op endpoint."""
    from . import control_flow, creation, extra, generated, inplace, linalg, manipulation, math, misc
    from .. import fft as fft_mod
    from .. import signal as signal_mod
    from ..nn import functional as F

    ops: Dict[str, Callable] = {}
    for mod in (math, manipulation, creation, linalg):
        ops.update(_module_fns(mod))
    ops.update({n: generated.GENERATED[n] for n in generated.GENERATED})
    ops.update({n: getattr(extra, n) for n in extra.__all__})
    ops.update({n: getattr(control_flow, n) for n in control_flow.__all__})
    ops.update({n: getattr(misc, n) for n in misc.__all__})
    ops.update({f"fft.{n}": getattr(fft_mod, n) for n in fft_mod.__all__})
    ops.update({f"signal.{n}": getattr(signal_mod, n) for n in signal_mod.__all__})
    ops.update({f"functional.{n}": v for n, v in _module_fns(F).items()})
    for mod_name in ("activation", "common", "conv", "loss", "norm", "pooling",
                     "attention", "vision"):
        try:
            sub = __import__(f"paddle_tpu.nn.functional.{mod_name}", fromlist=["x"])
            ops.update({f"functional.{n}": v for n, v in _module_fns(sub).items()})
        except ImportError:
            pass
    try:
        from ..vision import ops as vops
        ops.update({f"vision.{n}": v for n, v in _module_fns(vops).items()})
    except ImportError:
        pass
    from .. import sparse as sparse_mod

    ops.update({
        f"sparse.{n}": getattr(sparse_mod, n)
        for n in sparse_mod.__all__ if callable(getattr(sparse_mod, n))
    })
    from .. import quantization as quant_mod

    ops.update({
        f"quant.{n}": getattr(quant_mod, n)
        for n in ("fake_quantize_dequantize_abs_max", "quantize_to_int8")
    })
    try:
        from .. import text as text_mod
        ops.update({f"text.{n}": getattr(text_mod, n) for n in ("viterbi_decode",)})
    except ImportError:
        pass
    from . import sequence as sequence_mod

    ops.update({
        f"sequence.{n}": getattr(sequence_mod, n) for n in sequence_mod.__all__
    })
    from . import metrics_ops

    ops.update({
        f"metric.{n}": getattr(metrics_ops, n) for n in metrics_ops.__all__
    })
    try:
        from ..incubate import operators as incubate_ops

        ops.update({
            f"incubate.{n}": getattr(incubate_ops, n)
            for n in ("softmax_mask_fuse", "softmax_mask_fuse_upper_triangle",
                      "graph_send_recv", "graph_khop_sampler")
        })
    except ImportError:
        pass
    ops.update(inplace.INPLACE_OPS)
    return ops


def op_count() -> int:
    return len(all_ops())
