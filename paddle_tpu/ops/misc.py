"""Top-level misc ops closing the reference namespace gap.

Parity targets: python/paddle/tensor/attribute.py (rank/shape/is_*),
math.py (multiplex), manipulation.py (reverse), random.py (poisson),
search.py (mode), framework (set_printoptions, create_parameter).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.lazy import concrete as _concrete

from ..core.dispatch import as_tensor, eager_call
from ..core.tensor import Tensor


def is_tensor(x):
    return isinstance(x, Tensor)


def is_floating_point(x):
    return jnp.issubdtype(as_tensor(x)._data.dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(as_tensor(x)._data.dtype, jnp.integer)


def is_complex(x):
    return jnp.issubdtype(as_tensor(x)._data.dtype, jnp.complexfloating)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(as_tensor(x)._data.size == 0), stop_gradient=True)


def rank(input):
    return Tensor(jnp.asarray(as_tensor(input)._data.ndim), stop_gradient=True)


def shape(input):
    return Tensor(jnp.asarray(as_tensor(input)._data.shape, jnp.int64), stop_gradient=True)


def tolist(x):
    return np.asarray(as_tensor(x)._data).tolist()


def reverse(x, axis, name=None):
    """reference manipulation: reverse == flip."""
    from . import manipulation

    return manipulation.flip(x, axis)


def multiplex(inputs, index, name=None):
    """Row-wise select among candidate tensors (reference multiplex_op)."""
    tensors = [as_tensor(t) for t in inputs] + [as_tensor(index)]

    def fn(*arrays):
        *cands, idx = arrays
        stacked = jnp.stack(cands)  # (K, B, ...)
        idx = idx.reshape(-1).astype(jnp.int32)
        return stacked[idx, jnp.arange(stacked.shape[1])]

    return eager_call("multiplex", fn, tensors)


def mode(x, axis=-1, keepdim=False, name=None):
    """Most frequent value (reference mode_op): returns (values, indices)."""
    t = as_tensor(x)

    def fn(a, axis=-1, keepdim=False):
        s = jnp.sort(a, axis=axis)
        si = jnp.argsort(a, axis=axis)
        n = a.shape[axis]
        s_m = jnp.moveaxis(s, axis, -1)
        si_m = jnp.moveaxis(si, axis, -1)
        runs = jnp.cumsum(
            jnp.concatenate(
                [jnp.ones(s_m.shape[:-1] + (1,), jnp.int32),
                 (s_m[..., 1:] != s_m[..., :-1]).astype(jnp.int32)], axis=-1),
            axis=-1,
        )
        # count of each element's run, take the element ending the longest run
        counts = jax.vmap(
            lambda r: jnp.bincount(r, length=n + 1)[r],
            in_axes=0, out_axes=0,
        )(runs.reshape(-1, n)).reshape(runs.shape)
        best = jnp.argmax(counts, axis=-1)
        vals = jnp.take_along_axis(s_m, best[..., None], axis=-1)
        idxs = jnp.take_along_axis(si_m, best[..., None], axis=-1)
        if not keepdim:
            vals, idxs = vals[..., 0], idxs[..., 0]
        else:
            vals = jnp.moveaxis(vals, -1, axis)
            idxs = jnp.moveaxis(idxs, -1, axis)
        return vals, idxs

    return eager_call(
        "mode", fn, [t], attrs={"axis": axis, "keepdim": bool(keepdim)},
        differentiable=False,
    )


def poisson(x, name=None):
    """Poisson-sample with rate tensor x (reference poisson_op)."""
    from ..core import random as random_state

    t = as_tensor(x)
    key = random_state.next_key()
    return Tensor(
        jax.random.poisson(key, _concrete(t._data).astype(jnp.float32)).astype(t._data.dtype),
        stop_gradient=True,
    )


_PRINTOPTS = {"precision": 8, "threshold": 1000, "edgeitems": 3, "linewidth": 80}


def set_printoptions(precision=None, threshold=None, edgeitems=None, sci_mode=None, linewidth=None):
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    _PRINTOPTS.update(kw)
    np.set_printoptions(**kw)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False, default_initializer=None):
    """reference layers.create_parameter."""
    from ..core.tensor import Parameter
    from ..nn.initializer import Constant, Normal

    init = default_initializer or (Constant(0.0) if is_bias else Normal(std=0.02))
    data = jnp.zeros(tuple(int(s) for s in shape), dtype)
    p = Parameter(data, name=name)
    init(p)
    return p


def disable_signal_handler():
    return None


def is_compiled_with_cinn():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_mlu():
    return False


def is_compiled_with_ipu():
    return False


def get_cuda_rng_state():
    from ..core.random import get_rng_state

    return get_rng_state()


def set_cuda_rng_state(state):
    from ..core.random import set_rng_state

    return set_rng_state(state)


__all__ = [
    "is_tensor", "is_floating_point", "is_integer", "is_complex", "is_empty",
    "rank", "shape", "tolist", "reverse", "multiplex", "mode", "poisson",
    "set_printoptions", "create_parameter", "disable_signal_handler",
    "is_compiled_with_cinn", "is_compiled_with_rocm", "is_compiled_with_xpu",
    "is_compiled_with_npu", "is_compiled_with_mlu", "is_compiled_with_ipu",
    "get_cuda_rng_state", "set_cuda_rng_state",
]
