"""On-disk kernel tuning DB: verified autotune winners that survive restarts.

Layout: one JSON file per (kernel, shape-bucket key, platform, jax version)
under ``~/.cache/paddle_tpu/tune/`` (override: ``FLAGS_kernel_tune_dir``),
named ``<kernel>-<sha1[:16] of the canonical key>.json`` — keyed like the
XLA executable cache, so a DB written on one platform/jax can never leak a
config onto another.

Durability contract (the PR-3 torn-cache incident class must be impossible
here): every write goes through ``framework.io.atomic_open`` (tmp +
``os.replace``), and every read re-derives a sha1 checksum over the payload
body and re-checks every key field. A torn, truncated, hand-edited or
stale-keyed entry is a *structured reject* — counted
(``kernel_tune_db_rejects``), the bad file removed, and the lookup reported
as a miss so ``search`` mode re-tunes and ``off``/``ondemand`` fall back to
the pinned defaults. A wrong config is never returned; deleting the DB dir
is always safe (silent fallback to defaults).
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Optional

from ...framework import flags
from ...framework.io import atomic_open
from ...profiler import counter_inc

__all__ = ["tune_dir", "entry_path", "store", "lookup", "delete"]

_SCHEMA = 1


def tune_dir() -> str:
    d = flags.flag("FLAGS_kernel_tune_dir", "") or ""
    return d or os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                             "tune")


def _platform() -> str:
    import jax

    try:
        return jax.devices()[0].platform
    except Exception:
        return "unknown"


def _canon_key(key: tuple):
    # JSON round-trip canonicalization: tuples become lists, so a stored key
    # compares equal to a live one after one encode/decode
    return json.loads(json.dumps(list(key)))


def _body(name: str, key: tuple) -> dict:
    import jax

    return {"schema": _SCHEMA, "kernel": name, "key": _canon_key(key),
            "platform": _platform(), "jax": jax.__version__}


def _digest(body: dict) -> str:
    return hashlib.sha1(
        json.dumps(body, sort_keys=True).encode()).hexdigest()


def entry_path(name: str, key: tuple) -> str:
    tag = _digest(_body(name, key))[:16]
    safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in name)
    return os.path.join(tune_dir(), f"{safe}-{tag}.json")


def store(name: str, key: tuple, config: dict, best_ms: Optional[float],
          default_ms: Optional[float]) -> str:
    body = _body(name, key)
    body.update(config=dict(config),
                best_ms=best_ms, default_ms=default_ms)
    payload = dict(body, checksum=_digest(body))
    path = entry_path(name, key)
    os.makedirs(tune_dir(), exist_ok=True)
    with atomic_open(path, "w") as f:
        json.dump(payload, f, sort_keys=True, indent=1)
    return path


def lookup(name: str, key: tuple) -> Optional[dict]:
    """The winner config for ``key``, or None on a miss OR a rejected
    (torn/corrupt/mismatched) entry — a wrong config is never returned."""
    path = entry_path(name, key)
    try:
        with open(path) as f:
            raw = f.read()
    except OSError:
        return None  # plain miss
    try:
        payload = json.loads(raw)
        checksum = payload.pop("checksum")
        if checksum != _digest(payload):
            raise ValueError("checksum mismatch")
        expect = _body(name, key)
        for field, want in expect.items():
            if payload.get(field) != want:
                raise ValueError(f"key field {field!r} mismatch")
        config = payload["config"]
        if not isinstance(config, dict):
            raise ValueError("config is not a dict")
        return config
    except (ValueError, KeyError, TypeError, AttributeError):
        # torn/truncated/hand-edited/stale entry: structured reject — count,
        # drop the bad file, report a miss (search re-tunes; off/ondemand
        # fall back to the pinned defaults)
        counter_inc("kernel_tune_db_rejects")
        delete(name, key)
        return None


def delete(name: str, key: tuple) -> None:
    try:
        os.remove(entry_path(name, key))
    except OSError:
        pass
