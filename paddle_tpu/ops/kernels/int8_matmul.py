"""Weight-only int8 matmul kernel (registry: ``int8_matmul``).

The serving engine's int8 path (``serving/int8.py``) stores weights as
``{int8 q, f32 absmax scale}`` and dequantizes the WHOLE tensor to the
compute dtype before every dense matmul — for the LM head that is a full
``(V, d)`` f32 materialization per decode step just to read one row's
logits. This kernel fuses the dequant into the matmul: the int8 weight
streams into VMEM one ``block_n`` column-tile at a time, is dequantized
in-register with the exact ``(q.astype(f32) * (scale / 127)).astype(dtype)``
expression ``dequantize_tree`` uses, and is consumed immediately — 4x less
weight traffic (int8 vs f32), no full-size dequant buffer.

Because the per-tile dequant expression and the ``dot_general`` dims match
the dense path op-for-op, the output is **bit-identical** to
dequantize-then-matmul on the CPU tier (interpret mode); ``block_n`` only
changes the program count, never the accumulation order within a tile's dot.

``transpose_w=True`` is the GPT tied head (``rows @ wte.T``, weight stored
``(N, K)``); ``False`` is the Llama head (``rows @ head_w``, ``(K, N)``).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...core.compat import enable_x64
from .registry import register_kernel, resolve_config

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

__all__ = ["int8_matmul", "int8_matmul_key"]


def _kernel_x64_off(interpret):
    import contextlib

    return contextlib.nullcontext() if interpret else enable_x64(False)


def _pick_bn(limit: int, n: int) -> int:
    """Largest of (limit, 512, 256, 128) that tiles N; N itself if none do
    (mirrors flash's ``_pick_block`` degrade-don't-fail contract)."""
    for b in (limit, 512, 256, 128):
        if b <= n and n % b == 0:
            return b
    return n


def int8_matmul_key(M, K, N, transpose_w, dtype) -> tuple:
    """Shape bucket: M (the decode batch) rounded up to a power of two; K/N
    are weight dims and exact."""
    m = 1
    while m < int(M):
        m *= 2
    return (m, int(K), int(N), bool(transpose_w), str(jnp.dtype(dtype)))


def _int8_kernel(scale_ref, x_ref, w_ref, o_ref, *, transpose_w):
    # the exact dequant expression from serving/int8.py dequantize_tree —
    # required for bit-identity with the dense path
    wd = (w_ref[...].astype(jnp.float32)
          * (scale_ref[0] / 127.0)).astype(x_ref.dtype)
    dims = ((((1,), (1,)), ((), ())) if transpose_w
            else (((1,), (0,)), ((), ())))
    o_ref[...] = jax.lax.dot_general(x_ref[...], wd, dims).astype(o_ref.dtype)


def int8_matmul(x, qw, scale, transpose_w=True, config=None, interpret=None):
    """``x @ dequant(qw).T`` (transpose_w) or ``x @ dequant(qw)``.

    x: (..., K) activations; qw: int8 ``(N, K)`` if transpose_w else
    ``(K, N)``; scale: scalar f32 absmax. Leading dims of x are flattened
    into the row dim and restored on return.
    """
    if not _HAS_PALLAS:
        raise RuntimeError("pallas unavailable")
    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"
    lead = x.shape[:-1]
    K = x.shape[-1]
    x2 = x.reshape(-1, K)
    M = x2.shape[0]
    N = qw.shape[0] if transpose_w else qw.shape[1]
    if config is None:
        config = resolve_config(
            "int8_matmul", int8_matmul_key(M, K, N, transpose_w, x.dtype))
    bn = _pick_bn(int(config.get("block_n", 512)), N)
    wspec = (pl.BlockSpec((bn, K), lambda i: (i, 0)) if transpose_w
             else pl.BlockSpec((K, bn), lambda i: (0, i)))
    with _kernel_x64_off(interpret):
        out = pl.pallas_call(
            functools.partial(_int8_kernel, transpose_w=transpose_w),
            grid=(N // bn,),
            in_specs=[
                pl.BlockSpec((1,), lambda i: (0,), memory_space=pltpu.SMEM),
                pl.BlockSpec((M, K), lambda i: (0, 0)),
                wspec,
            ],
            out_specs=pl.BlockSpec((M, bn), lambda i: (0, i)),
            out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
            interpret=interpret,
        )(jnp.asarray(scale, jnp.float32).reshape(1), x2, qw)
    return out.reshape(*lead, N)


# -- registry ----------------------------------------------------------------

def _valid(config, key):
    # _pick_bn degrades any block_n, so every declared choice traces; still
    # skip tiles wider than the weight
    return int(config["block_n"]) <= key[2] or key[2] < 128


def _runner(key):
    import numpy as np

    M, K, N, transpose_w, dtype = key
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(M, K), dtype)
    w = rng.randn(*((N, K) if transpose_w else (K, N))).astype(np.float32)
    scale = jnp.asarray(np.abs(w).max(), jnp.float32)
    qw = jnp.asarray(
        np.clip(np.round(w / (np.asarray(scale) / 127.0)), -127, 127),
        jnp.int8)

    def make(config):
        fn = jax.jit(functools.partial(
            int8_matmul, transpose_w=transpose_w, config=config))
        return lambda: fn(x, qw, scale)

    return make


register_kernel(
    "int8_matmul",
    defaults={"block_n": 512},
    space={"block_n": (128, 256, 512, 1024, 2048)},
    runner=_runner,
    valid=_valid,
)
