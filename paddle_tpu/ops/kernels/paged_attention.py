"""Block-table-aware paged-attention decode kernel (registry: ``paged_attention``).

The serving engine's gather-based decode (``models/generation.py
build_paged_decode``) materializes every row's context DENSE in HBM —
``kpool[li][tables].reshape(B, T_pad, KV, D)`` per layer per step — then
attends over the padding behind each row's live mask. This kernel reads K/V
**directly from the PagePool blocks**: per grid step it DMAs exactly the
blocks named by that row's block table into VMEM scratch, bounds the score
loop at the row's LIVE block count (``pos // block_size + 1`` — no
trash-block padding attend), and runs the grouped-GQA attention math in the
same op order as the dense reference, so the output is **bit-identical** to
the gather path (pinned on the CPU tier via Pallas interpret mode, where
both paths execute the same XLA backend ops).

Contract vs the gather path: the caller scatters this step's fresh K/V into
the pool BEFORE the kernel reads it (the reference overwrites the gathered
context at ``pos`` in-context — same values, same slot). Trash blocks ARE
copied (matching the reference's gather of them) so dead context stays
finite; their scores are never computed and their softmax weights are an
exact 0.0, so they contribute exactly nothing — also matching the reference.

Tunables: ``rows_per_program`` amortizes per-program overhead over several
batch rows; ``score_mode`` picks the live-bounded per-block score loop
(``"live"``) or one whole-context dot (``"full"`` — the reference's exact
gemm shape, more FLOPs, fewer loop iterations). Both verified bit-identical
at every engine-reachable shape: the engine's ``block_size`` is a multiple
of 8, which keeps each per-block score gemm's output width on the CPU SIMD
grain so chunked and full-width dots round identically (at a hypothetical
block_size of 4 the Eigen kernels pick different vector strategies and the
live path drifts by a ulp — ``"full"`` is exact at ANY shape).

bf16-on-TPU note: the surrounding model runs its score einsum under the
global ``jax_default_matmul_precision`` while Mosaic uses the MXU's native
bf16×bf16→f32; the bit-identity pin is the f32 CPU tier, TPU bf16 parity is
numeric (same contract as the flash kernel).

Tensor-parallel note: under ``FLAGS_serve_tp`` the engine calls this kernel
INSIDE the per-device shard_map body with the local KV-head shard — q is
``(B, KV_local*rep, D)``, the pools are the chip's ``kv_heads/tp`` slice,
and the block tables are the replicated host truth. Attention is
independent per KV group, so the kernel needs no axis awareness: the local
call is exactly a smaller-KV instance of the same contract, and the tp
boundary (one all_gather of the per-head outputs) lives in the caller.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ...core.compat import enable_x64
from .registry import register_kernel, resolve_config

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

__all__ = ["paged_attention_rows", "paged_attention_key"]


def _kernel_x64_off(interpret):
    # Mosaic has no i64/f64 lowering (see ops/pallas/flash_attention.py);
    # interpret mode must keep the outer x64 state untouched
    import contextlib

    return contextlib.nullcontext() if interpret else enable_x64(False)


def paged_attention_key(B, MB, BS, KV, rep, D, dtype) -> tuple:
    """Shape-bucket key. B and MB arrive pre-bucketed (the engine's decode
    bucket and power-of-two gather width), so the key is exact."""
    return (int(B), int(MB), int(BS), int(KV), int(rep), int(D),
            str(jnp.dtype(dtype)))


def _attend_one_row(q, kc, vc, pos, *, KV, rep, D, BS, MB, score_mode):
    """The per-row attention math, mirroring ``_grouped_attention``'s op
    sequence exactly so the CPU interpret path is bit-identical to the dense
    reference. The size-1 query axis is KEPT in the einsum specs
    (``qgrd,kgd->grqk``): dropping it changes jnp.einsum's contraction
    lowering at rep=1 and costs a ulp vs the batched reference."""
    T_pad = MB * BS
    scale = jnp.asarray(1.0 / np.sqrt(D), q.dtype)
    live = jnp.arange(T_pad, dtype=jnp.int32) <= pos
    q = q.reshape(1, KV, rep, D)  # (q=1, g, r, d)
    if score_mode == "live":
        # per-block scores bounded at the row's live block count; dead
        # columns stay at the exact -inf the reference's mask produces
        n_live = pos // BS + 1
        s0 = jnp.where(jnp.zeros((KV, rep, 1, T_pad), bool),
                       jnp.zeros((KV, rep, 1, T_pad), q.dtype), -jnp.inf)

        def body(j, s):
            kb = jax.lax.dynamic_slice_in_dim(kc, j * BS, BS, axis=0)
            sb = jnp.einsum("qgrd,kgd->grqk", q, kb) * scale
            return jax.lax.dynamic_update_slice_in_dim(s, sb, j * BS, axis=3)

        s = jax.lax.fori_loop(0, n_live, body, s0)
        s = jnp.where(live[None, None, None, :], s, -jnp.inf)
    else:  # "full": one dot over the whole padded context (reference shape)
        s = jnp.einsum("qgrd,kgd->grqk", q, kc) * scale
        s = jnp.where(live[None, None, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("grqk,kgd->qgrd", p, vc)  # (1, KV, rep, D)


def _paged_kernel(tables_ref, pos_ref, q_ref, kpool_ref, vpool_ref, o_ref,
                  ctx_k, ctx_v, sem, *, KV, rep, D, BS, MB, R, score_mode):
    H = KV * rep
    T_pad = MB * BS
    for r in range(R):
        # copy the row's blocks (trash included — keeps dead context finite,
        # matching the gather) from the HBM pool into VMEM scratch
        for j in range(MB):
            bid = tables_ref[r, j]
            pltpu.make_async_copy(kpool_ref.at[bid], ctx_k.at[j], sem).start()
            pltpu.make_async_copy(kpool_ref.at[bid], ctx_k.at[j], sem).wait()
            pltpu.make_async_copy(vpool_ref.at[bid], ctx_v.at[j], sem).start()
            pltpu.make_async_copy(vpool_ref.at[bid], ctx_v.at[j], sem).wait()
        q = q_ref[r].reshape(KV, rep, D)
        o = _attend_one_row(
            q, ctx_k[:].reshape(T_pad, KV, D), ctx_v[:].reshape(T_pad, KV, D),
            pos_ref[r], KV=KV, rep=rep, D=D, BS=BS, MB=MB,
            score_mode=score_mode)
        o_ref[r] = o.reshape(H * D)


def paged_attention_rows(q, kpool, vpool, tables, pos, config=None,
                         interpret=None):
    """One decode step's attention read over the paged pool.

    q: (B, H, D) — one fresh-token query per batch row (its K/V already
    scattered into the pool at the row's write slot); kpool/vpool:
    (NB, BS, KV, D) — ONE layer's pool; tables: (B, MB) int32 per-row block
    tables (dead columns at the trash block); pos: (B,) int32 per-row write
    positions. Returns (B, H*D) — ``_grouped_attention``'s reshaped output.
    """
    if not _HAS_PALLAS:
        raise RuntimeError("pallas unavailable")
    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"
    B, H, D = q.shape
    NB, BS, KV, _ = kpool.shape
    MB = tables.shape[1]
    rep = H // KV
    if config is None:
        config = resolve_config(
            "paged_attention", paged_attention_key(B, MB, BS, KV, rep, D,
                                                   q.dtype))
    R = int(config.get("rows_per_program", 1))
    if B % R:
        R = 1
    score_mode = str(config.get("score_mode", "live"))
    kern = functools.partial(
        _paged_kernel, KV=KV, rep=rep, D=D, BS=BS, MB=MB, R=R,
        score_mode=score_mode)
    with _kernel_x64_off(interpret):
        return pl.pallas_call(
            kern,
            grid=(B // R,),
            in_specs=[
                pl.BlockSpec((R, MB), lambda b: (b, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((R,), lambda b: (b,), memory_space=pltpu.SMEM),
                pl.BlockSpec((R, H * D), lambda b: (b, 0)),
                pl.BlockSpec(memory_space=pltpu.ANY),
                pl.BlockSpec(memory_space=pltpu.ANY),
            ],
            out_specs=pl.BlockSpec((R, H * D), lambda b: (b, 0)),
            out_shape=jax.ShapeDtypeStruct((B, H * D), q.dtype),
            scratch_shapes=[
                pltpu.VMEM((MB, BS, KV, D), q.dtype),
                pltpu.VMEM((MB, BS, KV, D), q.dtype),
                pltpu.SemaphoreType.DMA,
            ],
            interpret=interpret,
        )(jnp.asarray(tables, jnp.int32).reshape(B, MB),
          jnp.asarray(pos, jnp.int32), q.reshape(B, H * D), kpool, vpool)


# -- registry ----------------------------------------------------------------

def _valid(config, key):
    B = key[0]
    return B % int(config["rows_per_program"]) == 0


def _runner(key):
    """Synthetic pool/tables at the bucketed shape for measured search."""
    B, MB, BS, KV, rep, D, dtype = key
    rng = np.random.RandomState(0)
    NB = max(B * MB + 1, 2)
    kpool = jnp.asarray(rng.randn(NB, BS, KV, D), dtype)
    vpool = jnp.asarray(rng.randn(NB, BS, KV, D), dtype)
    tables = np.zeros((B, MB), np.int32)
    pos = np.zeros((B,), np.int32)
    for b in range(B):
        n_live = 1 + (b % MB)
        pos[b] = n_live * BS - 1
        tables[b, :n_live] = 1 + b * MB + np.arange(n_live)
    tables, pos = jnp.asarray(tables), jnp.asarray(pos)
    q = jnp.asarray(rng.randn(B, KV * rep, D), dtype)

    def make(config):
        fn = jax.jit(functools.partial(paged_attention_rows, config=config))
        return lambda: fn(q, kpool, vpool, tables, pos)

    return make


register_kernel(
    "paged_attention",
    defaults={"rows_per_program": 1, "score_mode": "live"},
    space={"rows_per_program": (1, 2, 4), "score_mode": ("live", "full")},
    runner=_runner,
    valid=_valid,
)
