"""Registry specs for the pre-existing tunable kernels.

These hoist the block-size constants that were frozen at the flash-attention
and fused-CE call sites into registry DEFAULTS — the values here must stay
equal to the constants that shipped before the registry existed, because
with ``FLAGS_kernel_autotune=off`` the call sites must trace byte-identical
HLO to HEAD. The kernel implementations stay where they are
(``ops/pallas/flash_attention.py``, ``ops/fused_ce.py``); runners import
them lazily so registering a spec never pulls in pallas.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .registry import register_kernel

__all__ = ["flash_attention_key", "fused_ce_key"]


def _pow2(n: int) -> int:
    m = 1
    while m < int(n):
        m *= 2
    return m


def flash_attention_key(b, h, t, t_kv, d, dtype, causal) -> tuple:
    """(batch*heads pow2-bucketed, heads, q len, kv len, head dim, dtype,
    causal) — lengths stay exact because block divisibility depends on them.
    """
    return (_pow2(int(b) * int(h)), int(h), int(t), int(t_kv), int(d),
            str(jnp.dtype(dtype)), bool(causal))


def _flash_runner(key):
    import numpy as np

    from ..pallas.flash_attention import flash_attention_array

    bh, h, t, t_kv, d, dtype, causal = key
    b = max(bh // h, 1)
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, h, t, d), dtype)
    k = jnp.asarray(rng.randn(b, h, t_kv, d), dtype)
    v = jnp.asarray(rng.randn(b, h, t_kv, d), dtype)

    def make(config):
        fn = jax.jit(functools.partial(
            flash_attention_array, causal=causal,
            block_q=int(config["block_q"]), block_k=int(config["block_k"])))
        return lambda: fn(q, k, v)

    return make


register_kernel(
    "flash_attention",
    # the frozen flash_attention_array signature defaults at registry birth
    defaults={"block_q": 512, "block_k": 512},
    space={"block_q": (128, 256, 512, 1024),
           "block_k": (128, 256, 512, 1024)},
    runner=_flash_runner,
    # _pick_block degrades any requested block to a divisor of t, so every
    # declared choice traces for every key
    valid=None,
)


def fused_ce_key(n, d, v, dtype) -> tuple:
    """(rows pow2-bucketed, hidden, vocab, dtype). Rows bucket because the
    scan pads the last block anyway; d and V set the block-logits footprint
    and stay exact."""
    return (_pow2(int(n)), int(d), int(v), str(jnp.dtype(dtype)))


def _fce_runner(key):
    import numpy as np

    from ..fused_ce import fused_linear_cross_entropy

    n, d, v, dtype = key
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(n, d), dtype)
    w = jnp.asarray(rng.randn(v, d), dtype)
    labels = jnp.asarray(rng.randint(0, v, size=(n,)), jnp.int32)

    def make(config):
        br = int(config["block_rows"])

        @jax.jit
        def step():
            # time the full train-step shape: forward + both grads (the
            # backward rematerializes block logits, so block_rows matters
            # twice)
            return jax.value_and_grad(
                lambda xx, ww: fused_linear_cross_entropy(
                    xx, ww, labels, br), argnums=(0, 1))(x, w)

        return step

    return make


register_kernel(
    "fused_ce",
    # the frozen fused_linear_cross_entropy block_rows default
    defaults={"block_rows": 2048},
    space={"block_rows": (512, 1024, 2048, 4096, 8192)},
    runner=_fce_runner,
    valid=None,
)
