"""Kernel registry: tunable Pallas kernels behind one config-resolution door.

Every registered kernel declares (a) a correctness-pinned **default config**
equal to the constants that were hand-frozen at its call site before this
layer existed, and (b) a **config space** of tunable axes (block sizes,
score/pipelining strategy, rows-per-program …). Call sites ask
:func:`resolve_config` for the config to trace with:

- ``FLAGS_kernel_autotune=off`` (default): the resolve is a plain dict probe
  returning the declared defaults — no autotuner, no tuning-DB I/O, no
  verifier, nothing imported beyond this module. Byte-identical to the
  pre-registry call sites (the inert-layer contract, tier-1 tripwired).
- ``ondemand``: winners previously persisted in the on-disk tuning DB
  (``ops/kernels/db.py``) are used when present; a miss falls back to the
  defaults. Never searches.
- ``search``: a DB miss triggers a real measured-timing search over the
  config space (``ops/kernels/autotune.py``) and persists the verified
  winner.

Resolution happens at TRACE time (shapes are static), so the per-call cost
with autotune off is one dict lookup — not a per-step runtime cost.

This registry is about *kernel configs*; it is unrelated to
``ops/registry.py`` (the functional op-surface registry).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Mapping, Optional, Tuple

from ...framework import flags

__all__ = ["KernelSpec", "register_kernel", "get_kernel", "kernel_names",
           "resolve_config"]


@dataclasses.dataclass(frozen=True)
class KernelSpec:
    """One registered kernel.

    ``runner(key)`` returns ``make(config) -> step`` where ``step()`` runs
    the kernel once on synthetic inputs shaped like ``key`` and returns its
    output — the autotuner's measurement/verification harness. ``valid``
    filters configs that cannot trace for ``key`` (e.g. rows-per-program not
    dividing the batch). Both are only touched in ``ondemand``/``search``.
    """

    name: str
    defaults: Mapping[str, Any]
    space: Mapping[str, Tuple[Any, ...]]
    runner: Optional[Callable[[tuple], Callable[[dict], Callable[[], Any]]]] = None
    valid: Optional[Callable[[dict, tuple], bool]] = None


_REGISTRY: Dict[str, KernelSpec] = {}


def register_kernel(name: str, *, defaults: Mapping[str, Any],
                    space: Mapping[str, Tuple[Any, ...]],
                    runner=None, valid=None) -> KernelSpec:
    """Register (or re-register — last wins, so tests can stub) a kernel."""
    spec = KernelSpec(name=name, defaults=dict(defaults),
                      space={k: tuple(v) for k, v in space.items()},
                      runner=runner, valid=valid)
    _REGISTRY[name] = spec
    return spec


def get_kernel(name: str) -> KernelSpec:
    return _REGISTRY[name]


def kernel_names():
    return sorted(_REGISTRY)


def resolve_config(name: str, key: tuple = ()) -> Dict[str, Any]:
    """The one config-resolution door every registered call site goes
    through. ``key`` is the kernel's shape bucket (see each kernel's
    ``*_key`` helper) — the DB key is (kernel, key, dtype-in-key, platform,
    jax version), mirroring the executable cache's keying."""
    spec = _REGISTRY[name]
    mode = flags.flag("FLAGS_kernel_autotune", "off")
    if mode not in ("ondemand", "search"):
        # inert layer: a dict probe, nothing else (tier-1 tripwire)
        return dict(spec.defaults)
    from . import autotune

    return autotune.resolve(spec, tuple(key), mode)
