"""Autotuned kernel registry (ROADMAP item 6).

Public surface:

- :func:`register_kernel` / :func:`get_kernel` / :func:`kernel_names` /
  :func:`resolve_config` — the registry (``registry.py``);
- ``autotune`` / ``db`` submodules — the measured-timing search and the
  persistent tuning DB; imported lazily by ``resolve_config`` only when
  ``FLAGS_kernel_autotune`` is ``ondemand``/``search``, so with the default
  ``off`` this package costs one dict probe per trace and nothing else;
- kernel modules — ``paged_attention`` and ``int8_matmul`` (new Pallas
  kernels for serving), plus ``builtin`` (registry specs hoisting the
  frozen flash-attention / fused-CE block constants into defaults).

Importing this package registers every built-in spec. It must NOT import
``autotune``/``db`` at import time (the inert-layer contract).
"""
from __future__ import annotations

from .registry import (KernelSpec, get_kernel, kernel_names, register_kernel,
                       resolve_config)
from . import builtin  # noqa: F401  (registers flash_attention, fused_ce)
from . import paged_attention  # noqa: F401
from . import int8_matmul as int8_matmul_mod  # noqa: F401
from .paged_attention import paged_attention_key, paged_attention_rows
from .int8_matmul import int8_matmul, int8_matmul_key
from .builtin import flash_attention_key, fused_ce_key

__all__ = [
    "KernelSpec", "register_kernel", "get_kernel", "kernel_names",
    "resolve_config",
    "paged_attention_rows", "paged_attention_key",
    "int8_matmul", "int8_matmul_key",
    "flash_attention_key", "fused_ce_key",
]
