"""Measured-timing autotuner for registered kernels.

``resolve`` (the only entry the registry calls) checks the in-process memo,
then the on-disk tuning DB, then — in ``search`` mode — runs a real search:

- candidates are the cartesian product of the kernel's declared config
  space, filtered by the spec's validity predicate and ORDERED by
  ``cost_model.CostModel.kernel_estimate`` (the analytic flops/bytes/
  program-overhead model calibrated against XLA ``cost_analysis`` numbers),
  so plausible configs are visited first under the per-kernel time budget
  (``FLAGS_kernel_tune_budget_s``, a monotonic-clock deadline);
- each candidate is timed with median-of-k wall samples
  (``FLAGS_kernel_tune_samples``) with the FIRST call excluded — that call
  compiles, and compile time must never leak into a steady-state ranking;
- a candidate can only win if :func:`verify` accepts its output against the
  DEFAULT config's output (dtype-scaled allclose + same finite mask) — the
  default is always measured first, so the result is never worse than the
  pinned defaults: a verified faster winner, or the defaults themselves.

Winners persist via ``db.store`` (atomic write); a later process resolves
them straight from disk with zero re-search (``kernel_tune_hits``).
"""
from __future__ import annotations

import itertools
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ...framework import flags
from ...profiler import counter_inc
from ...profiler.spans import span
from . import db

__all__ = ["resolve", "search", "candidates", "verify", "clear_cache"]

# in-process memo of resolved configs: (kernel, key) -> config. One disk
# probe (and at most one search) per shape bucket per process.
_MEM: Dict[tuple, dict] = {}


def clear_cache():
    _MEM.clear()


def _config_in_space(spec, config: dict) -> bool:
    """A DB entry is only trusted if every field names a declared axis with
    a declared choice (defaults count) — a schema-drifted or hand-edited
    config is rejected, never traced."""
    for k, v in config.items():
        if k in spec.defaults and v == spec.defaults[k]:
            continue
        if k not in spec.space or v not in spec.space[k]:
            return False
    return set(config) == set(spec.defaults)


def resolve(spec, key: tuple, mode: str) -> dict:
    memo_key = (spec.name, key)
    cached = _MEM.get(memo_key)
    if cached is not None:
        return dict(cached)
    config = db.lookup(spec.name, key)
    if config is not None and not _config_in_space(spec, config):
        counter_inc("kernel_tune_db_rejects")
        db.delete(spec.name, key)
        config = None
    if config is not None:
        counter_inc("kernel_tune_hits")
        _MEM[memo_key] = dict(config)
        return dict(config)
    counter_inc("kernel_tune_misses")
    if mode == "search" and spec.runner is not None:
        config, best_ms, default_ms, searched = search(spec, key)
        if searched:
            db.store(spec.name, key, config, best_ms, default_ms)
        _MEM[memo_key] = dict(config)
        return dict(config)
    # ondemand miss (or un-runnable kernel): the pinned defaults
    _MEM[memo_key] = dict(spec.defaults)
    return dict(spec.defaults)


def candidates(spec, key: tuple):
    """Non-default configs in cost-model order (cheapest estimate first)."""
    from ...cost_model import CostModel

    names = sorted(spec.space)
    cands = []
    for combo in itertools.product(*(spec.space[n] for n in names)):
        cfg = dict(spec.defaults)
        cfg.update(zip(names, combo))
        if cfg == dict(spec.defaults):
            continue
        if spec.valid is not None and not spec.valid(cfg, key):
            continue
        if cfg not in cands:
            cands.append(cfg)
    cm = CostModel()
    cands.sort(key=lambda c: cm.kernel_estimate(spec.name, key, c))
    return cands


def verify(out, ref) -> bool:
    """Accept a candidate's output only if it matches the default config's
    output: same tree/shape/dtype, same finite mask, values within a
    dtype-scaled tolerance (block-size changes reorder float accumulation
    by a few ulps; anything beyond tolerance is a broken config)."""
    import jax

    la = jax.tree_util.tree_leaves(out)
    lb = jax.tree_util.tree_leaves(ref)
    if len(la) != len(lb):
        return False
    for a, b in zip(la, lb):
        a = np.asarray(a)
        b = np.asarray(b)
        if a.shape != b.shape or a.dtype != b.dtype:
            return False
        af = a.astype(np.float64)
        bf = b.astype(np.float64)
        if not np.array_equal(np.isfinite(af), np.isfinite(bf)):
            return False
        tol = 2e-2 if a.dtype.itemsize <= 2 else 1e-5
        fin = np.isfinite(bf)
        if not np.allclose(af[fin], bf[fin], rtol=tol, atol=tol):
            return False
    return True


def _measure(make: Callable[[dict], Callable[[], Any]], config: dict,
             samples: int) -> Tuple[Optional[Any], Optional[float]]:
    """(output, median ms over ``samples`` runs); first call excluded — it
    compiles, and compile time must not rank steady-state configs."""
    import jax

    step = make(dict(config))
    out = step()
    jax.block_until_ready(out)
    times = []
    for _ in range(max(int(samples), 1)):
        t0 = time.monotonic()
        o = step()
        jax.block_until_ready(o)
        times.append(time.monotonic() - t0)
    times.sort()
    return out, times[len(times) // 2] * 1e3


def search(spec, key: tuple):
    """Returns ``(config, best_ms, default_ms, searched)``. ``searched`` is
    False when even the default config failed to run (nothing to persist)."""
    budget_s = float(flags.flag("FLAGS_kernel_tune_budget_s", 20.0))
    samples = int(flags.flag("FLAGS_kernel_tune_samples", 5))
    deadline = time.monotonic() + budget_s
    make = spec.runner(key)
    counter_inc("kernel_tune_searches")
    from ...cost_model import CostModel

    cm = CostModel()
    with span("kernel_tune", kernel=spec.name) as sp:
        try:
            ref_out, default_ms = _measure(make, spec.defaults, samples)
        except Exception:
            # a broken runner degrades to the pinned defaults; it must never
            # take the call site down
            counter_inc("kernel_tune_candidate_errors")
            sp.set(result="default_failed")
            return dict(spec.defaults), None, None, False
        best_cfg, best_ms = dict(spec.defaults), default_ms
        # cost-model drift (PR 20): (analytic estimate, measured ms) per
        # config that actually ran — the model's job here is ORDERING the
        # visit sequence, so its drift sample is the discordant-pair
        # fraction between estimated and measured rankings
        measured = [(cm.kernel_estimate(spec.name, key, dict(spec.defaults)),
                     default_ms)]
        tried = 0
        for cfg in candidates(spec, key):
            if time.monotonic() >= deadline:
                counter_inc("kernel_tune_budget_stops")
                break
            tried += 1
            counter_inc("kernel_tune_candidates")
            try:
                out, ms = _measure(make, cfg, samples)
            except Exception:
                # an invalid config failing to trace/compile just
                # disqualifies it
                counter_inc("kernel_tune_candidate_errors")
                continue
            measured.append((cm.kernel_estimate(spec.name, key, cfg), ms))
            if not verify(out, ref_out):
                counter_inc("kernel_tune_verify_fails")
                continue
            if ms < best_ms:
                best_cfg, best_ms = dict(cfg), ms
        sp.set(candidates=tried, default_ms=default_ms, best_ms=best_ms,
               tuned=best_cfg != dict(spec.defaults))
        if len(measured) >= 2:
            disc = tot = 0
            for i in range(len(measured)):
                for j in range(i + 1, len(measured)):
                    (ei, mi), (ej, mj) = measured[i], measured[j]
                    if ei == ej or mi == mj:
                        continue
                    tot += 1
                    if (ei < ej) != (mi < mj):
                        disc += 1
            if tot:
                frac = disc / tot
                sp.set(cost_drift=round(frac, 6))
                try:
                    from ...serving import observe as _observe

                    _observe.drift_value(
                        "kernel_estimate", frac, pairs=tot,
                        measured=len(measured))
                except Exception:
                    # drift accounting must never take a tuning search down
                    pass
    return best_cfg, best_ms, default_ms, True
