"""Flash attention — Pallas TPU kernel.

Replaces the reference's fused attention CUDA kernels
(``paddle/fluid/operators/fused/fused_attention_op.cu``, ``fmha_ref.h``) with
a TPU-native blockwise kernel: Q blocks stream over K/V blocks held in VMEM,
softmax is accumulated online (running max + sum), the T×T score matrix never
reaches HBM. Forward stores the logsumexp so the backward recomputes
probabilities row-block-wise.

Layout: q, k, v are (B, T, H, D) paddle-convention; kernel operates on
(B*H, T, D). D must be ≤ 256 and a multiple of 8 for clean tiling; T must be
a multiple of the block size (the functional pads otherwise).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int, causal: bool, scale: float, t_kv: int):
    # q_ref: (1, BQ, D); k_ref/v_ref: (1, T, D); o_ref: (1, BQ, D); lse_ref: (1, BQ, 1)
    iq = pl.program_id(1)
    bq = q_ref.shape[1]
    d = q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32) * jnp.float32(scale)  # (BQ, D)

    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)

    n_kb = t_kv // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (BQ, BK)
        if causal:
            q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(_NEG_INF))
        m_blk = jnp.max(s, axis=1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    if causal and bq == block_k:
        # equal q/k blocks: q block iq attends k blocks 0..iq (no division —
        # in-kernel int64 promotion breaks the Mosaic lowering under x64)
        last_kb = jnp.minimum(iq + 1, n_kb)
    else:
        last_kb = n_kb
    m, l, acc = jax.lax.fori_loop(0, last_kb, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, jnp.float32(1e-30))
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0, :, 0] = m + jnp.log(l_safe)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    # q: (BH, T, D). Traced with x64 disabled: the framework enables x64
    # globally (paddle int64 semantics) but Mosaic has no i64/f64 lowering —
    # index maps and weak python scalars must stay 32-bit inside the kernel.
    with jax.enable_x64(False):
        return _flash_fwd_inner(q, k, v, causal, block_q, block_k, interpret)


def _flash_fwd_inner(q, k, v, causal, block_q, block_k, interpret):
    bh, t, d = q.shape
    t_kv = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    grid = (bh, t // block_q)
    kernel = functools.partial(
        _fwd_kernel, block_k=block_k, causal=causal, scale=scale, t_kv=t_kv
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t_kv, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t_kv, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return out


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, block_q, block_k, interpret, res, do):
    # Backward from saved lse: p = exp(q·kᵀ·scale − lse). Chunked over query
    # blocks (lax.map) so peak memory is BQ×T, not T×T.
    q, k, v, out, lse = res
    lse = lse[..., 0]  # (BH, T)
    bh, t, d = q.shape
    scale = 1.0 / math.sqrt(d)
    qf, kf, vf = q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    dof, of = do.astype(jnp.float32), out.astype(jnp.float32)
    delta = jnp.sum(dof * of, axis=-1)  # (BH, T)

    n_q = t // block_q
    q_c = qf.reshape(bh, n_q, block_q, d)
    do_c = dof.reshape(bh, n_q, block_q, d)
    lse_c = lse.reshape(bh, n_q, block_q)
    delta_c = delta.reshape(bh, n_q, block_q)

    q_pos_base = jnp.arange(block_q)
    k_pos = jnp.arange(t)

    def per_qblock(args):
        qb, dob, lseb, deltab, iq = args
        s = jnp.einsum("bqd,bkd->bqk", qb, kf) * scale
        if causal:
            qpos = iq * block_q + q_pos_base
            mask = qpos[None, :, None] >= k_pos[None, None, :]
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lseb[..., None])  # (BH, BQ, T)
        dv_b = jnp.einsum("bqk,bqd->bkd", p, dob)
        dp = jnp.einsum("bqd,bkd->bqk", dob, vf)
        ds = p * (dp - deltab[..., None]) * scale
        dq_b = jnp.einsum("bqk,bkd->bqd", ds, kf)
        dk_b = jnp.einsum("bqk,bqd->bkd", ds, qb)
        return dq_b, dk_b, dv_b

    dq_c, dk_parts, dv_parts = jax.lax.map(
        per_qblock,
        (
            jnp.moveaxis(q_c, 1, 0),
            jnp.moveaxis(do_c, 1, 0),
            jnp.moveaxis(lse_c, 1, 0),
            jnp.moveaxis(delta_c, 1, 0),
            jnp.arange(n_q),
        ),
    )
    dq = jnp.moveaxis(dq_c, 0, 1).reshape(bh, t, d).astype(q.dtype)
    dk = jnp.sum(dk_parts, axis=0).astype(k.dtype)
    dv = jnp.sum(dv_parts, axis=0).astype(v.dtype)
    return dq, dk, dv


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention_array(q, k, v, causal=False, block_q=128, block_k=128, interpret=None):
    """Pure-array flash attention. q,k,v: (B, T, H, D) → (B, T, H, D)."""
    if not _HAS_PALLAS:
        raise RuntimeError("pallas unavailable")
    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"
    b, t, h, d = q.shape
    t_kv = k.shape[1]
    block_q = min(block_q, t)
    block_k = min(block_k, t_kv)

    def to_bh(x):
        return jnp.swapaxes(x, 1, 2).reshape(b * h, x.shape[1], d)

    pad_q = (-t) % block_q
    pad_k = (-t_kv) % block_k
    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    if pad_q:
        qb = jnp.pad(qb, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kb = jnp.pad(kb, ((0, 0), (0, pad_k), (0, 0)))
        vb = jnp.pad(vb, ((0, 0), (0, pad_k), (0, 0)))
        if not causal:
            # padded keys must not attend: give them -inf via a key mask by
            # pushing k to a value that zeroes post-softmax contribution —
            # handled by causal masking when causal; for non-causal fall back
            raise ValueError("non-causal flash requires T_kv % block_k == 0")
    out = _flash(qb, kb, vb, causal, block_q, block_k, interpret)
    if pad_q:
        out = out[:, :t]
    return jnp.swapaxes(out.reshape(b, h, t, d), 1, 2)


def flash_attention_tpu(q, k, v, causal=False):
    """Tensor-level wrapper used by nn.functional.flash_attention."""
    from ...core.dispatch import eager_call

    return eager_call(
        "flash_attention",
        lambda qa, ka, va: flash_attention_array(qa, ka, va, causal=causal),
        [q, k, v],
    )
