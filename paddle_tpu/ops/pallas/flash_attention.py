"""Flash attention — Pallas TPU kernel.

Replaces the reference's fused attention CUDA kernels
(``paddle/fluid/operators/fused/fused_attention_op.cu``, ``fmha_ref.h``) with
a TPU-native blockwise kernel: Q blocks stream over K/V blocks held in VMEM,
softmax is accumulated online (running max + sum), the T×T score matrix never
reaches HBM. Forward stores the logsumexp so the backward recomputes
probabilities row-block-wise.

Layout: q, k, v are (B, T, H, D) paddle-convention; kernel operates on
(B*H, T, D). D must be ≤ 256 and a multiple of 8 for clean tiling; T must be
a multiple of the block size (the functional pads otherwise).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from ...core.compat import enable_x64

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

_NEG_INF = -1e30

# The framework pins jax_default_matmul_precision="highest" (fp32 parity for
# f32 tests); Mosaic rejects fp32 contract precision on bf16 operands, and the
# MXU's native mode is bf16×bf16→f32 anyway. For f32 inputs keep HIGHEST
# (true fp32 passes — the pre-rework accuracy); dtype is known at trace time.
def _prec(dtype):
    return (
        jax.lax.Precision.HIGHEST
        if dtype == jnp.float32
        else jax.lax.Precision.DEFAULT
    )


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_sc, l_sc, acc_sc, *, block_k: int, causal: bool, scale: float, n_kv: int, kv_len: int):
    # STREAMED K/V: grid is (BH, n_q, n_kv) with the kv dim innermost, so K/V
    # arrive one (1, BK, D) block at a time (Pallas double-buffers the fetch
    # under the previous block's compute) and VMEM never holds (T, D) — this
    # is what makes 32k+ sequences fit. Running max / sum / output accumulate
    # in VMEM scratch across the kv steps of one q block.
    # q_ref: (1, BQ, D); k_ref/v_ref: (1, BK, D); o_ref: (1, BQ, D);
    # lse_ref: (1, 1, BQ) — lse rides the LANE axis ((T, 1) single-lane VMEM
    # blocks crash Mosaic at T=8192; (1, T) tiles fine)
    iq = pl.program_id(1)
    ikv = pl.program_id(2)
    bq = q_ref.shape[1]
    _PREC = _prec(q_ref.dtype)

    @pl.when(ikv == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    # causal: block is live iff some q_pos >= some k_pos, i.e. the block's
    # max q_pos reaches its min k_pos. Dead blocks skip COMPUTE only — the
    # sweep still fetches them (affine index maps keep the DMA pipelined;
    # see _kv_index_map).
    live = ((iq + 1) * bq > ikv * block_k) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0]  # (BQ, D) — keep input dtype: MXU does bf16×bf16→f32
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        m, l = m_sc[:], l_sc[:]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=_PREC
        ) * jnp.float32(scale)  # (BQ, BK) f32 accum
        if causal or kv_len < n_kv * block_k:
            q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = ikv * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            valid = k_pos < kv_len  # zero-padded keys must not attend
            if causal:
                valid = valid & (q_pos >= k_pos)
            s = jnp.where(valid, s, jnp.float32(_NEG_INF))
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        m_sc[:] = m_new
        l_sc[:] = l * alpha + jnp.sum(p, axis=1)
        acc_sc[:] = acc_sc[:] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_PREC,
        )

    @pl.when(ikv == n_kv - 1)
    def _finalize():
        l_safe = jnp.maximum(l_sc[:], jnp.float32(1e-30))
        o_ref[0] = (acc_sc[:] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0, :] = m_sc[:] + jnp.log(l_safe)


def _kernel_x64_off(interpret):
    """Mosaic has no i64/f64 lowering, so the real-kernel trace runs with x64
    off. Interpret mode (CPU) handles 64-bit fine — and toggling x64 inside
    an outer x64 trace (jit/shard_map around the model) makes the
    interpreter's grid loops mix i32/i64 on jax<=0.4 — so leave it alone."""
    import contextlib

    return contextlib.nullcontext() if interpret else enable_x64(False)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret, kv_len):
    # q: (BH, T, D). Traced with x64 disabled: the framework enables x64
    # globally (paddle int64 semantics) but Mosaic has no i64/f64 lowering —
    # index maps and weak python scalars must stay 32-bit inside the kernel.
    with _kernel_x64_off(interpret):
        return _flash_fwd_inner(q, k, v, causal, block_q, block_k, interpret, kv_len)


def _kv_index_map():
    """K/V block index for grid step (b, iq, ikv) of the streamed kernels.

    Deliberately AFFINE (plain sweep) even for causal: clamping dead ikv to
    the last live block (to skip their fetch) makes the map non-affine, which
    disables Mosaic's pipelined double-buffering — measured 2.8x SLOWER at
    32k than sweeping every block and skipping only the compute (pl.when in
    the kernels). Dead-block DMA is cheap; a serialized pipeline is not."""
    return lambda b, iq, ikv: (b, ikv, 0)


# K/V (and the dkv pass's Q/dO) stay whole-T VMEM-resident up to this byte
# budget; beyond it the streamed-grid kernels take over (see kernel comments)
_RESIDENT_BYTES = 8 * 1024 * 1024


def _resident_ok(t_side: int, d: int, dtype) -> bool:
    return 2 * t_side * d * jnp.dtype(dtype).itemsize <= _RESIDENT_BYTES


# -- MULTI-ROW resident kernels (A/B: LOSES — kept behind a flag) ------------
# Hypothesis (round 5): per-program overhead at short T (each (b·h, q-block)
# program runs ~2 small (BQ,BK)·D matmuls) capped the kernel at ~27 TF/s,
# since the same matmul chain hits ~95 TF/s with 8 chunks per program at
# T=4096. These kernels batch ROWS (b·h pairs) per program to amortize it.
# MEASURED A/B at (B=8,H=16,T=1024,D=64) bf16, 24-layer chain, v5e:
#   single-row  fwd 1.118 ms/layer   fwd+bwd 1.998 ms/layer
#   rows=8/4    fwd 1.250 ms/layer   fwd+bwd 2.148 ms/layer   <- LOSES ~7%
#   (also tried: chunk-outer/rows-inner with one fori per program: 1.41-1.53;
#    static-unrolled row loop: 0.98; native (B,T,H·D) two-pass layout: 2.09)
# The per-program-overhead theory did not survive contact: the win at long T
# comes from fori steady-state, which row batching does not create. Flag kept
# so the A/B is reproducible.
_MULTI_ROW = False

def _pick_rows(bh: int, t: int, d: int, dtype, arrays: int, budget=10 * 1024 * 1024) -> int:
    """Rows per program: largest R | bh with `arrays` resident (T, D) buffers
    (double-buffered) under the VMEM budget."""
    es = jnp.dtype(dtype).itemsize
    for r in (8, 4, 2):
        if bh % r == 0 and arrays * r * t * d * es * 2 <= budget:
            return r
    return 1


def _fwd_kernel_multi(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int, causal: bool, scale: float, t_kv: int, kv_len: int, rows: int):
    # q/o: (R, BQ, D); k/v: (R, T, D); lse: (R, 1, BQ)
    iq = pl.program_id(1)
    bq = q_ref.shape[1]
    d = q_ref.shape[2]
    _PREC = _prec(q_ref.dtype)
    n_kb = t_kv // block_k
    if causal and bq == block_k:
        last_kb = jnp.minimum(iq + 1, n_kb)
    else:
        last_kb = n_kb

    def row(r, _):
        q = q_ref[r]  # (BQ, D)

        def body(kb, carry):
            m, l, acc = carry
            k_blk = k_ref[r, pl.ds(kb * block_k, block_k), :]
            v_blk = v_ref[r, pl.ds(kb * block_k, block_k), :]
            s = jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=_PREC
            ) * jnp.float32(scale)
            if causal or kv_len < t_kv:
                q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
                k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
                valid = k_pos < kv_len
                if causal:
                    valid = valid & (q_pos >= k_pos)
                s = jnp.where(valid, s, jnp.float32(_NEG_INF))
            m_new = jnp.maximum(m, jnp.max(s, axis=1))
            p = jnp.exp(s - m_new[:, None])
            alpha = jnp.exp(m - m_new)
            l = l * alpha + jnp.sum(p, axis=1)
            acc = acc * alpha[:, None] + jax.lax.dot_general(
                p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=_PREC,
            )
            return m_new, l, acc

        m, l, acc = jax.lax.fori_loop(
            0, last_kb, body,
            (jnp.full((bq,), _NEG_INF, jnp.float32), jnp.zeros((bq,), jnp.float32),
             jnp.zeros((bq, d), jnp.float32)),
        )
        l_safe = jnp.maximum(l, jnp.float32(1e-30))
        o_ref[r] = (acc / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[r, 0, :] = m + jnp.log(l_safe)
        return 0

    jax.lax.fori_loop(0, rows, row, 0)


def _dq_kernel_multi(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, block_k: int, causal: bool, scale: float, t_kv: int, kv_len: int, rows: int):
    # q/do/dq: (R, BQ, D); k/v: (R, T, D); lse/delta: (R, 1, BQ)
    iq = pl.program_id(1)
    bq = q_ref.shape[1]
    d = q_ref.shape[2]
    _PREC = _prec(q_ref.dtype)
    n_kb = t_kv // block_k
    if causal and bq == block_k:
        last_kb = jnp.minimum(iq + 1, n_kb)
    else:
        last_kb = n_kb

    def row(r, _):
        q = q_ref[r]
        do = do_ref[r]
        lse = lse_ref[r, 0, :]
        delta = delta_ref[r, 0, :]

        def body(kb, acc):
            k_blk = k_ref[r, pl.ds(kb * block_k, block_k), :]
            v_blk = v_ref[r, pl.ds(kb * block_k, block_k), :]
            s = jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=_PREC
            ) * jnp.float32(scale)
            if causal or kv_len < t_kv:
                q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
                k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
                valid = k_pos < kv_len
                if causal:
                    valid = valid & (q_pos >= k_pos)
                s = jnp.where(valid, s, jnp.float32(_NEG_INF))
            p = jnp.exp(s - lse[:, None])
            dp = jax.lax.dot_general(
                do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=_PREC
            )
            ds = p * (dp - delta[:, None])
            return acc + jax.lax.dot_general(
                ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=_PREC,
            )

        acc = jax.lax.fori_loop(0, last_kb, body, jnp.zeros((bq, d), jnp.float32))
        dq_ref[r] = (acc * jnp.float32(scale)).astype(dq_ref.dtype)
        return 0

    jax.lax.fori_loop(0, rows, row, 0)


def _dkv_kernel_multi(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *, block_q: int, causal: bool, scale: float, t_q: int, kv_len: int, rows: int):
    # k/v/dk/dv: (R, BK, D); q/do: (R, T, D); lse/delta: (R, 1, T)
    ik = pl.program_id(1)
    bk = k_ref.shape[1]
    d = k_ref.shape[2]
    _PREC = _prec(k_ref.dtype)
    n_qb = t_q // block_q
    first_qb = ik if (causal and bk == block_q) else 0

    def row(r, _):
        k_blk = k_ref[r]  # (BK, D)
        v_blk = v_ref[r]

        def body(qb, carry):
            dk, dv = carry
            qq = q_ref[r, pl.ds(qb * block_q, block_q), :]
            do = do_ref[r, pl.ds(qb * block_q, block_q), :]
            lse = lse_ref[r, 0, pl.ds(qb * block_q, block_q)]
            delta = delta_ref[r, 0, pl.ds(qb * block_q, block_q)]
            s = jax.lax.dot_general(
                qq, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=_PREC
            ) * jnp.float32(scale)
            q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
            k_pos = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
            valid = k_pos < kv_len
            if causal:
                valid = valid & (q_pos >= k_pos)
            s = jnp.where(valid, s, jnp.float32(_NEG_INF))
            p = jnp.exp(s - lse[:, None])
            dv = dv + jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=_PREC,
            )
            dp = jax.lax.dot_general(
                do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=_PREC
            )
            ds = p * (dp - delta[:, None]) * jnp.float32(scale)
            dk = dk + jax.lax.dot_general(
                ds.astype(qq.dtype), qq, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=_PREC,
            )
            return dk, dv

        dk, dv = jax.lax.fori_loop(
            first_qb, n_qb, body,
            (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32)),
        )
        dk_ref[r] = dk.astype(dk_ref.dtype)
        dv_ref[r] = dv.astype(dv_ref.dtype)
        return 0

    jax.lax.fori_loop(0, rows, row, 0)


def _flash_fwd_inner(q, k, v, causal, block_q, block_k, interpret, kv_len):
    bh, t, d = q.shape
    t_kv = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    n_kv = t_kv // block_k

    if _resident_ok(t_kv, d, k.dtype):
        rows = _pick_rows(bh, t_kv, d, k.dtype, arrays=2)  # K+V resident
        if _MULTI_ROW and rows > 1 and t == t_kv:
            out, lse = pl.pallas_call(
                functools.partial(
                    _fwd_kernel_multi, block_k=block_k, causal=causal,
                    scale=scale, t_kv=t_kv, kv_len=kv_len, rows=rows,
                ),
                grid=(bh // rows, t // block_q),
                in_specs=[
                    pl.BlockSpec((rows, block_q, d), lambda b, i: (b, i, 0)),
                    pl.BlockSpec((rows, t_kv, d), lambda b, i: (b, 0, 0)),
                    pl.BlockSpec((rows, t_kv, d), lambda b, i: (b, 0, 0)),
                ],
                out_specs=[
                    pl.BlockSpec((rows, block_q, d), lambda b, i: (b, i, 0)),
                    pl.BlockSpec((rows, 1, block_q), lambda b, i: (b, 0, i)),
                ],
                out_shape=[
                    jax.ShapeDtypeStruct((bh, t, d), q.dtype),
                    jax.ShapeDtypeStruct((bh, 1, t), jnp.float32),
                ],
                interpret=interpret,
            )(q, k, v)
            return out, lse
        out, lse = pl.pallas_call(
            functools.partial(
                _fwd_kernel_resident, block_k=block_k, causal=causal,
                scale=scale, t_kv=t_kv, kv_len=kv_len,
            ),
            grid=(bh, t // block_q),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, t_kv, d), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((1, t_kv, d), lambda b, i: (b, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, t, d), q.dtype),
                jax.ShapeDtypeStruct((bh, 1, t), jnp.float32),
            ],
            interpret=interpret,
        )(q, k, v)
        return out, lse

    grid = (bh, t // block_q, n_kv)
    kernel = functools.partial(
        _fwd_kernel, block_k=block_k, causal=causal, scale=scale, n_kv=n_kv,
        kv_len=kv_len,
    )
    kv_map = _kv_index_map()
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_k, d), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, t), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse




# -- RESIDENT-K/V kernels (short/medium sequences) ---------------------------
# Whole K/V (or Q/dO for the dkv pass) stays VMEM-resident across the block
# loop: fetched once per (batch*head) row and reused by every q block. For
# sequences that fit (the common <=8k training case) this beats the streamed
# grid by avoiding the per-q-block re-stream of the whole K/V prefix
# (measured 2.5x at 8k); the streamed kernels above exist for the lengths
# where (T, D) simply cannot sit in VMEM (32k+).

def _fwd_kernel_resident(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int, causal: bool, scale: float, t_kv: int, kv_len: int):
    # q_ref: (1, BQ, D); k_ref/v_ref: (1, T, D); o_ref: (1, BQ, D); lse_ref: (1, 1, BQ)
    # lse/delta ride the LANE axis: a (T, 1) single-lane VMEM block crashes
    # the Mosaic compiler at T=8192 (one f32 per 8x128 tile); (1, T) tiles fine
    iq = pl.program_id(1)
    bq = q_ref.shape[1]
    d = q_ref.shape[2]
    q = q_ref[0]  # (BQ, D) — keep input dtype: MXU does bf16×bf16→f32
    _PREC = _prec(q.dtype)

    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)

    n_kb = t_kv // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=_PREC
        ) * jnp.float32(scale)  # (BQ, BK) f32 accum
        if causal or kv_len < t_kv:
            q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            valid = k_pos < kv_len  # zero-padded keys must not attend
            if causal:
                valid = valid & (q_pos >= k_pos)
            s = jnp.where(valid, s, jnp.float32(_NEG_INF))
        m_blk = jnp.max(s, axis=1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_PREC,
        )
        return m_new, l_new, acc_new

    if causal and bq == block_k:
        # equal q/k blocks: q block iq attends k blocks 0..iq (no division —
        # in-kernel int64 promotion breaks the Mosaic lowering under x64)
        last_kb = jnp.minimum(iq + 1, n_kb)
    else:
        last_kb = n_kb
    m, l, acc = jax.lax.fori_loop(0, last_kb, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, jnp.float32(1e-30))
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0, 0, :] = m + jnp.log(l_safe)


def _dq_kernel_resident(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, block_k: int, causal: bool, scale: float, t_kv: int, kv_len: int):
    # q/do/dq: (1, BQ, D); k/v: (1, T, D); lse/delta: (1, 1, BQ)
    iq = pl.program_id(1)
    bq = q_ref.shape[1]
    q = q_ref[0]  # (BQ, D)
    _PREC = _prec(q.dtype)
    do = do_ref[0]
    lse = lse_ref[0, 0, :]
    delta = delta_ref[0, 0, :]
    n_kb = t_kv // block_k

    def body(kb, acc):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=_PREC
        ) * jnp.float32(scale)  # (BQ, BK)
        if causal or kv_len < t_kv:
            q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            valid = k_pos < kv_len  # zero-padded keys must not attend
            if causal:
                valid = valid & (q_pos >= k_pos)
            s = jnp.where(valid, s, jnp.float32(_NEG_INF))
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=_PREC
        )  # (BQ, BK)
        ds = p * (dp - delta[:, None])
        return acc + jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_PREC,
        )

    if causal and bq == block_k:
        last_kb = jnp.minimum(iq + 1, n_kb)
    else:
        last_kb = n_kb
    acc = jax.lax.fori_loop(0, last_kb, body, jnp.zeros((bq, q_ref.shape[2]), jnp.float32))
    dq_ref[0] = (acc * jnp.float32(scale)).astype(dq_ref.dtype)


def _dkv_kernel_resident(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *, block_q: int, causal: bool, scale: float, t_q: int, kv_len: int):
    # k/v/dk/dv: (1, BK, D); q/do: (1, T, D); lse/delta: (1, 1, T)
    ik = pl.program_id(1)
    bk = k_ref.shape[1]
    d = k_ref.shape[2]
    k_blk = k_ref[0]  # (BK, D)
    _PREC = _prec(k_blk.dtype)
    v_blk = v_ref[0]
    n_qb = t_q // block_q

    def body(qb, carry):
        dk, dv = carry
        qq = q_ref[0, pl.ds(qb * block_q, block_q), :]
        do = do_ref[0, pl.ds(qb * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(qb * block_q, block_q)]
        delta = delta_ref[0, 0, pl.ds(qb * block_q, block_q)]
        s = jax.lax.dot_general(
            qq, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=_PREC
        ) * jnp.float32(scale)  # (BQ, BK)
        q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
        k_pos = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
        valid = k_pos < kv_len  # zero-padded keys contribute nothing
        if causal:
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, jnp.float32(_NEG_INF))
        p = jnp.exp(s - lse[:, None])  # (BQ, BK)
        dv = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_PREC,
        )  # (BK, D)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=_PREC
        )  # (BQ, BK)
        ds = p * (dp - delta[:, None]) * jnp.float32(scale)
        dk = dk + jax.lax.dot_general(
            ds.astype(qq.dtype), qq, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_PREC,
        )  # (BK, D)
        return dk, dv

    if causal and bk == block_q:
        first_qb = ik  # q blocks strictly before this k block are fully masked
    else:
        first_qb = 0
    dk, dv = jax.lax.fori_loop(
        first_qb, n_qb, body,
        (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32)),
    )
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# -- NATIVE-LAYOUT (B, T, H·D) resident kernels -------------------------------
# The (B,T,H,D)→(B·H,T,D) swapaxes around the BH kernels are real per-layer
# HBM transposes in a model (each layer has its own k/v — XLA cannot hoist
# them the way a k/v-reusing microbenchmark lets it). These kernels read the
# contiguous (B, T, H·D) view (a FREE reshape of the paddle layout — exactly
# what the QKV projection emits) with `hp` heads per program so the lane
# width hp·D tiles the 128-lane axis (hp=2 for D=64). Softmax is two-pass
# against a VMEM score scratch: pass A writes score chunks and the true row
# max, pass B does exp exactly once — no per-chunk accumulator rescaling.

def _fwd_kernel_hd(q_ref, k_ref, v_ref, o_ref, lse_ref, s_sc, *, block_k: int, causal: bool, scale: float, t_kv: int, kv_len: int, d: int, hp: int):
    # q/o: (1, BQ, hp·D); k/v: (1, T, hp·D); lse: (1, 1, hp, BQ); s_sc: (BQ, T) f32
    iq = pl.program_id(2)
    bq = q_ref.shape[1]
    _PREC = _prec(q_ref.dtype)
    n_kb = t_kv // block_k
    if causal and bq == block_k:
        last_kb = jnp.minimum(iq + 1, n_kb)
    else:
        last_kb = n_kb

    for hi in range(hp):
        q = q_ref[0, :, hi * d:(hi + 1) * d]  # (BQ, D)

        def pass_a(kb, m, _q=q):
            k_blk = k_ref[0, pl.ds(kb * block_k, block_k), hi * d:(hi + 1) * d]
            s = jax.lax.dot_general(
                _q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=_PREC
            ) * jnp.float32(scale)  # (BQ, BK)
            if causal or kv_len < t_kv:
                q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
                k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
                valid = k_pos < kv_len
                if causal:
                    valid = valid & (q_pos >= k_pos)
                s = jnp.where(valid, s, jnp.float32(_NEG_INF))
            s_sc[:, pl.ds(kb * block_k, block_k)] = s
            return jnp.maximum(m, jnp.max(s, axis=1))

        m = jax.lax.fori_loop(0, last_kb, pass_a, jnp.full((bq,), _NEG_INF, jnp.float32))

        def pass_b(kb, carry):
            l, acc = carry
            v_blk = v_ref[0, pl.ds(kb * block_k, block_k), hi * d:(hi + 1) * d]
            p = jnp.exp(s_sc[:, pl.ds(kb * block_k, block_k)] - m[:, None])
            l = l + jnp.sum(p, axis=1)
            acc = acc + jax.lax.dot_general(
                p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=_PREC,
            )
            return l, acc

        l, acc = jax.lax.fori_loop(
            0, last_kb, pass_b,
            (jnp.zeros((bq,), jnp.float32), jnp.zeros((bq, d), jnp.float32)),
        )
        l_safe = jnp.maximum(l, jnp.float32(1e-30))
        o_ref[0, :, hi * d:(hi + 1) * d] = (acc / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0, hi, :] = m + jnp.log(l_safe)


def _dq_kernel_hd(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, block_k: int, causal: bool, scale: float, t_kv: int, kv_len: int, d: int, hp: int):
    # q/do/dq: (1, BQ, hp·D); k/v: (1, T, hp·D); lse/delta: (1, 1, hp, BQ)
    iq = pl.program_id(2)
    bq = q_ref.shape[1]
    _PREC = _prec(q_ref.dtype)
    n_kb = t_kv // block_k
    if causal and bq == block_k:
        last_kb = jnp.minimum(iq + 1, n_kb)
    else:
        last_kb = n_kb

    for hi in range(hp):
        q = q_ref[0, :, hi * d:(hi + 1) * d]
        do = do_ref[0, :, hi * d:(hi + 1) * d]
        lse = lse_ref[0, 0, hi, :]
        delta = delta_ref[0, 0, hi, :]

        def body(kb, acc, _q=q, _do=do, _lse=lse, _delta=delta):
            k_blk = k_ref[0, pl.ds(kb * block_k, block_k), hi * d:(hi + 1) * d]
            v_blk = v_ref[0, pl.ds(kb * block_k, block_k), hi * d:(hi + 1) * d]
            s = jax.lax.dot_general(
                _q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=_PREC
            ) * jnp.float32(scale)
            if causal or kv_len < t_kv:
                q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
                k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
                valid = k_pos < kv_len
                if causal:
                    valid = valid & (q_pos >= k_pos)
                s = jnp.where(valid, s, jnp.float32(_NEG_INF))
            p = jnp.exp(s - _lse[:, None])
            dp = jax.lax.dot_general(
                _do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=_PREC
            )
            ds = p * (dp - _delta[:, None])
            return acc + jax.lax.dot_general(
                ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=_PREC,
            )

        acc = jax.lax.fori_loop(0, last_kb, body, jnp.zeros((bq, d), jnp.float32))
        dq_ref[0, :, hi * d:(hi + 1) * d] = (acc * jnp.float32(scale)).astype(dq_ref.dtype)


def _dkv_kernel_hd(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *, block_q: int, causal: bool, scale: float, t_q: int, kv_len: int, d: int, hp: int):
    # k/v/dk/dv: (1, BK, hp·D); q/do: (1, T, hp·D); lse/delta: (1, 1, hp, T)
    ik = pl.program_id(2)
    bk = k_ref.shape[1]
    _PREC = _prec(k_ref.dtype)
    n_qb = t_q // block_q
    first_qb = ik if (causal and bk == block_q) else 0

    for hi in range(hp):
        k_blk = k_ref[0, :, hi * d:(hi + 1) * d]  # (BK, D)
        v_blk = v_ref[0, :, hi * d:(hi + 1) * d]

        def body(qb, carry, _k=k_blk, _v=v_blk):
            dk, dv = carry
            qq = q_ref[0, pl.ds(qb * block_q, block_q), hi * d:(hi + 1) * d]
            do = do_ref[0, pl.ds(qb * block_q, block_q), hi * d:(hi + 1) * d]
            lse = lse_ref[0, 0, hi, pl.ds(qb * block_q, block_q)]
            delta = delta_ref[0, 0, hi, pl.ds(qb * block_q, block_q)]
            s = jax.lax.dot_general(
                qq, _k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=_PREC
            ) * jnp.float32(scale)  # (BQ, BK)
            q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
            k_pos = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
            valid = k_pos < kv_len
            if causal:
                valid = valid & (q_pos >= k_pos)
            s = jnp.where(valid, s, jnp.float32(_NEG_INF))
            p = jnp.exp(s - lse[:, None])
            dv = dv + jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=_PREC,
            )
            dp = jax.lax.dot_general(
                do, _v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=_PREC
            )
            ds = p * (dp - delta[:, None]) * jnp.float32(scale)
            dk = dk + jax.lax.dot_general(
                ds.astype(qq.dtype), qq, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=_PREC,
            )
            return dk, dv

        dk, dv = jax.lax.fori_loop(
            first_qb, n_qb, body,
            (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32)),
        )
        dk_ref[0, :, hi * d:(hi + 1) * d] = dk.astype(dk_ref.dtype)
        dv_ref[0, :, hi * d:(hi + 1) * d] = dv.astype(dv_ref.dtype)


def _flash_hd_fwd_inner(q, k, v, causal, block_q, block_k, interpret, kv_len, d, hp):
    b, t, hd = q.shape
    t_kv = k.shape[1]
    g = hd // (hp * d)
    w = hp * d
    scale = 1.0 / math.sqrt(d)
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel_hd, block_k=block_k, causal=causal, scale=scale,
            t_kv=t_kv, kv_len=kv_len, d=d, hp=hp,
        ),
        grid=(b, g, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, w), lambda bb, gg, i: (bb, i, gg)),
            pl.BlockSpec((1, t_kv, w), lambda bb, gg, i: (bb, 0, gg)),
            pl.BlockSpec((1, t_kv, w), lambda bb, gg, i: (bb, 0, gg)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, w), lambda bb, gg, i: (bb, i, gg)),
            pl.BlockSpec((1, 1, hp, block_q), lambda bb, gg, i: (bb, gg, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, t, hd), q.dtype),
            jax.ShapeDtypeStruct((b, g, hp, t), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_q, t_kv), jnp.float32)],
        interpret=interpret,
    )(q, k, v)
    return out, lse


def _flash_hd_bwd_inner(q, k, v, out, lse, do, causal, block_q, block_k, interpret, kv_len, d, hp):
    b, t, hd = q.shape
    t_kv = k.shape[1]
    h = hd // d
    g = h // hp
    w = hp * d
    scale = 1.0 / math.sqrt(d)
    # delta_i = dO_i · O_i per head, laid out (B, G, hp, T): rows on lanes
    delta = jnp.transpose(
        jnp.sum(
            (do.astype(jnp.float32) * out.astype(jnp.float32)).reshape(b, t, g, hp, d),
            axis=-1,
        ),
        (0, 2, 3, 1),
    )
    dq = pl.pallas_call(
        functools.partial(_dq_kernel_hd, block_k=block_k, causal=causal, scale=scale, t_kv=t_kv, kv_len=kv_len, d=d, hp=hp),
        grid=(b, g, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, w), lambda bb, gg, i: (bb, i, gg)),
            pl.BlockSpec((1, t_kv, w), lambda bb, gg, i: (bb, 0, gg)),
            pl.BlockSpec((1, t_kv, w), lambda bb, gg, i: (bb, 0, gg)),
            pl.BlockSpec((1, block_q, w), lambda bb, gg, i: (bb, i, gg)),
            pl.BlockSpec((1, 1, hp, block_q), lambda bb, gg, i: (bb, gg, 0, i)),
            pl.BlockSpec((1, 1, hp, block_q), lambda bb, gg, i: (bb, gg, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, w), lambda bb, gg, i: (bb, i, gg)),
        out_shape=jax.ShapeDtypeStruct((b, t, hd), q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel_hd, block_q=block_q, causal=causal, scale=scale, t_q=t, kv_len=kv_len, d=d, hp=hp),
        grid=(b, g, t_kv // block_k),
        in_specs=[
            pl.BlockSpec((1, block_k, w), lambda bb, gg, j: (bb, j, gg)),
            pl.BlockSpec((1, block_k, w), lambda bb, gg, j: (bb, j, gg)),
            pl.BlockSpec((1, t, w), lambda bb, gg, j: (bb, 0, gg)),
            pl.BlockSpec((1, t, w), lambda bb, gg, j: (bb, 0, gg)),
            pl.BlockSpec((1, 1, hp, t), lambda bb, gg, j: (bb, gg, 0, 0)),
            pl.BlockSpec((1, 1, hp, t), lambda bb, gg, j: (bb, gg, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, w), lambda bb, gg, j: (bb, j, gg)),
            pl.BlockSpec((1, block_k, w), lambda bb, gg, j: (bb, j, gg)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, t_kv, hd), k.dtype),
            jax.ShapeDtypeStruct((b, t_kv, hd), v.dtype),
        ],
        interpret=interpret,
    )(k, v, q, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_hd(q, k, v, causal, block_q, block_k, interpret, kv_len, d, hp):
    with _kernel_x64_off(interpret):
        out, _ = _flash_hd_fwd_inner(q, k, v, causal, block_q, block_k, interpret, kv_len, d, hp)
    return out


def _flash_hd_vjp_fwd(q, k, v, causal, block_q, block_k, interpret, kv_len, d, hp):
    with _kernel_x64_off(interpret):
        out, lse = _flash_hd_fwd_inner(q, k, v, causal, block_q, block_k, interpret, kv_len, d, hp)
    return out, (q, k, v, out, lse)


def _flash_hd_vjp_bwd(causal, block_q, block_k, interpret, kv_len, d, hp, res, do):
    q, k, v, out, lse = res
    with _kernel_x64_off(interpret):
        return _flash_hd_bwd_inner(q, k, v, out, lse, do, causal, block_q, block_k, interpret, kv_len, d, hp)


_flash_hd.defvjp(_flash_hd_vjp_fwd, _flash_hd_vjp_bwd)


def _hd_heads_per_program(h: int, d: int):
    """Heads per program so the lane width hp·D tiles 128 lanes; None if the
    native-layout path can't tile this head shape."""
    if d % 128 == 0:
        return 1
    if 128 % d == 0 and h % (128 // d) == 0:
        return 128 // d
    return None


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, block_q, block_k, interpret, kv_len):
    out, _ = _flash_fwd(q, k, v, causal, block_q, block_k, interpret, kv_len)
    return out


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k, interpret, kv_len):
    out, lse = _flash_fwd(q, k, v, causal, block_q, block_k, interpret, kv_len)
    return out, (q, k, v, out, lse)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, acc_sc, *, block_k: int, causal: bool, scale: float, n_kv: int, kv_len: int):
    # STREAMED K/V, grid (BH, n_q, n_kv): q/do/dq: (1, BQ, D);
    # k/v: (1, BK, D); lse/delta: (1, 1, BQ); dq accumulates in scratch.
    iq = pl.program_id(1)
    ikv = pl.program_id(2)
    bq = q_ref.shape[1]
    _PREC = _prec(q_ref.dtype)

    @pl.when(ikv == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)

    live = ((iq + 1) * bq > ikv * block_k) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0]  # (BQ, D)
        do = do_ref[0]
        lse = lse_ref[0, 0, :]
        delta = delta_ref[0, 0, :]
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=_PREC
        ) * jnp.float32(scale)  # (BQ, BK)
        if causal or kv_len < n_kv * block_k:
            q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = ikv * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            valid = k_pos < kv_len  # zero-padded keys must not attend
            if causal:
                valid = valid & (q_pos >= k_pos)
            s = jnp.where(valid, s, jnp.float32(_NEG_INF))
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=_PREC
        )  # (BQ, BK)
        ds = p * (dp - delta[:, None])
        acc_sc[:] = acc_sc[:] + jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_PREC,
        )

    @pl.when(ikv == n_kv - 1)
    def _finalize():
        dq_ref[0] = (acc_sc[:] * jnp.float32(scale)).astype(dq_ref.dtype)


def _dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_sc, dv_sc, *, block_q: int, causal: bool, scale: float, n_q: int, kv_len: int):
    # STREAMED Q/dO, grid (BH, n_kv, n_q): k/v/dk/dv: (1, BK, D);
    # q/do: (1, BQ, D); lse/delta: (1, 1, BQ); dk/dv accumulate in scratch.
    ik = pl.program_id(1)
    iqb = pl.program_id(2)
    bk = k_ref.shape[1]
    _PREC = _prec(k_ref.dtype)

    @pl.when(iqb == 0)
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    live = ((iqb + 1) * block_q > ik * bk) if causal else True

    @pl.when(live)
    def _compute():
        k_blk = k_ref[0]  # (BK, D)
        v_blk = v_ref[0]
        qq = q_ref[0]  # (BQ, D)
        do = do_ref[0]
        lse = lse_ref[0, 0, :]
        delta = delta_ref[0, 0, :]
        s = jax.lax.dot_general(
            qq, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=_PREC
        ) * jnp.float32(scale)  # (BQ, BK)
        q_pos = iqb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
        k_pos = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
        valid = k_pos < kv_len  # zero-padded keys contribute nothing
        if causal:
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, jnp.float32(_NEG_INF))
        p = jnp.exp(s - lse[:, None])  # (BQ, BK)
        dv_sc[:] = dv_sc[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_PREC,
        )  # (BK, D)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=_PREC
        )  # (BQ, BK)
        ds = p * (dp - delta[:, None]) * jnp.float32(scale)
        dk_sc[:] = dk_sc[:] + jax.lax.dot_general(
            ds.astype(qq.dtype), qq, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_PREC,
        )  # (BK, D)

    @pl.when(iqb == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[:].astype(dv_ref.dtype)


def _q_index_map(lane: bool = False):
    """Q/dO (lane=False) or lse/delta (lane=True: the block rides the lane
    axis) index for grid step (b, ik, iqb) of the dkv pass. Affine for the
    same pipelining reason as _kv_index_map; dead blocks skip compute only."""

    def imap(b, ik, iqb):
        return (b, 0, iqb) if lane else (b, iqb, 0)

    return imap


# A/B: MERGED backward LOSES — kept behind _MERGED_BWD for reproducibility.
# Measured at (B=2,H=16,T=8192,D=64) bf16, 24-layer chain, v5e:
#   two-kernel bwd (dq + dkv): fwd+bwd 12.64 ms/layer
#   merged single-sweep bwd:   fwd+bwd 16.94 ms/layer   <- LOSES 34%
# The saved score/dp recompute (2 of 7 matmuls) is outweighed by the
# per-iteration read-modify-write of the persistent (T, D) f32 dq scratch.
_MERGED_BWD = False


def _dfused_kernel_resident(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dq_ref, dq_sc, *, block_q: int, causal: bool, scale: float, t_q: int, kv_len: int, n_kv: int):
    # MERGED backward: one sweep computes dq, dk, dv — the separate dq pass's
    # score and dp recomputes (2 of the 7 backward matmuls, plus one of the
    # two exp passes) disappear. Grid (BH, n_kv): dk/dv are per-block
    # outputs; dq accumulates in a PERSISTENT f32 VMEM scratch across the
    # consecutive ik steps of one row and is written once at ik == n_kv-1
    # (the dq output block is the full (1, T, D) row, revisited across ik).
    # k/v/dk/dv: (1, BK, D); q/do: (1, T, D); lse/delta: (1, 1, T);
    # dq: (1, T, D); dq_sc: (T, D) f32.
    ik = pl.program_id(1)
    bk = k_ref.shape[1]
    d = k_ref.shape[2]
    k_blk = k_ref[0]  # (BK, D)
    _PREC = _prec(k_blk.dtype)
    v_blk = v_ref[0]
    n_qb = t_q // block_q

    @pl.when(ik == 0)
    def _init():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    def body(qb, carry):
        dk, dv = carry
        qq = q_ref[0, pl.ds(qb * block_q, block_q), :]
        do = do_ref[0, pl.ds(qb * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(qb * block_q, block_q)]
        delta = delta_ref[0, 0, pl.ds(qb * block_q, block_q)]
        s = jax.lax.dot_general(
            qq, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=_PREC
        ) * jnp.float32(scale)  # (BQ, BK)
        q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
        k_pos = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
        valid = k_pos < kv_len
        if causal:
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, jnp.float32(_NEG_INF))
        p = jnp.exp(s - lse[:, None])  # (BQ, BK)
        dv = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_PREC,
        )  # (BK, D)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=_PREC
        )  # (BQ, BK)
        ds = p * (dp - delta[:, None])  # unscaled; scale folded at the writes
        dsb = ds.astype(qq.dtype)
        dk = dk + jax.lax.dot_general(
            dsb, qq, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_PREC,
        )  # (BK, D)
        dq_sc[pl.ds(qb * block_q, block_q), :] = (
            dq_sc[pl.ds(qb * block_q, block_q), :]
            + jax.lax.dot_general(
                dsb, k_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=_PREC,
            )
        )
        return dk, dv

    first_qb = ik if (causal and bk == block_q) else 0
    dk, dv = jax.lax.fori_loop(
        first_qb, n_qb, body,
        (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32)),
    )
    dk_ref[0] = (dk * jnp.float32(scale)).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)

    @pl.when(ik == n_kv - 1)
    def _finalize():
        dq_ref[0] = (dq_sc[:] * jnp.float32(scale)).astype(dq_ref.dtype)


def _flash_bwd_inner(q, k, v, out, lse, do, causal, block_q, block_k, interpret, kv_len):
    bh, t, d = q.shape
    t_kv = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    n_kv = t_kv // block_k
    n_q = t // block_q
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)[:, None, :]  # (BH, 1, T)

    if _resident_ok(max(t, t_kv), d, q.dtype):
        # merged single-sweep backward: needs q/do resident + a (T, D) f32
        # dq accumulator scratch + k/v blocks; square self-attention only
        # (causal block skip + the dq row write assume t == t_kv)
        if (_MERGED_BWD and t == t_kv and block_q == block_k
                and t * d * 4 <= 4 * 1024 * 1024):
            dk, dv, dq = pl.pallas_call(
                functools.partial(
                    _dfused_kernel_resident, block_q=block_q, causal=causal,
                    scale=scale, t_q=t, kv_len=kv_len, n_kv=n_kv,
                ),
                grid=(bh, n_kv),
                in_specs=[
                    pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
                    pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
                    pl.BlockSpec((1, t, d), lambda b, j: (b, 0, 0)),
                    pl.BlockSpec((1, t, d), lambda b, j: (b, 0, 0)),
                    pl.BlockSpec((1, 1, t), lambda b, j: (b, 0, 0)),
                    pl.BlockSpec((1, 1, t), lambda b, j: (b, 0, 0)),
                ],
                out_specs=[
                    pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
                    pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
                    pl.BlockSpec((1, t, d), lambda b, j: (b, 0, 0)),
                ],
                out_shape=[
                    jax.ShapeDtypeStruct((bh, t_kv, d), k.dtype),
                    jax.ShapeDtypeStruct((bh, t_kv, d), v.dtype),
                    jax.ShapeDtypeStruct((bh, t, d), q.dtype),
                ],
                scratch_shapes=[pltpu.VMEM((t, d), jnp.float32)],
                interpret=interpret,
            )(k, v, q, do, lse, delta)
            return dq, dk, dv
        # Both bwd kernels stream 4 (T,D)-class operands + 2 lse rows and
        # carry several live (BQ,BK) f32 temporaries, so they get a tighter
        # row cap than the fwd: rows=8 measured 20 KB over the 16 MB
        # scoped-vmem limit at T=1024/D=64; rows=4 fits.
        rows = 1
        if _MULTI_ROW:
            rows = _pick_rows(bh, max(t, t_kv), d, q.dtype, arrays=2)
            while rows > 4:  # bwd hard cap: 8 rows = 16.02M scoped vmem (OOM)
                rows //= 2
        if _MULTI_ROW and rows > 1 and t == t_kv:
            dq = pl.pallas_call(
                functools.partial(_dq_kernel_multi, block_k=block_k, causal=causal, scale=scale, t_kv=t_kv, kv_len=kv_len, rows=rows),
                grid=(bh // rows, n_q),
                in_specs=[
                    pl.BlockSpec((rows, block_q, d), lambda b, i: (b, i, 0)),
                    pl.BlockSpec((rows, t_kv, d), lambda b, i: (b, 0, 0)),
                    pl.BlockSpec((rows, t_kv, d), lambda b, i: (b, 0, 0)),
                    pl.BlockSpec((rows, block_q, d), lambda b, i: (b, i, 0)),
                    pl.BlockSpec((rows, 1, block_q), lambda b, i: (b, 0, i)),
                    pl.BlockSpec((rows, 1, block_q), lambda b, i: (b, 0, i)),
                ],
                out_specs=pl.BlockSpec((rows, block_q, d), lambda b, i: (b, i, 0)),
                out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
                interpret=interpret,
            )(q, k, v, do, lse, delta)
            dk, dv = pl.pallas_call(
                functools.partial(_dkv_kernel_multi, block_q=block_q, causal=causal, scale=scale, t_q=t, kv_len=kv_len, rows=rows),
                grid=(bh // rows, n_kv),
                in_specs=[
                    pl.BlockSpec((rows, block_k, d), lambda b, j: (b, j, 0)),
                    pl.BlockSpec((rows, block_k, d), lambda b, j: (b, j, 0)),
                    pl.BlockSpec((rows, t, d), lambda b, j: (b, 0, 0)),
                    pl.BlockSpec((rows, t, d), lambda b, j: (b, 0, 0)),
                    pl.BlockSpec((rows, 1, t), lambda b, j: (b, 0, 0)),
                    pl.BlockSpec((rows, 1, t), lambda b, j: (b, 0, 0)),
                ],
                out_specs=[
                    pl.BlockSpec((rows, block_k, d), lambda b, j: (b, j, 0)),
                    pl.BlockSpec((rows, block_k, d), lambda b, j: (b, j, 0)),
                ],
                out_shape=[
                    jax.ShapeDtypeStruct((bh, t_kv, d), k.dtype),
                    jax.ShapeDtypeStruct((bh, t_kv, d), v.dtype),
                ],
                interpret=interpret,
            )(k, v, q, do, lse, delta)
            return dq, dk, dv
        dq = pl.pallas_call(
            functools.partial(_dq_kernel_resident, block_k=block_k, causal=causal, scale=scale, t_kv=t_kv, kv_len=kv_len),
            grid=(bh, n_q),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, t_kv, d), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((1, t_kv, d), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
                pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
            ],
            out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            interpret=interpret,
        )(q, k, v, do, lse, delta)

        dk, dv = pl.pallas_call(
            functools.partial(_dkv_kernel_resident, block_q=block_q, causal=causal, scale=scale, t_q=t, kv_len=kv_len),
            grid=(bh, n_kv),
            in_specs=[
                pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
                pl.BlockSpec((1, t, d), lambda b, j: (b, 0, 0)),
                pl.BlockSpec((1, t, d), lambda b, j: (b, 0, 0)),
                pl.BlockSpec((1, 1, t), lambda b, j: (b, 0, 0)),
                pl.BlockSpec((1, 1, t), lambda b, j: (b, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, t_kv, d), k.dtype),
                jax.ShapeDtypeStruct((bh, t_kv, d), v.dtype),
            ],
            interpret=interpret,
        )(k, v, q, do, lse, delta)
        return dq, dk, dv

    kv_map = _kv_index_map()
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_k=block_k, causal=causal, scale=scale, n_kv=n_kv, kv_len=kv_len),
        grid=(bh, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    q_map = _q_index_map()
    q_map_lane = _q_index_map(lane=True)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q=block_q, causal=causal, scale=scale, n_q=n_q, kv_len=kv_len),
        grid=(bh, n_kv, n_q),
        in_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, 1, block_q), q_map_lane),
            pl.BlockSpec((1, 1, block_q), q_map_lane),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_kv, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t_kv, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(k, v, q, do, lse, delta)
    return dq, dk, dv


def _flash_vjp_bwd(causal, block_q, block_k, interpret, kv_len, res, do):
    # Pallas backward: recompute p = exp(q·kᵀ·scale − lse) block-wise in VMEM.
    # Two kernels — dq streams K/V blocks per query block; dk/dv streams Q/dO
    # blocks per key block (causal lower bound skips fully-masked blocks).
    # No (BQ,T) score block or (n_q,BH,T,D) intermediate ever reaches HBM.
    q, k, v, out, lse = res
    with _kernel_x64_off(interpret):
        return _flash_bwd_inner(q, k, v, out, lse, do, causal, block_q, block_k, interpret, kv_len)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _pick_block(limit, t):
    # Largest power-of-two block ≤ limit that divides t — avoids zero-padding
    # (a 512-block on T=640 would pad ~60% wasted FLOPs). 512 measured fastest
    # on v5e (vs 128: 1.55× at T=1024, 3.2× at T=8192).
    for b in (limit, 256, 128):
        if b <= limit and t % b == 0 and b % 8 == 0:
            return b
    return 128  # no aligned divisor: 128 block + zero-padding


def flash_attention_array(q, k, v, causal=False, block_q=None, block_k=None, interpret=None):
    """Pure-array flash attention. q,k,v: (B, T, H, D) → (B, T, H, D).

    ``block_q``/``block_k`` default to the kernel registry's resolved config
    (``ops/kernels``: the pinned 512/512 defaults with autotune off, a tuned
    winner otherwise); explicit values bypass the registry. Either way the
    requested blocks flow through ``_pick_block``'s divisibility degrade
    exactly as before the registry existed."""
    if not _HAS_PALLAS:
        raise RuntimeError("pallas unavailable")
    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"
    # mixed q/k/v dtypes (e.g. one operand silently upcast to f32 upstream)
    # would pair HIGHEST precision with bf16 operands inside the kernel,
    # which Mosaic rejects — unify on q's dtype
    if k.dtype != q.dtype:
        k = k.astype(q.dtype)
    if v.dtype != q.dtype:
        v = v.astype(q.dtype)
    b, t, h, d = q.shape
    t_kv = k.shape[1]
    if block_q is None or block_k is None:
        from ..kernels import flash_attention_key, resolve_config

        cfg = resolve_config(
            "flash_attention",
            flash_attention_key(b, h, t, t_kv, d, q.dtype, causal))
        block_q = int(cfg["block_q"]) if block_q is None else block_q
        block_k = int(cfg["block_k"]) if block_k is None else block_k
    block_q = _pick_block(min(block_q, t), t)
    block_k = _pick_block(min(block_k, t_kv), t_kv)

    # native-layout path: no (B,T,H,D)→(BH,T,D) transpose round-trips (real
    # per-layer HBM passes in a model); scores scratch caps VMEM
    hp = _hd_heads_per_program(h, d)
    if (
        hp is not None
        and t == t_kv  # dkv holds full-length-t q/do resident: square only
        and t % block_q == 0 and t_kv % block_k == 0
        and _resident_ok(t_kv, hp * d, k.dtype)
        and block_q * t_kv * 4 <= 4 * 1024 * 1024
    ):
        out = _flash_hd(
            q.reshape(b, t, h * d), k.reshape(b, t_kv, h * d),
            v.reshape(b, t_kv, h * d), causal, block_q, block_k, interpret,
            t_kv, d, hp,
        )
        return out.reshape(b, t, h, d)

    def to_bh(x):
        return jnp.swapaxes(x, 1, 2).reshape(b * h, x.shape[1], d)

    pad_q = (-t) % block_q
    pad_k = (-t_kv) % block_k
    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    if pad_q:
        qb = jnp.pad(qb, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        # padded keys are masked inside the kernels via kv_len (k_pos >=
        # kv_len contributes -inf scores), so any T_kv works non-causally too
        kb = jnp.pad(kb, ((0, 0), (0, pad_k), (0, 0)))
        vb = jnp.pad(vb, ((0, 0), (0, pad_k), (0, 0)))
    out = _flash(qb, kb, vb, causal, block_q, block_k, interpret, t_kv)
    if pad_q:
        out = out[:, :t]
    return jnp.swapaxes(out.reshape(b, h, t, d), 1, 2)


def flash_attention_tpu(q, k, v, causal=False):
    """Tensor-level wrapper used by nn.functional.flash_attention."""
    from ...core.dispatch import eager_call

    return eager_call(
        "flash_attention",
        lambda qa, ka, va: flash_attention_array(qa, ka, va, causal=causal),
        [q, k, v],
    )
