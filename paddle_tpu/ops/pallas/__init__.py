"""Pallas TPU kernels (hot-op fast paths). Imported lazily; each kernel file
guards on TPU availability and falls back to the XLA formulation."""
