"""Op library + Tensor method attachment.

The reference attaches tensor methods by monkey-patching VarBase
(``python/paddle/fluid/dygraph/varbase_patch_methods.py``) and the math-op
dunder set (``python/paddle/fluid/dygraph/math_op_patch.py``); we do the same
onto our eager Tensor so ``x + y``, ``x.sum()``, ``x[i]`` behave identically.
"""
from __future__ import annotations

import numpy as np

from . import creation, linalg, manipulation, math
from ..core.tensor import Tensor


def _attach(name, fn):
    setattr(Tensor, name, fn)


def monkey_patch_tensor():
    m, mp, li, cr = math, manipulation, linalg, creation

    # operators
    _attach("__add__", lambda self, o: m.add(self, o))
    _attach("__radd__", lambda self, o: m.add(self, o))
    _attach("__sub__", lambda self, o: m.subtract(self, o))
    _attach("__rsub__", lambda self, o: m.subtract(o if isinstance(o, Tensor) else creation.full_like(self, o), self))
    _attach("__mul__", lambda self, o: m.multiply(self, o))
    _attach("__rmul__", lambda self, o: m.multiply(self, o))
    _attach("__truediv__", lambda self, o: m.divide(self, o))
    _attach(
        "__rtruediv__",
        lambda self, o: m.divide(o if isinstance(o, Tensor) else creation.full_like(self, o), self),
    )
    _attach("__floordiv__", lambda self, o: m.floor_divide(self, o))
    _attach("__mod__", lambda self, o: m.remainder(self, o))
    _attach("__pow__", lambda self, o: m.pow(self, o))
    _attach("__rpow__", lambda self, o: m.pow(creation.full_like(self, o), self))
    _attach("__neg__", lambda self: m.neg(self))
    _attach("__abs__", lambda self: m.abs(self))
    _attach("__matmul__", lambda self, o: m.matmul(self, o))
    _attach("__rmatmul__", lambda self, o: m.matmul(o, self))
    _attach("__eq__", lambda self, o: m.equal(self, o))
    _attach("__ne__", lambda self, o: m.not_equal(self, o))
    _attach("__lt__", lambda self, o: m.less_than(self, o))
    _attach("__le__", lambda self, o: m.less_equal(self, o))
    _attach("__gt__", lambda self, o: m.greater_than(self, o))
    _attach("__ge__", lambda self, o: m.greater_equal(self, o))
    _attach("__invert__", lambda self: m.logical_not(self))
    _attach("__and__", lambda self, o: m.bitwise_and(self, o))
    _attach("__or__", lambda self, o: m.bitwise_or(self, o))
    _attach("__xor__", lambda self, o: m.bitwise_xor(self, o))
    Tensor.__hash__ = lambda self: id(self)

    _attach("__getitem__", lambda self, item: mp.getitem(self, item))
    _attach("__setitem__", lambda self, item, v: mp.setitem(self, item, v))

    # math methods
    for name in (
        "add", "subtract", "multiply", "divide", "floor_divide", "remainder", "mod",
        "pow", "matmul", "mm", "dot", "inner", "outer", "bmm", "addmm", "kron",
        "exp", "log", "log2", "log10", "log1p", "expm1", "sqrt", "rsqrt", "abs",
        "sign", "floor", "ceil", "round", "trunc", "frac", "sin", "cos", "tan",
        "asin", "acos", "atan", "sinh", "cosh", "tanh", "asinh", "acosh", "atanh",
        "erf", "erfinv", "reciprocal", "square", "digamma", "lgamma", "sigmoid",
        "clip", "lerp", "maximum", "minimum", "fmax", "fmin", "atan2",
        "sum", "mean", "max", "min", "prod", "std", "var", "median", "nanmean",
        "nansum", "logsumexp", "argmax", "argmin", "cumsum", "cumprod", "all",
        "any", "isnan", "isinf", "isfinite", "equal", "not_equal", "greater_than",
        "greater_equal", "less_than", "less_equal", "logical_and", "logical_or",
        "logical_not", "logical_xor", "bitwise_and", "bitwise_or", "bitwise_xor",
        "bitwise_not", "allclose", "isclose", "equal_all", "cast", "scale",
        "trace", "diagonal", "dist", "neg", "heaviside",
    ):
        _attach(name, getattr(m, name))

    # manipulation methods
    for name in (
        "reshape", "reshape_", "transpose", "t", "split", "chunk", "squeeze",
        "unsqueeze", "flatten", "expand", "expand_as", "broadcast_to", "tile",
        "repeat_interleave", "flip", "roll", "gather", "gather_nd", "scatter",
        "scatter_nd_add", "index_select", "index_sample", "index_add",
        "masked_select", "masked_fill", "topk", "sort", "argsort", "unbind",
        "unique", "unique_consecutive", "nonzero", "searchsorted", "bincount",
        "take_along_axis", "put_along_axis", "moveaxis", "as_real", "as_complex",
        "real", "imag", "conj", "pad", "unstack",
    ):
        _attach(name, getattr(mp, name))

    # linalg methods
    for name in ("cholesky", "inverse", "norm", "matrix_power", "pinv", "solve"):
        _attach(name, getattr(li, name))

    # creation-style methods
    _attach("clone", lambda self: cr.clone(self))
    _attach("fill_", lambda self, v: self.set_value(np.full(self.shape, v, self.dtype)) or self)
    _attach("zero_", lambda self: self.set_value(np.zeros(self.shape, self.dtype)) or self)

    def _astype(self, dtype):
        return m.cast(self, dtype)

    _attach("astype", _astype)

    def _item_method(self, *args):
        return Tensor.item(self, *args)

    # iteration over first axis
    def _iter(self):
        for i in range(self.shape[0]):
            yield self[i]

    _attach("__iter__", _iter)


monkey_patch_tensor()
