"""Math / elementwise / reduction ops.

Parity surface: reference ``python/paddle/tensor/math.py`` + the C++/CUDA
elementwise kernels (``paddle/fluid/operators/elementwise/``), reduce ops
(``reduce_ops/``) and activation kernels — all jnp/XLA here, fused by the
compiler instead of hand-written grad kernels.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.lazy import concrete as _concrete

from ..core import dtype as dtypes
from ..core.tensor import Tensor
from ..core.dispatch import as_tensor, eager_call


def _scalarize(v):
    """True if v should be closed over as a python scalar (weak-typed)."""
    return isinstance(v, (int, float, bool)) and not isinstance(v, Tensor)


def _binary(op_name, jfn):
    def op(x, y, name=None):
        if _scalarize(y) and isinstance(x, Tensor):
            return eager_call(op_name, lambda a, s: jfn(a, s), [x], {"s": y})
        if _scalarize(x) and isinstance(y, Tensor):
            return eager_call(op_name, lambda b, s: jfn(s, b), [y], {"s": x})
        return eager_call(op_name, jfn, [as_tensor(x), as_tensor(y)])

    op.__name__ = op_name
    return op


def _rbinary(op_name, jfn):
    def op(y, x, name=None):  # reflected
        if _scalarize(x):
            return eager_call(op_name, lambda b, s: jfn(s, b), [as_tensor(y)], {"s": x})
        return eager_call(op_name, jfn, [as_tensor(x), as_tensor(y)])

    return op


def _unary(op_name, jfn, differentiable=True):
    def op(x, name=None):
        return eager_call(op_name, jfn, [as_tensor(x)], differentiable=differentiable)

    op.__name__ = op_name
    return op


# -- elementwise binary ------------------------------------------------------
add = _binary("add", jnp.add)
subtract = _binary("subtract", jnp.subtract)
multiply = _binary("multiply", jnp.multiply)
divide = _binary("divide", jnp.divide)
floor_divide = _binary("floor_divide", jnp.floor_divide)
remainder = _binary("remainder", jnp.remainder)
mod = remainder
floor_mod = remainder
maximum = _binary("maximum", jnp.maximum)
minimum = _binary("minimum", jnp.minimum)
fmax = _binary("fmax", jnp.fmax)
fmin = _binary("fmin", jnp.fmin)
atan2 = _binary("atan2", jnp.arctan2)
hypot = _binary("hypot", jnp.hypot)
heaviside = _binary("heaviside", jnp.heaviside)
copysign = _binary("copysign", jnp.copysign)
nextafter = _binary("nextafter", jnp.nextafter)
gcd = _binary("gcd", jnp.gcd)
lcm = _binary("lcm", jnp.lcm)
ldexp = _binary("ldexp", jnp.ldexp)


def pow(x, y, name=None):
    if _scalarize(y):
        return eager_call("pow", lambda a, s: jnp.power(a, s), [as_tensor(x)], {"s": y})
    return eager_call("pow", jnp.power, [as_tensor(x), as_tensor(y)])


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    x = as_tensor(x)

    def fn(a, scale, bias, bias_after_scale):
        if bias_after_scale:
            return a * scale + bias
        return (a + bias) * scale

    out = eager_call("scale", fn, [x], {"scale": float(scale), "bias": float(bias), "bias_after_scale": bias_after_scale})
    return out


# -- elementwise unary -------------------------------------------------------
exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", lambda a: jax.lax.rsqrt(a))
abs = _unary("abs", jnp.abs)
neg = _unary("neg", jnp.negative)
sign = _unary("sign", jnp.sign)
floor = _unary("floor", jnp.floor)
ceil = _unary("ceil", jnp.ceil)
round = _unary("round", jnp.round)
trunc = _unary("trunc", jnp.trunc)
frac = _unary("frac", lambda a: a - jnp.trunc(a))
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
reciprocal = _unary("reciprocal", jnp.reciprocal)
square = _unary("square", jnp.square)
digamma = _unary("digamma", jax.scipy.special.digamma)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
i0 = _unary("i0", jax.scipy.special.i0)
i1 = _unary("i1", jax.scipy.special.i1)
isnan = _unary("isnan", jnp.isnan, differentiable=False)
isinf = _unary("isinf", jnp.isinf, differentiable=False)
isfinite = _unary("isfinite", jnp.isfinite, differentiable=False)
logical_not = _unary("logical_not", jnp.logical_not, differentiable=False)
bitwise_not = _unary("bitwise_not", jnp.bitwise_not, differentiable=False)


def clip(x, min=None, max=None, name=None):
    x = as_tensor(x)
    mn = min.item() if isinstance(min, Tensor) else min
    mx = max.item() if isinstance(max, Tensor) else max
    return eager_call("clip", lambda a, mn, mx: jnp.clip(a, mn, mx), [x], {"mn": mn, "mx": mx})


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return eager_call("lerp", lambda a, b, w: a + w * (b - a), [as_tensor(x), as_tensor(y), weight])
    return eager_call("lerp", lambda a, b, w: a + w * (b - a), [as_tensor(x), as_tensor(y)], {"w": weight})


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return eager_call(
        "nan_to_num",
        lambda a, nan, posinf, neginf: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf),
        [as_tensor(x)],
        {"nan": nan, "posinf": posinf, "neginf": neginf},
    )


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return eager_call(
        "stanh", lambda a, sa, sb: sb * jnp.tanh(sa * a), [as_tensor(x)], {"sa": scale_a, "sb": scale_b}
    )


# -- comparison / logical (non-differentiable) -------------------------------
def _cmp(op_name, jfn):
    def op(x, y, name=None):
        if _scalarize(y):
            return eager_call(op_name, lambda a, s: jfn(a, s), [as_tensor(x)], {"s": y}, differentiable=False)
        return eager_call(op_name, jfn, [as_tensor(x), as_tensor(y)], differentiable=False)

    return op


equal = _cmp("equal", jnp.equal)
not_equal = _cmp("not_equal", jnp.not_equal)
greater_than = _cmp("greater_than", jnp.greater)
greater_equal = _cmp("greater_equal", jnp.greater_equal)
less_than = _cmp("less_than", jnp.less)
less_equal = _cmp("less_equal", jnp.less_equal)
logical_and = _cmp("logical_and", jnp.logical_and)
logical_or = _cmp("logical_or", jnp.logical_or)
logical_xor = _cmp("logical_xor", jnp.logical_xor)
bitwise_and = _cmp("bitwise_and", jnp.bitwise_and)
bitwise_or = _cmp("bitwise_or", jnp.bitwise_or)
bitwise_xor = _cmp("bitwise_xor", jnp.bitwise_xor)


def equal_all(x, y, name=None):
    x, y = as_tensor(x), as_tensor(y)
    return Tensor(jnp.array_equal(_concrete(x._data), _concrete(y._data)))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    x, y = as_tensor(x), as_tensor(y)
    return Tensor(jnp.allclose(_concrete(x._data), _concrete(y._data), rtol=rtol, atol=atol, equal_nan=equal_nan))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return eager_call(
        "isclose",
        lambda a, b, rtol, atol, equal_nan: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        [as_tensor(x), as_tensor(y)],
        {"rtol": rtol, "atol": atol, "equal_nan": equal_nan},
        differentiable=False,
    )


# -- reductions --------------------------------------------------------------
def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    if isinstance(axis, Tensor):
        return tuple(int(a) for a in axis.tolist())
    return int(axis)


def _reduce(op_name, jfn, differentiable=True):
    def op(x, axis=None, keepdim=False, name=None):
        x = as_tensor(x)
        return eager_call(
            op_name,
            lambda a, axis, keepdim: jfn(a, axis=axis, keepdims=keepdim),
            [x],
            {"axis": _norm_axis(axis), "keepdim": keepdim},
            differentiable=differentiable,
        )

    op.__name__ = op_name
    return op


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    x = as_tensor(x)
    dt = dtypes.convert_dtype(dtype) if dtype is not None else None
    if dt is None and (dtypes.is_integer(x.dtype) or x.dtype == np.dtype("bool")):
        dt = np.dtype("int64")

    def fn(a, axis, keepdim, dt):
        return jnp.sum(a, axis=axis, keepdims=keepdim, dtype=dt)

    return eager_call(
        "sum", fn, [x], {"axis": _norm_axis(axis), "keepdim": keepdim, "dt": dt}
    )


def mean(x, axis=None, keepdim=False, name=None):
    return _reduce("mean", jnp.mean)(x, axis, keepdim)


max = _reduce("max", jnp.max)
min = _reduce("min", jnp.min)
prod = _reduce("prod", jnp.prod)
amax = max
amin = min


def all(x, axis=None, keepdim=False, name=None):
    return _reduce("all", jnp.all, differentiable=False)(x, axis, keepdim)


def any(x, axis=None, keepdim=False, name=None):
    return _reduce("any", jnp.any, differentiable=False)(x, axis, keepdim)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = as_tensor(x)
    return eager_call(
        "std",
        lambda a, axis, ddof, keepdim: jnp.std(a, axis=axis, ddof=ddof, keepdims=keepdim),
        [x],
        {"axis": _norm_axis(axis), "ddof": 1 if unbiased else 0, "keepdim": keepdim},
    )


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = as_tensor(x)
    return eager_call(
        "var",
        lambda a, axis, ddof, keepdim: jnp.var(a, axis=axis, ddof=ddof, keepdims=keepdim),
        [x],
        {"axis": _norm_axis(axis), "ddof": 1 if unbiased else 0, "keepdim": keepdim},
    )


def median(x, axis=None, keepdim=False, name=None):
    return eager_call(
        "median",
        lambda a, axis, keepdim: jnp.median(a, axis=axis, keepdims=keepdim),
        [as_tensor(x)],
        {"axis": _norm_axis(axis), "keepdim": keepdim},
    )


def quantile(x, q, axis=None, keepdim=False, name=None):
    return eager_call(
        "quantile",
        lambda a, q, axis, keepdim: jnp.quantile(a, jnp.asarray(q), axis=axis, keepdims=keepdim),
        [as_tensor(x)],
        {"q": q, "axis": _norm_axis(axis), "keepdim": keepdim},
    )


def nanmean(x, axis=None, keepdim=False, name=None):
    return eager_call(
        "nanmean",
        lambda a, axis, keepdim: jnp.nanmean(a, axis=axis, keepdims=keepdim),
        [as_tensor(x)],
        {"axis": _norm_axis(axis), "keepdim": keepdim},
    )


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return eager_call(
        "nansum",
        lambda a, axis, keepdim: jnp.nansum(a, axis=axis, keepdims=keepdim),
        [as_tensor(x)],
        {"axis": _norm_axis(axis), "keepdim": keepdim},
    )


def logsumexp(x, axis=None, keepdim=False, name=None):
    return eager_call(
        "logsumexp",
        lambda a, axis, keepdim: jax.scipy.special.logsumexp(a, axis=axis, keepdims=keepdim),
        [as_tensor(x)],
        {"axis": _norm_axis(axis), "keepdim": keepdim},
    )


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = as_tensor(x)

    def fn(a, axis, keepdim):
        r = jnp.argmax(a, axis=axis, keepdims=keepdim if axis is not None else False)
        return r.astype(np.int64)

    return eager_call("argmax", fn, [x], {"axis": _norm_axis(axis), "keepdim": keepdim}, differentiable=False)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = as_tensor(x)

    def fn(a, axis, keepdim):
        r = jnp.argmin(a, axis=axis, keepdims=keepdim if axis is not None else False)
        return r.astype(np.int64)

    return eager_call("argmin", fn, [x], {"axis": _norm_axis(axis), "keepdim": keepdim}, differentiable=False)


# -- scans -------------------------------------------------------------------
def cumsum(x, axis=None, dtype=None, name=None):
    x = as_tensor(x)

    def fn(a, axis):
        if axis is None:
            return jnp.cumsum(a.reshape(-1))
        return jnp.cumsum(a, axis=axis)

    return eager_call("cumsum", fn, [x], {"axis": _norm_axis(axis)})


def cumprod(x, dim=None, dtype=None, name=None):
    x = as_tensor(x)
    return eager_call("cumprod", lambda a, axis: jnp.cumprod(a, axis=axis), [x], {"axis": _norm_axis(dim)})


def _cum_minmax(x, axis, op):
    """Cumulative max/min with per-position argmax/argmin indices via one
    associative scan over (value, index) pairs — XLA log-depth scan."""
    x = as_tensor(x)
    flat = axis is None

    def fn(a, axis, flat, op):
        if flat:
            a = a.reshape(-1)
            axis = 0
        n = a.shape[axis]
        shape = [1] * a.ndim
        shape[axis] = n
        idx = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int64).reshape(shape), a.shape)

        def combine(c1, c2):
            v1, i1 = c1
            v2, i2 = c2
            take2 = (v2 > v1) if op == "max" else (v2 < v1)
            return jnp.where(take2, v2, v1), jnp.where(take2, i2, i1)

        vals, inds = jax.lax.associative_scan(combine, (a, idx), axis=axis)
        return vals, inds

    out = eager_call(
        f"cum{op}", fn, [x],
        {"axis": _norm_axis(axis) if not flat else None, "flat": flat, "op": op},
        nondiff_outputs=[1],
    )
    return out[0], out[1]


def cummax(x, axis=None, dtype="int64", name=None):
    return _cum_minmax(x, axis, "max")


def cummin(x, axis=None, dtype="int64", name=None):
    return _cum_minmax(x, axis, "min")


def logcumsumexp(x, axis=None, name=None):
    x = as_tensor(x)

    def fn(a, axis):
        if axis is None:
            a = a.reshape(-1)
            axis = 0
        return jax.lax.associative_scan(jnp.logaddexp, a, axis=axis)

    return eager_call("logcumsumexp", fn, [x], {"axis": _norm_axis(axis)})


# -- matmul family -----------------------------------------------------------
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    """Reference: matmul_v2 (paddle/fluid/operators/matmul_v2_op.cc) — lowered
    straight to the MXU via jnp.matmul/dot_general."""

    def fn(a, b, transpose_x, transpose_y):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)

    return eager_call(
        "matmul", fn, [as_tensor(x), as_tensor(y)],
        {"transpose_x": transpose_x, "transpose_y": transpose_y},
    )


mm = matmul


def dot(x, y, name=None):
    def fn(a, b):
        return jnp.sum(a * b, axis=-1)

    return eager_call("dot", fn, [as_tensor(x), as_tensor(y)])


def inner(x, y, name=None):
    return eager_call("inner", jnp.inner, [as_tensor(x), as_tensor(y)])


def outer(x, y, name=None):
    return eager_call("outer", lambda a, b: jnp.outer(a, b), [as_tensor(x), as_tensor(y)])


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return eager_call(
        "addmm",
        lambda i, a, b, beta, alpha: beta * i + alpha * (a @ b),
        [as_tensor(input), as_tensor(x), as_tensor(y)],
        {"beta": beta, "alpha": alpha},
    )


def bmm(x, y, name=None):
    return eager_call("bmm", jnp.matmul, [as_tensor(x), as_tensor(y)])


def kron(x, y, name=None):
    return eager_call("kron", jnp.kron, [as_tensor(x), as_tensor(y)])


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return eager_call(
        "trace",
        lambda a, offset, axis1, axis2: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2),
        [as_tensor(x)],
        {"offset": offset, "axis1": axis1, "axis2": axis2},
    )


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return eager_call(
        "diagonal",
        lambda a, offset, axis1, axis2: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2),
        [as_tensor(x)],
        {"offset": offset, "axis1": axis1, "axis2": axis2},
    )


def mv(x, vec, name=None):
    return eager_call("mv", jnp.matmul, [as_tensor(x), as_tensor(vec)])


def dist(x, y, p=2, name=None):
    return eager_call(
        "dist",
        lambda a, b, p: jnp.linalg.norm((a - b).reshape(-1), ord=p),
        [as_tensor(x), as_tensor(y)],
        {"p": float(p)},
    )


# -- misc --------------------------------------------------------------------
def cast(x, dtype):
    x = as_tensor(x)
    dt = dtypes.convert_dtype(dtype)
    src_float = dtypes.is_floating_point(x.dtype) or dtypes.is_complex(x.dtype)
    return eager_call(
        "cast", lambda a, dt: a.astype(dt), [x], {"dt": dt},
        differentiable=src_float and dtypes.is_floating_point(dt),
    )


def increment(x, value=1.0, name=None):
    x = as_tensor(x)
    x._set_data(x._data + value)
    return x


def accuracy_tensor(pred, label):  # helper used by metric
    pred, label = as_tensor(pred), as_tensor(label)
    correct = jnp.equal(jnp.argmax(_concrete(pred._data), axis=-1), _concrete(label._data).reshape(-1))
    return Tensor(jnp.mean(correct.astype(jnp.float32)))
