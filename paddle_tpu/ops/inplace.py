"""In-place op variants (``x.add_(y)``, ``x.clip_(...)``, …).

Parity: the reference registers ``<op>_``/``Inplace`` kernel variants and
checks tensor inplace-version counters for autograd safety
(``paddle/fluid/imperative/dygraph_grad_maker.h`` inplace version checking).
TPU-native: arrays are immutable under XLA, so "in-place" rebinds the
tensor's buffer to the op result — observationally identical (paddle's
inplace ops also return the tensor). When the target is autograd-tracked and
grad is enabled we refuse, mirroring the reference's leaf-inplace error, to
keep the vjp tape sound.
"""
from __future__ import annotations

from ..core.engine import grad_enabled
from ..core.tensor import Tensor

# base-op name -> resolved lazily from the assembled paddle namespace
_INPLACE_BASES = [
    "add", "subtract", "multiply", "divide", "remainder", "pow",
    "clip", "scale", "exp", "sqrt", "rsqrt", "reciprocal", "round",
    "floor", "ceil", "trunc", "abs", "tanh", "sigmoid", "erfinv", "sin",
    "cos", "neg", "sign", "lerp", "cast", "flatten", "reshape", "squeeze",
    "unsqueeze", "clone", "tril", "triu", "digamma", "lgamma",
    "nan_to_num", "logit", "masked_fill", "index_add", "put_along_axis",
    "scatter", "renorm", "fill_diagonal",
]


def _make_inplace(base_name):
    def op_(self, *args, **kwargs):
        import paddle_tpu as _p

        base = getattr(_p, base_name, None)
        if base is None:
            from . import generated

            base = generated.GENERATED.get(base_name)
        if base is None:
            raise NotImplementedError(f"no base op {base_name} for {base_name}_")
        if not self.stop_gradient and grad_enabled():
            raise RuntimeError(
                f"{base_name}_(): in-place on a tensor that requires grad is "
                "not supported (reference: inplace version-check error); use "
                f"the out-of-place {base_name}() instead"
            )
        out = base(self, *args, **kwargs)
        self._set_data(out._data if isinstance(out, Tensor) else out)
        return self

    op_.__name__ = base_name + "_"
    op_.__doc__ = f"In-place variant of `{base_name}` (rebinds this tensor's buffer)."
    return op_


def uniform_(self, min=-1.0, max=1.0, seed=0):
    """In-place uniform refill (reference uniform_random_inplace op).
    seed!=0 makes the refill deterministic, matching reference semantics."""
    import jax
    from ..core import random as random_state

    if not self.stop_gradient and grad_enabled():
        raise RuntimeError("uniform_(): in-place on a tensor that requires grad")
    key = jax.random.PRNGKey(seed) if seed else random_state.next_key()
    self._set_data(jax.random.uniform(key, self._data.shape, self._data.dtype, min, max))
    return self


def normal_(self, mean=0.0, std=1.0):
    import jax
    from ..core import random as random_state

    if not self.stop_gradient and grad_enabled():
        raise RuntimeError("normal_(): in-place on a tensor that requires grad")
    key = random_state.next_key()
    self._set_data(jax.random.normal(key, self._data.shape, self._data.dtype) * std + mean)
    return self


def exponential_(self, lam=1.0):
    import jax
    from ..core import random as random_state

    if not self.stop_gradient and grad_enabled():
        raise RuntimeError("exponential_(): in-place on a tensor that requires grad")
    key = random_state.next_key()
    self._set_data(jax.random.exponential(key, self._data.shape, self._data.dtype) / lam)
    return self


def fill_(self, value):
    import jax.numpy as jnp

    if not self.stop_gradient and grad_enabled():
        raise RuntimeError("fill_(): in-place on a tensor that requires grad")
    self._set_data(jnp.full(tuple(self._data.shape), value, dtype=self._data.dtype))
    return self


def zero_(self):
    return fill_(self, 0.0)


INPLACE_OPS = {}


def attach():
    for base in _INPLACE_BASES:
        name = base + "_"
        fn = _make_inplace(base)
        INPLACE_OPS[name] = fn
        if not hasattr(Tensor, name):
            setattr(Tensor, name, fn)
    for name, fn in (
        ("fill_", fill_), ("zero_", zero_), ("uniform_", uniform_),
        ("normal_", normal_), ("exponential_", exponential_),
    ):
        INPLACE_OPS[name] = fn
        if not hasattr(Tensor, name):
            setattr(Tensor, name, fn)


attach()
