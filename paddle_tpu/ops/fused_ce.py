"""Fused LM-head cross-entropy (blockwise logits→CE, no full-logits tensor).

Role parity: reference ``operators/collective/c_softmax_with_cross_entropy``
+ the fused softmax/CE kernels (``operators/math/`` softmax impls) — the
reason those exist is exactly this memory wall: a (B·T, V) fp32 logits
tensor for V≈50k bounds the trainable batch. TPU-first design: a
``lax.scan`` over row blocks computes ``x_block @ W^T`` on the MXU
(bf16 in, f32 accumulate), reduces each block to its logsumexp + label
logit, and discards the block logits — peak extra memory is
``block_rows × V`` fp32 instead of ``B·T × V``. The custom VJP recomputes
block logits in the backward (rematerialization: FLOPs are cheap, HBM is
not) and streams ``dW`` accumulation in fp32.
"""
from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def _block(x, labels, block_rows):
    N, d = x.shape
    nb = -(-N // block_rows)
    pad = nb * block_rows - N
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        labels = jnp.pad(labels, (0, pad), constant_values=-1)
    return x.reshape(nb, block_rows, d), labels.reshape(nb, block_rows)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fce_call(x, w, labels, block_rows, ignore_index):
    loss, _ = _fce_fwd(x, w, labels, block_rows, ignore_index)
    return loss


def fused_linear_cross_entropy(x, w, labels, block_rows=None, ignore_index=-100):
    """mean over valid rows of CE(softmax(x @ w.T), labels).

    x: (N, d); w: (V, d) — the (tied) LM-head/embedding weight; labels: (N,)
    int. Rows where ``labels == ignore_index`` (or padding) are excluded
    from both the sum and the mean denominator.

    ``block_rows=None`` resolves through the kernel registry
    (``ops/kernels``: the pinned 2048 default with autotune off, a tuned
    winner otherwise); an explicit value bypasses the registry. Resolution
    is trace-time python — the traced program always sees a concrete block.
    """
    if block_rows is None:
        from .kernels import fused_ce_key, resolve_config

        cfg = resolve_config(
            "fused_ce", fused_ce_key(x.shape[0], x.shape[1], w.shape[0],
                                     x.dtype))
        block_rows = int(cfg["block_rows"])
    return _fce_call(x, w, labels, int(block_rows), ignore_index)


def _fce_fwd(x, w, labels, block_rows, ignore_index):
    xb, lb = _block(x, labels, block_rows)
    V = w.shape[0]

    def body(carry, blk):
        xs, ls = blk
        logits = jnp.dot(xs, w.T, preferred_element_type=jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        li = jnp.clip(ls, 0, V - 1)
        corr = jnp.take_along_axis(logits, li[:, None], axis=-1)[:, 0]
        valid = (ls != ignore_index) & (ls >= 0)
        nll = jnp.where(valid, lse - corr, 0.0)
        s, c = carry
        return (s + nll.sum(), c + valid.sum(dtype=jnp.int32)), None

    (total, cnt), _ = lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)), (xb, lb)
    )
    loss = total / jnp.maximum(cnt, 1).astype(jnp.float32)
    return loss, (x, w, labels)


def _fce_bwd(block_rows, ignore_index, res, ct):
    x, w, labels = res
    xb, lb = _block(x, labels, block_rows)
    V, d = w.shape
    valid_all = (labels != ignore_index) & (labels >= 0)
    n_valid = jnp.maximum(valid_all.sum(), 1).astype(jnp.float32)
    scale = (ct / n_valid).astype(jnp.float32)

    def body(dw, blk):
        xs, ls = blk
        logits = jnp.dot(xs, w.T, preferred_element_type=jnp.float32)
        p = jax.nn.softmax(logits, axis=-1)
        li = jnp.clip(ls, 0, V - 1)
        valid = (ls != ignore_index) & (ls >= 0)
        g = p - jax.nn.one_hot(li, V, dtype=p.dtype)
        g = g * (valid.astype(p.dtype) * scale)[:, None]
        gb = g.astype(w.dtype)
        dx_blk = jnp.dot(gb, w, preferred_element_type=jnp.float32).astype(x.dtype)
        dw_blk = jnp.dot(gb.T, xs, preferred_element_type=jnp.float32)
        return dw + dw_blk, dx_blk

    dw, dxb = lax.scan(body, jnp.zeros((V, d), jnp.float32), (xb, lb))
    dx = dxb.reshape(-1, d)[: x.shape[0]].astype(x.dtype)
    dlabels = np.zeros(labels.shape, dtype=jax.dtypes.float0)
    return dx, dw.astype(w.dtype), dlabels


_fce_call.defvjp(_fce_fwd, _fce_bwd)
