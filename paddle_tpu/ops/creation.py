"""Tensor creation ops.

Parity surface: reference ``python/paddle/tensor/creation.py`` (zeros/ones/
full/arange/...) and random ops in ``python/paddle/tensor/random.py``; kernels
that were per-backend C++/CUDA (e.g. ``paddle/phi/kernels/gpu/full_kernel.cu``)
are jnp/XLA here.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.lazy import concrete as _concrete

from ..core import dtype as dtypes
from ..core import random as random_state
from ..core.tensor import Tensor
from ..core.dispatch import as_tensor, eager_call


def _dt(dtype, default=None):
    if dtype is None:
        return default if default is not None else dtypes.get_default_dtype()
    return dtypes.convert_dtype(dtype)


def _shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), dtype=_dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), dtype=_dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        dtype = dtypes.get_default_dtype() if isinstance(fill_value, float) else None
    return Tensor(jnp.full(_shape(shape), fill_value, dtype=_dt(dtype) if dtype else None))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    x = as_tensor(x)
    return Tensor(jnp.zeros(tuple(x._data.shape), dtype=_dt(dtype, x.dtype) or x._data.dtype))


def ones_like(x, dtype=None, name=None):
    x = as_tensor(x)
    return Tensor(jnp.ones(tuple(x._data.shape), dtype=_dt(dtype, x.dtype) or x._data.dtype))


def full_like(x, fill_value, dtype=None, name=None):
    x = as_tensor(x)
    return Tensor(jnp.full(tuple(x._data.shape), fill_value, dtype=_dt(dtype, x.dtype) or x._data.dtype))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    if end is None:
        start, end = 0, start
    for v in (start, end, step):
        if isinstance(v, float):
            dtype = dtype or dtypes.get_default_dtype()
    if isinstance(start, Tensor):
        start = start.item()
    if isinstance(end, Tensor):
        end = end.item()
    if isinstance(step, Tensor):
        step = step.item()
    return Tensor(jnp.arange(start, end, step, dtype=_dt(dtype, np.dtype("int64")) if dtype else None))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(float(start), float(stop), int(num), dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(float(start), float(stop), int(num), base=base, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    x = as_tensor(x)

    def fn(a, offset, padding_value):
        if a.ndim == 1:
            d = jnp.diag(a, k=offset)
            if padding_value != 0:
                n = a.shape[0] + abs(offset)
                mask = jnp.eye(n, k=offset, dtype=bool)
                d = jnp.where(mask, d, jnp.asarray(padding_value, d.dtype))
            return d
        return jnp.diagonal(a, offset=offset)

    return eager_call("diag", fn, [x], {"offset": offset, "padding_value": padding_value})


def diagflat(x, offset=0, name=None):
    x = as_tensor(x)
    return eager_call("diagflat", lambda a, offset: jnp.diagflat(a, k=offset), [x], {"offset": offset})


def tril(x, diagonal=0, name=None):
    return eager_call("tril", lambda a, diagonal: jnp.tril(a, k=diagonal), [as_tensor(x)], {"diagonal": diagonal})


def triu(x, diagonal=0, name=None):
    return eager_call("triu", lambda a, diagonal: jnp.triu(a, k=diagonal), [as_tensor(x)], {"diagonal": diagonal})


def meshgrid(*args, name=None):
    tensors = [as_tensor(a) for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    outs = jnp.meshgrid(*[_concrete(t._data) for t in tensors], indexing="ij")
    return [Tensor(o) for o in outs]


def assign(x, output=None):
    x = as_tensor(x)
    out = eager_call("assign", lambda a: a + 0, [x])
    if output is not None:
        output._set_data(out._data)
        return output
    return out


def clone(x, name=None):
    return eager_call("clone", lambda a: a + 0, [as_tensor(x)])


def numel(x, name=None):
    return Tensor(np.int64(as_tensor(x).size))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    inp = as_tensor(input)
    size = index_num // nshards

    def fn(a, size, shard_id, ignore_value):
        in_shard = (a // size) == shard_id
        return jnp.where(in_shard, a % size, ignore_value)

    return eager_call(
        "shard_index", fn, [inp],
        {"size": size, "shard_id": shard_id, "ignore_value": ignore_value},
        differentiable=False,
    )


# ---------------------------------------------------------------------------
# Random ops (reference python/paddle/tensor/random.py)
# ---------------------------------------------------------------------------
def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype=dtype, min=0.0, max=1.0)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = random_state.next_key()
    dt = _dt(dtype)
    arr = jax.random.uniform(key, _shape(shape), dtype=jnp.float32, minval=min, maxval=max)
    return Tensor(arr.astype(dt))


def randn(shape, dtype=None, name=None):
    return normal(0.0, 1.0, shape, dtype=dtype)


def normal(mean=0.0, std=1.0, shape=None, dtype=None, name=None):
    key = random_state.next_key()
    dt = _dt(dtype)
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = as_tensor(mean)._data if isinstance(mean, Tensor) else mean
        s = as_tensor(std)._data if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(
            jnp.shape(m) if hasattr(m, "shape") else (), jnp.shape(s) if hasattr(s, "shape") else ()
        )
        arr = jax.random.normal(key, shp, dtype=jnp.float32) * s + m
        return Tensor(arr.astype(dt))
    arr = jax.random.normal(key, _shape(shape), dtype=jnp.float32) * std + mean
    return Tensor(arr.astype(dt))


def gaussian(shape, mean=0.0, std=1.0, dtype=None, name=None):
    return normal(mean, std, shape, dtype=dtype)


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    key = random_state.next_key()
    dt = _dt(dtype, np.dtype("int64"))
    return Tensor(jax.random.randint(key, _shape(shape), low, high, dtype=jnp.int32).astype(dt))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = as_tensor(x)
    return randint(low, high, tuple(x.shape), dtype=_dt(dtype, x.dtype))


def randperm(n, dtype=None, name=None):
    key = random_state.next_key()
    dt = _dt(dtype, np.dtype("int64"))
    return Tensor(jax.random.permutation(key, n).astype(dt))


def bernoulli(x, name=None):
    x = as_tensor(x)
    key = random_state.next_key()
    return Tensor(jax.random.bernoulli(key, _concrete(x._data).astype(jnp.float32)).astype(x.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = as_tensor(x)
    key = random_state.next_key()
    x = Tensor(_concrete(x._data), stop_gradient=x.stop_gradient)
    logits = jnp.log(jnp.maximum(x._data.astype(jnp.float32), 1e-30))
    if x.ndim == 1:
        out = jax.random.choice(
            key, x.shape[0], shape=(num_samples,), replace=replacement, p=x._data / x._data.sum()
        )
    else:
        out = jax.random.categorical(key, logits, axis=-1, shape=(x.shape[0], num_samples) if replacement else None)
        if not replacement:
            keys = jax.random.split(key, x.shape[0])
            out = jnp.stack(
                [
                    jax.random.choice(k, x.shape[1], shape=(num_samples,), replace=False, p=row / row.sum())
                    for k, row in zip(keys, x._data)
                ]
            )
    return Tensor(out.astype(np.int64))


def standard_normal(shape, dtype=None, name=None):
    return normal(0.0, 1.0, shape, dtype=dtype)
