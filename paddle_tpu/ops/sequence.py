"""Sequence op family — the reference's LoD ``sequence_ops/`` re-designed
masked-ragged.

Reference: ``paddle/fluid/operators/sequence_ops/`` (16 ops over LoDTensors —
variable-length rows packed flat with a level-of-detail offset table). LoD is
a CPU-pointer idiom; the TPU-native representation is PADDED + LENGTHS:
``x: (B, T, ...)`` with ``length: (B,)`` valid counts (static shapes, XLA
tiles cleanly, and it is exactly what `functional.sequence_mask` / the ragged
BucketSampler already produce). Every op here takes/returns that pair; ops
that change lengths return ``(values, new_length)``.

All ops route through ``core.dispatch.eager_call`` so they carry autograd,
AMP hooks, per-op jit caching and nan/inf scans like every other op.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import as_tensor, eager_call

__all__ = [
    "sequence_pad", "sequence_unpad", "sequence_softmax", "sequence_pool",
    "sequence_reverse", "sequence_expand", "sequence_expand_as",
    "sequence_concat", "sequence_slice", "sequence_erase",
    "sequence_enumerate", "sequence_reshape", "sequence_scatter",
    "sequence_topk_avg_pooling", "sequence_conv", "sequence_first_step",
    "sequence_last_step",
]


def _valid(length, t):
    """(B, T) bool validity mask from (B,) lengths."""
    return jnp.arange(t)[None, :] < length[:, None]


def sequence_pad(x, length, max_len, pad_value=0.0, name=None):
    """Flat packed values -> padded batch (reference sequence_pad_op.cc).

    x: (total, ...) rows of all sequences concatenated; length: (B,);
    returns (B, max_len, ...) with ``pad_value`` beyond each row's length.
    """
    x, length = as_tensor(x), as_tensor(length)

    def fn(xv, lv, max_len, pad_value):
        off = jnp.concatenate([jnp.zeros((1,), lv.dtype), jnp.cumsum(lv)[:-1]])
        t = jnp.arange(max_len)[None, :]
        idx = jnp.clip(off[:, None] + t, 0, xv.shape[0] - 1)
        out = xv[idx]
        mask = (t < lv[:, None]).reshape(idx.shape + (1,) * (xv.ndim - 1))
        return jnp.where(mask, out, jnp.asarray(pad_value, out.dtype))

    return eager_call("sequence_pad", fn, [x, length],
                      {"max_len": int(max_len), "pad_value": float(pad_value)})


def sequence_unpad(x, length, name=None):
    """Padded batch -> flat packed values (reference sequence_unpad_op.cc).

    Returns (B*T, ...): the first sum(length) rows hold the valid values in
    order, the rest are zeros (static-shape compaction)."""
    x, length = as_tensor(x), as_tensor(length)

    def fn(xv, lv):
        b, t = xv.shape[0], xv.shape[1]
        off = jnp.concatenate([jnp.zeros((1,), lv.dtype), jnp.cumsum(lv)[:-1]])
        tt = jnp.arange(t)[None, :]
        valid = tt < lv[:, None]
        # invalid rows scatter into a trash slot past the end
        pos = jnp.where(valid, off[:, None] + tt, b * t)
        flat = xv.reshape((b * t,) + xv.shape[2:])
        out = jnp.zeros((b * t + 1,) + xv.shape[2:], xv.dtype)
        out = out.at[pos.reshape(-1)].set(flat)
        return out[: b * t]

    return eager_call("sequence_unpad", fn, [x, length])


def sequence_softmax(x, length, name=None):
    """Masked softmax over the time axis (reference sequence_softmax_op.cc)."""
    x, length = as_tensor(x), as_tensor(length)

    def fn(xv, lv):
        mask = _valid(lv, xv.shape[1])
        mask = mask.reshape(mask.shape + (1,) * (xv.ndim - 2))
        s = jnp.where(mask, xv.astype(jnp.float32), -jnp.inf)
        p = jax.nn.softmax(s, axis=1)
        return jnp.where(mask, p, 0.0).astype(xv.dtype)

    return eager_call("sequence_softmax", fn, [x, length])


def sequence_pool(x, length, pool_type="SUM", name=None):
    """Masked pooling over time (reference sequence_pool_op.cc):
    SUM | AVERAGE | SQRT | MAX | MIN | LAST | FIRST."""
    x, length = as_tensor(x), as_tensor(length)
    pt = pool_type.upper()
    if pt not in ("SUM", "AVERAGE", "SQRT", "MAX", "MIN", "LAST", "FIRST"):
        raise ValueError(f"unknown pool_type {pool_type!r}")

    def fn(xv, lv, pt):
        t = xv.shape[1]
        mask = _valid(lv, t).reshape((xv.shape[0], t) + (1,) * (xv.ndim - 2))
        n = jnp.maximum(lv, 1).reshape((-1,) + (1,) * (xv.ndim - 2))
        if pt == "SUM":
            return jnp.where(mask, xv, 0).sum(axis=1)
        if pt == "AVERAGE":
            return jnp.where(mask, xv, 0).sum(axis=1) / n.astype(xv.dtype)
        if pt == "SQRT":
            return jnp.where(mask, xv, 0).sum(axis=1) / jnp.sqrt(n.astype(xv.dtype))
        if pt in ("MAX", "MIN"):
            fill = -jnp.inf if pt == "MAX" else jnp.inf
            red = jnp.where(mask, xv, fill)
            out = red.max(axis=1) if pt == "MAX" else red.min(axis=1)
            # zero-length rows (legal: e.g. sequence_slice can produce them)
            # must not emit +-inf into downstream reductions
            empty = (lv == 0).reshape((-1,) + (1,) * (xv.ndim - 2))
            return jnp.where(empty, jnp.zeros_like(out), out)
        idx = (lv - 1 if pt == "LAST" else jnp.zeros_like(lv))
        out = jnp.take_along_axis(
            xv, jnp.clip(idx, 0, t - 1).reshape((-1, 1) + (1,) * (xv.ndim - 2)), axis=1
        )[:, 0]
        # zero-length rows would otherwise leak x[i, 0] padding garbage
        empty = (lv == 0).reshape((-1,) + (1,) * (xv.ndim - 2))
        return jnp.where(empty, jnp.zeros_like(out), out)

    return eager_call("sequence_pool", fn, [x, length], {"pt": pt})


def sequence_first_step(x, length, name=None):
    return sequence_pool(x, length, "FIRST")


def sequence_last_step(x, length, name=None):
    return sequence_pool(x, length, "LAST")


def sequence_reverse(x, length, name=None):
    """Reverse each row's valid prefix (reference sequence_reverse_op.cc)."""
    x, length = as_tensor(x), as_tensor(length)

    def fn(xv, lv):
        t = xv.shape[1]
        tt = jnp.arange(t)[None, :]
        idx = jnp.where(tt < lv[:, None], lv[:, None] - 1 - tt, tt)
        return jnp.take_along_axis(
            xv, idx.reshape(idx.shape + (1,) * (xv.ndim - 2)), axis=1)

    return eager_call("sequence_reverse", fn, [x, length])


def sequence_expand(x, length, max_len, name=None):
    """Broadcast each batch row along a fresh time axis of per-row length
    (reference sequence_expand_op.cc with ref_level lengths). x: (B, ...) ->
    (B, max_len, ...) masked to ``length``."""
    x, length = as_tensor(x), as_tensor(length)

    def fn(xv, lv, max_len):
        out = jnp.broadcast_to(xv[:, None], (xv.shape[0], max_len) + xv.shape[1:])
        mask = _valid(lv, max_len).reshape(
            (xv.shape[0], max_len) + (1,) * (xv.ndim - 1))
        return jnp.where(mask, out, 0)

    return eager_call("sequence_expand", fn, [x, length], {"max_len": int(max_len)})


def sequence_expand_as(x, y, y_length, name=None):
    """Expand x rows to y's time layout (reference sequence_expand_as_op.cc)."""
    y = as_tensor(y)
    return sequence_expand(x, y_length, max_len=y._data.shape[1])


def sequence_concat(x, x_length, y, y_length, name=None):
    """Time-wise ragged concat (reference sequence_concat_op.cc):
    row b becomes x[b,:lx[b]] ++ y[b,:ly[b]]. Returns (values, new_length)."""
    x, x_length = as_tensor(x), as_tensor(x_length)
    y, y_length = as_tensor(y), as_tensor(y_length)

    def fn(xv, lx, yv, ly):
        t1, t2 = xv.shape[1], yv.shape[1]
        cat = jnp.concatenate([xv, yv], axis=1)  # (B, T1+T2, ...)
        tt = jnp.arange(t1 + t2)[None, :]
        # read x[t] while t < lx, else y[t - lx]
        idx = jnp.where(tt < lx[:, None], tt, t1 + jnp.clip(tt - lx[:, None], 0, t2 - 1))
        out = jnp.take_along_axis(
            cat, idx.reshape(idx.shape + (1,) * (xv.ndim - 2)), axis=1)
        mask = (tt < (lx + ly)[:, None]).reshape(
            idx.shape + (1,) * (xv.ndim - 2))
        return jnp.where(mask, out, 0), lx + ly

    return eager_call("sequence_concat", fn, [x, x_length, y, y_length],
                      nondiff_outputs=(1,))


def sequence_slice(x, length, offset, slice_length, name=None):
    """Per-row slice [offset, offset+slice_length) (sequence_slice_op.cc).
    offset/slice_length: (B,). Returns (values, new_length)."""
    x, length = as_tensor(x), as_tensor(length)
    offset, slice_length = as_tensor(offset), as_tensor(slice_length)

    def fn(xv, lv, off, sl):
        t = xv.shape[1]
        tt = jnp.arange(t)[None, :]
        idx = jnp.clip(off[:, None] + tt, 0, t - 1)
        out = jnp.take_along_axis(
            xv, idx.reshape(idx.shape + (1,) * (xv.ndim - 2)), axis=1)
        new_len = jnp.minimum(sl, jnp.maximum(lv - off, 0))
        mask = (tt < new_len[:, None]).reshape(idx.shape + (1,) * (xv.ndim - 2))
        return jnp.where(mask, out, 0), new_len

    return eager_call("sequence_slice", fn, [x, length, offset, slice_length],
                      nondiff_outputs=(1,))


def sequence_erase(x, length, tokens, name=None):
    """Remove listed token ids and compact (sequence_erase_op.cc).
    x: (B, T) int ids. Returns (values, new_length)."""
    x, length = as_tensor(x), as_tensor(length)

    def fn(xv, lv, tokens):
        b, t = xv.shape
        tt = jnp.arange(t)[None, :]
        keep = (tt < lv[:, None]) & ~jnp.isin(xv, jnp.asarray(list(tokens)))
        pos = jnp.cumsum(keep, axis=1) - 1  # target slot per kept token
        pos = jnp.where(keep, pos, t)  # trash slot
        out = jnp.zeros((b, t + 1), xv.dtype)
        out = out.at[jnp.arange(b)[:, None], pos].set(xv)
        return out[:, :t], keep.sum(axis=1).astype(lv.dtype)

    return eager_call("sequence_erase", fn, [x, length],
                      {"tokens": tuple(int(t) for t in tokens)},
                      differentiable=False)


def sequence_enumerate(x, length, win_size, pad_value=0, name=None):
    """Sliding windows of ids (sequence_enumerate_op.cc): (B, T) ->
    (B, T, win_size); positions past the row length give pad_value."""
    x, length = as_tensor(x), as_tensor(length)

    def fn(xv, lv, win_size, pad_value):
        t = xv.shape[1]
        tt = jnp.arange(t)[:, None] + jnp.arange(win_size)[None, :]  # (T, W)
        idx = jnp.clip(tt, 0, t - 1)
        out = xv[:, idx]  # (B, T, W)
        ok = tt[None, :, :] < lv[:, None, None]
        return jnp.where(ok, out, jnp.asarray(pad_value, xv.dtype))

    return eager_call("sequence_enumerate", fn, [x, length],
                      {"win_size": int(win_size), "pad_value": int(pad_value)},
                      differentiable=False)


def sequence_reshape(x, length, new_dim, name=None):
    """Re-chunk each row's values to width new_dim (sequence_reshape_op.cc).
    x: (B, T, D) with T*D % new_dim == 0; lengths scale by D/new_dim.
    Returns (values, new_length)."""
    x, length = as_tensor(x), as_tensor(length)

    def fn(xv, lv, new_dim):
        b, t, d = xv.shape
        out = xv.reshape(b, t * d // new_dim, new_dim)
        return out, (lv * d) // new_dim

    return eager_call("sequence_reshape", fn, [x, length],
                      {"new_dim": int(new_dim)}, nondiff_outputs=(1,))


def sequence_scatter(x, index, updates, updates_length, name=None):
    """Scatter-add per-row updates at per-row positions
    (sequence_scatter_op.cc). x: (B, T); index/updates: (B, K)."""
    x, index = as_tensor(x), as_tensor(index)
    updates, updates_length = as_tensor(updates), as_tensor(updates_length)

    def fn(xv, idx, upd, ul):
        k = idx.shape[1]
        ok = jnp.arange(k)[None, :] < ul[:, None]
        upd = jnp.where(ok, upd, 0)
        b = xv.shape[0]
        return xv.at[jnp.arange(b)[:, None], jnp.clip(idx, 0, xv.shape[1] - 1)].add(upd)

    return eager_call("sequence_scatter", fn, [x, index, updates, updates_length])


def sequence_topk_avg_pooling(x, length, topks, name=None):
    """Mean of each row's top-k valid values for every k in ``topks``
    (sequence_topk_avg_pooling_op.cc). x: (B, T) -> (B, len(topks))."""
    x, length = as_tensor(x), as_tensor(length)

    def fn(xv, lv, topks):
        t = xv.shape[1]
        masked = jnp.where(_valid(lv, t), xv.astype(jnp.float32), -jnp.inf)
        srt = jnp.sort(masked, axis=1)[:, ::-1]  # desc
        srt = jnp.where(jnp.isfinite(srt), srt, 0.0)
        csum = jnp.cumsum(srt, axis=1)
        outs = []
        for k in topks:
            kk = jnp.minimum(lv, k)
            kk = jnp.maximum(kk, 1)
            outs.append(jnp.take_along_axis(csum, (kk - 1)[:, None], axis=1)[:, 0]
                        / kk.astype(jnp.float32))
        return jnp.stack(outs, axis=1).astype(xv.dtype)

    return eager_call("sequence_topk_avg_pooling", fn, [x, length],
                      {"topks": tuple(int(k) for k in topks)})


def sequence_conv(x, length, weight, context_start=None, name=None):
    """Context-window convolution over time (sequence_conv_op.cc).
    x: (B, T, D); weight: (ctx*D, M); positions outside the row are zero.
    context length = weight.shape[0] // D, default centered window."""
    x, length, weight = as_tensor(x), as_tensor(length), as_tensor(weight)

    def fn(xv, lv, wv, context_start):
        b, t, d = xv.shape
        ctx = wv.shape[0] // d
        start = context_start if context_start is not None else -(ctx // 2)
        mask = _valid(lv, t)[:, :, None]
        xz = jnp.where(mask, xv, 0)
        frames = []
        for c in range(ctx):
            shift = start + c
            rolled = jnp.roll(xz, -shift, axis=1)
            tt = jnp.arange(t)[None, :] + shift
            ok = (tt >= 0) & (tt < lv[:, None])
            frames.append(jnp.where(ok[:, :, None], rolled, 0))
        stacked = jnp.concatenate(frames, axis=-1)  # (B, T, ctx*D)
        out = jnp.einsum("btc,cm->btm", stacked, wv)
        return jnp.where(mask, out, 0)

    return eager_call(
        "sequence_conv", fn, [x, length, weight],
        {"context_start": None if context_start is None else int(context_start)},
    )
