"""Linear algebra ops (paddle.linalg parity).

Parity surface: reference ``python/paddle/tensor/linalg.py`` and C++ kernels
(``paddle/fluid/operators/{cholesky,svd,qr,eig,inverse,...}_op.cc``, LAPACK
functors ``paddle/phi/kernels/funcs/lapack/``) — all via jnp.linalg/XLA.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import as_tensor, eager_call


def cholesky(x, upper=False, name=None):
    def fn(a, upper):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2) if upper else L

    return eager_call("cholesky", fn, [as_tensor(x)], {"upper": upper})


def inv(x, name=None):
    return eager_call("inv", jnp.linalg.inv, [as_tensor(x)])


inverse = inv


def det(x, name=None):
    return eager_call("det", jnp.linalg.det, [as_tensor(x)])


def slogdet(x, name=None):
    def fn(a):
        sign, logdet = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logdet])

    return eager_call("slogdet", fn, [as_tensor(x)])


def svd(x, full_matrices=False, name=None):
    def fn(a, full_matrices):
        u, s, vh = jnp.linalg.svd(a, full_matrices=full_matrices)
        return u, s, jnp.swapaxes(vh, -1, -2)

    out = eager_call("svd", fn, [as_tensor(x)], {"full_matrices": full_matrices})
    return out[0], out[1], out[2]


def qr(x, mode="reduced", name=None):
    def fn(a, mode):
        return jnp.linalg.qr(a, mode=mode)

    if mode == "r":
        return eager_call("qr_r", lambda a: jnp.linalg.qr(a, mode="r"), [as_tensor(x)])
    out = eager_call("qr", fn, [as_tensor(x)], {"mode": mode})
    return out[0], out[1]


def eig(x, name=None):
    x = as_tensor(x)
    w, v = np.linalg.eig(np.asarray(x._data))  # general eig: host LAPACK (like reference CPU kernel)
    return Tensor(w), Tensor(v)


def eigh(x, UPLO="L", name=None):
    def fn(a, UPLO):
        return jnp.linalg.eigh(a, UPLO=UPLO)

    out = eager_call("eigh", fn, [as_tensor(x)], {"UPLO": UPLO})
    return out[0], out[1]


def eigvals(x, name=None):
    x = as_tensor(x)
    return Tensor(np.linalg.eigvals(np.asarray(x._data)))


def eigvalsh(x, UPLO="L", name=None):
    return eager_call("eigvalsh", lambda a, UPLO: jnp.linalg.eigvalsh(a, UPLO=UPLO), [as_tensor(x)], {"UPLO": UPLO})


def norm(x, p=None, axis=None, keepdim=False, name=None):
    x = as_tensor(x)
    if p is None:
        p = "fro" if axis is None or isinstance(axis, (list, tuple)) else 2

    def fn(a, p, axis, keepdim):
        if axis is None:
            if p == "fro" or p == 2:
                return jnp.sqrt(jnp.sum(jnp.square(a)))
            if p == np.inf:
                return jnp.max(jnp.abs(a))
            if p == -np.inf:
                return jnp.min(jnp.abs(a))
            if p == 1:
                return jnp.sum(jnp.abs(a))
            if p == 0:
                return jnp.sum((a != 0).astype(a.dtype))
            return jnp.power(jnp.sum(jnp.power(jnp.abs(a), p)), 1.0 / p)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if p == "fro":
            return jnp.sqrt(jnp.sum(jnp.square(a), axis=ax, keepdims=keepdim))
        if p == np.inf:
            return jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == -np.inf:
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=ax, keepdims=keepdim)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a), p), axis=ax, keepdims=keepdim), 1.0 / p)

    axis_n = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return eager_call("norm", fn, [x], {"p": p, "axis": axis_n, "keepdim": keepdim})


def cond(x, p=None, name=None):
    x = as_tensor(x)
    return Tensor(np.linalg.cond(np.asarray(x._data), p=p))


def matrix_power(x, n, name=None):
    return eager_call("matrix_power", lambda a, n: jnp.linalg.matrix_power(a, n), [as_tensor(x)], {"n": int(n)})


def matrix_rank(x, tol=None, hermitian=False, name=None):
    x = as_tensor(x)
    return Tensor(
        np.linalg.matrix_rank(np.asarray(x._data, dtype=np.float64), tol=tol, hermitian=hermitian).astype(np.int64)
    )


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return eager_call(
        "pinv", lambda a, rcond, hermitian: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian),
        [as_tensor(x)], {"rcond": rcond, "hermitian": hermitian},
    )


def solve(x, y, name=None):
    return eager_call("solve", jnp.linalg.solve, [as_tensor(x), as_tensor(y)])


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def fn(a, b, upper, transpose, unitriangular):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular
        )

    return eager_call(
        "triangular_solve", fn, [as_tensor(x), as_tensor(y)],
        {"upper": upper, "transpose": transpose, "unitriangular": unitriangular},
    )


def cholesky_solve(x, y, upper=False, name=None):
    def fn(b, L, upper):
        return jax.scipy.linalg.cho_solve((L, not upper), b)

    return eager_call("cholesky_solve", fn, [as_tensor(x), as_tensor(y)], {"upper": upper})


def lstsq(x, y, rcond=None, driver=None, name=None):
    x, y = as_tensor(x), as_tensor(y)
    sol, res, rank, sv = np.linalg.lstsq(np.asarray(x._data), np.asarray(y._data), rcond=rcond)
    return Tensor(sol), Tensor(res), Tensor(np.int64(rank)), Tensor(sv)


def lu(x, pivot=True, get_infos=False, name=None):
    def fn(a):
        lu_mat, piv = jax.scipy.linalg.lu_factor(a)
        return lu_mat, piv.astype(np.int32) + 1  # paddle pivots are 1-based

    out = eager_call("lu", fn, [as_tensor(x)], nondiff_outputs=[1])
    if get_infos:
        return out[0], out[1], Tensor(np.zeros((), np.int32))
    return out[0], out[1]


def multi_dot(tensors, name=None):
    ts = [as_tensor(t) for t in tensors]
    return eager_call("multi_dot", lambda *arrs: jnp.linalg.multi_dot(arrs), ts)


def corrcoef(x, rowvar=True, name=None):
    return eager_call("corrcoef", lambda a, rowvar: jnp.corrcoef(a, rowvar=rowvar), [as_tensor(x)], {"rowvar": rowvar})


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return eager_call(
        "cov", lambda a, rowvar, ddof: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0),
        [as_tensor(x)], {"rowvar": rowvar, "ddof": ddof},
    )


def householder_product(x, tau, name=None):
    def fn(a, tau):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)

        def body(i, Q):
            v = jnp.where(jnp.arange(m) < i, 0.0, a[..., :, i].at[i].set(1.0))
            H = eye - tau[..., i] * jnp.outer(v, v)
            return Q @ H

        Q = eye
        for i in range(n):
            v = a[..., :, i]
            v = jnp.where(jnp.arange(m) < i, 0.0, v)
            v = v.at[i].set(1.0)
            H = eye - tau[..., i] * jnp.outer(v, v)
            Q = Q @ H
        return Q[..., :, :n]

    return eager_call("householder_product", fn, [as_tensor(x), as_tensor(tau)])
