"""Shape / indexing / search manipulation ops.

Parity surface: reference ``python/paddle/tensor/manipulation.py``,
``search.py``, ``logic.py`` plus the C++ kernels behind them (concat, split,
gather/scatter, slice, transpose — ``paddle/fluid/operators/*.cc``).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.lazy import concrete as _concrete

from ..core import dtype as dtypes
from ..core.tensor import Tensor
from ..core.dispatch import as_tensor, eager_call


def _axes(axis):
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis) if axis is not None else None


def reshape(x, shape, name=None):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    shape = tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)
    return eager_call("reshape", lambda a, shape: jnp.reshape(a, shape), [as_tensor(x)], {"shape": shape})


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    x._data = out._data
    x._grad_node = out._grad_node
    x._out_index = out._out_index
    x.stop_gradient = out.stop_gradient
    return x


def transpose(x, perm, name=None):
    perm = tuple(int(p) for p in perm)
    return eager_call("transpose", lambda a, perm: jnp.transpose(a, perm), [as_tensor(x)], {"perm": perm})


def t(x, name=None):
    x = as_tensor(x)
    if x.ndim < 2:
        return x
    return eager_call("t", lambda a: jnp.swapaxes(a, -1, -2), [x])


def concat(x, axis=0, name=None):
    tensors = [as_tensor(t) for t in x]
    axis = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return eager_call("concat", lambda *arrs, axis: jnp.concatenate(arrs, axis=axis), tensors, {"axis": axis})


def stack(x, axis=0, name=None):
    tensors = [as_tensor(t) for t in x]
    return eager_call("stack", lambda *arrs, axis: jnp.stack(arrs, axis=axis), tensors, {"axis": int(axis)})


def split(x, num_or_sections, axis=0, name=None):
    x = as_tensor(x)
    axis = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: dimension {dim} on axis {axis} is not divisible by "
                f"num_or_sections={num_or_sections}"
            )
        sections = [dim // num_or_sections] * num_or_sections
    else:
        sections = [int(s) for s in num_or_sections]
        n_unknown = sections.count(-1)
        if n_unknown:
            known = builtins_sum(s for s in sections if s != -1)
            sections = [s if s != -1 else dim - known for s in sections]
    offsets = np.cumsum([0] + sections).tolist()

    def fn(a, offsets, axis):
        return tuple(jax.lax.slice_in_dim(a, offsets[i], offsets[i + 1], axis=axis) for i in range(len(offsets) - 1))

    return eager_call("split", fn, [x], {"offsets": tuple(offsets), "axis": axis})


import builtins

builtins_sum = builtins.sum


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    x = as_tensor(x)
    n = x.shape[axis]

    def fn(a, axis, n):
        return tuple(jnp.squeeze(jax.lax.slice_in_dim(a, i, i + 1, axis=axis), axis=axis) for i in range(n))

    return eager_call("unbind", fn, [x], {"axis": int(axis), "n": n})


unstack = unbind


def squeeze(x, axis=None, name=None):
    x = as_tensor(x)

    def fn(a, axis):
        if axis is None:
            return jnp.squeeze(a)
        axes = axis if isinstance(axis, tuple) else (axis,)
        axes = tuple(a_ for a_ in axes if a.shape[a_] == 1)
        return jnp.squeeze(a, axis=axes) if axes else a

    return eager_call("squeeze", fn, [x], {"axis": _axes(axis)})


def unsqueeze(x, axis, name=None):
    x = as_tensor(x)
    axes = _axes(axis)
    if isinstance(axes, int):
        axes = (axes,)
    return eager_call("unsqueeze", lambda a, axes: jnp.expand_dims(a, axes), [x], {"axes": axes})


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = as_tensor(x)
    nd = x.ndim
    sa = start_axis % nd if nd else 0
    ea = stop_axis % nd if nd else 0

    def fn(a, sa, ea):
        shape = a.shape[:sa] + (-1,) + a.shape[ea + 1 :]
        return jnp.reshape(a, shape)

    return eager_call("flatten", fn, [x], {"sa": sa, "ea": ea})


def expand(x, shape, name=None):
    x = as_tensor(x)
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    shape = tuple(int(s) for s in shape)
    # paddle semantics: -1 means keep original dim
    full = []
    offset = len(shape) - x.ndim
    for i, s in enumerate(shape):
        if s == -1:
            full.append(x.shape[i - offset])
        else:
            full.append(s)
    return eager_call("expand", lambda a, shape: jnp.broadcast_to(a, shape), [x], {"shape": tuple(full)})


broadcast_to = expand


def expand_as(x, y, name=None):
    return expand(x, as_tensor(y).shape)


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def broadcast_tensors(inputs, name=None):
    arrs = jnp.broadcast_arrays(*[as_tensor(t)._data for t in inputs])
    return [Tensor(a) for a in arrs]


def tile(x, repeat_times, name=None):
    if isinstance(repeat_times, Tensor):
        repeat_times = repeat_times.tolist()
    reps = tuple(int(r.item()) if isinstance(r, Tensor) else int(r) for r in repeat_times)
    return eager_call("tile", lambda a, reps: jnp.tile(a, reps), [as_tensor(x)], {"reps": reps})


def repeat_interleave(x, repeats, axis=None, name=None):
    x = as_tensor(x)
    if isinstance(repeats, Tensor):
        return eager_call(
            "repeat_interleave_t",
            lambda a, r, axis: jnp.repeat(a, r, axis=axis, total_repeat_length=int(np.asarray(repeats.numpy()).sum())),
            [x, repeats],
            {"axis": _axes(axis)},
        )
    return eager_call(
        "repeat_interleave",
        lambda a, repeats, axis: jnp.repeat(a, repeats, axis=axis),
        [x],
        {"repeats": int(repeats), "axis": _axes(axis)},
    )


def flip(x, axis, name=None):
    axes = _axes(axis)
    if isinstance(axes, int):
        axes = (axes,)
    return eager_call("flip", lambda a, axes: jnp.flip(a, axis=axes), [as_tensor(x)], {"axes": axes})


def roll(x, shifts, axis=None, name=None):
    return eager_call(
        "roll",
        lambda a, shifts, axis: jnp.roll(a, shifts, axis=axis),
        [as_tensor(x)],
        {"shifts": _axes(shifts), "axis": _axes(axis)},
    )


def rot90(x, k=1, axes=(0, 1), name=None):
    return eager_call("rot90", lambda a, k, axes: jnp.rot90(a, k=k, axes=axes), [as_tensor(x)], {"k": k, "axes": tuple(axes)})


def cast(x, dtype):
    from .math import cast as _cast

    return _cast(x, dtype)


# -- gather / scatter --------------------------------------------------------
def gather(x, index, axis=0, name=None):
    x, index = as_tensor(x), as_tensor(index)
    axis = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return eager_call("gather", lambda a, idx, axis: jnp.take(a, idx.reshape(-1), axis=axis), [x, index], {"axis": axis})


def gather_nd(x, index, name=None):
    x, index = as_tensor(x), as_tensor(index)

    def fn(a, idx):
        nd = idx.shape[-1]
        idx_t = tuple(jnp.moveaxis(idx, -1, 0))
        return a[idx_t]

    return eager_call("gather_nd", fn, [x, index])


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    arr, indices = as_tensor(arr), as_tensor(indices)
    return eager_call(
        "take_along_axis",
        lambda a, idx, axis: jnp.take_along_axis(a, idx, axis=axis),
        [arr, indices],
        {"axis": int(axis)},
    )


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    arr, indices, values = as_tensor(arr), as_tensor(indices), as_tensor(values)

    def fn(a, idx, v, axis, reduce):
        v = jnp.broadcast_to(v, idx.shape).astype(a.dtype)
        if reduce == "assign":
            return jnp.put_along_axis(a, idx, v, axis=axis, inplace=False)
        mode = {"add": "add", "mul": "multiply", "multiply": "multiply"}[reduce]
        return _scatter_reduce(a, idx, v, axis, mode)

    return eager_call("put_along_axis", fn, [arr, indices, values], {"axis": int(axis), "reduce": reduce})


def _scatter_reduce(a, idx, v, axis, mode):
    a_m = jnp.moveaxis(a, axis, 0)
    idx_m = jnp.moveaxis(idx, axis, 0)
    v_m = jnp.moveaxis(v, axis, 0)
    grid = jnp.indices(idx_m.shape[1:])
    out = a_m
    if mode == "add":
        out = out.at[(idx_m,) + tuple(jnp.broadcast_to(g, idx_m.shape) for g in grid)].add(v_m)
    else:
        out = out.at[(idx_m,) + tuple(jnp.broadcast_to(g, idx_m.shape) for g in grid)].multiply(v_m)
    return jnp.moveaxis(out, 0, axis)


def scatter(x, index, updates, overwrite=True, name=None):
    x, index, updates = as_tensor(x), as_tensor(index), as_tensor(updates)

    def fn(a, idx, upd, overwrite):
        idx = idx.reshape(-1)
        if overwrite:
            return a.at[idx].set(upd)
        return a.at[idx].add(upd)

    return eager_call("scatter", fn, [x, index, updates], {"overwrite": overwrite})


def scatter_nd_add(x, index, updates, name=None):
    x, index, updates = as_tensor(x), as_tensor(index), as_tensor(updates)

    def fn(a, idx, upd):
        idx_t = tuple(jnp.moveaxis(idx, -1, 0))
        return a.at[idx_t].add(upd)

    return eager_call("scatter_nd_add", fn, [x, index, updates])


def scatter_nd(index, updates, shape, name=None):
    index, updates = as_tensor(index), as_tensor(updates)
    return eager_call(
        "scatter_nd",
        lambda idx, upd, shape: jnp.zeros(shape, upd.dtype).at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd),
        [index, updates],
        {"shape": tuple(int(s) for s in shape)},
    )


def index_select(x, index, axis=0, name=None):
    x, index = as_tensor(x), as_tensor(index)
    return eager_call(
        "index_select", lambda a, idx, axis: jnp.take(a, idx, axis=axis), [x, index], {"axis": int(axis)}
    )


def index_sample(x, index):
    x, index = as_tensor(x), as_tensor(index)
    return eager_call(
        "index_sample", lambda a, idx: jnp.take_along_axis(a, idx, axis=1), [x, index]
    )


def index_add(x, index, axis, value, name=None):
    x, index, value = as_tensor(x), as_tensor(index), as_tensor(value)

    def fn(a, idx, v, axis):
        a_m = jnp.moveaxis(a, axis, 0)
        v_m = jnp.moveaxis(v, axis, 0)
        return jnp.moveaxis(a_m.at[idx].add(v_m), 0, axis)

    return eager_call("index_add", fn, [x, index, value], {"axis": int(axis)})


class _HashableArray:
    """Wrap an ndarray so it can live in the jit-cache attr key."""

    def __init__(self, arr):
        self.arr = np.asarray(arr)

    def __hash__(self):
        return hash((self.arr.shape, str(self.arr.dtype), self.arr.tobytes()))

    def __eq__(self, other):
        return (
            isinstance(other, _HashableArray)
            and self.arr.shape == other.arr.shape
            and np.array_equal(self.arr, other.arr)
        )


def masked_select(x, mask, name=None):
    """Differentiable: mask must be concrete (dynamic output shape), but the
    gather itself is a recorded op so gradients scatter back into x."""
    x, mask = as_tensor(x), as_tensor(mask)
    m = np.broadcast_to(np.asarray(mask._data), tuple(x.shape))
    flat_idx = np.flatnonzero(m)

    def fn(a, flat_idx):
        return jnp.take(a.reshape(-1), jnp.asarray(flat_idx.arr))

    return eager_call("masked_select", fn, [x], {"flat_idx": _HashableArray(flat_idx)})


def masked_fill(x, mask, value, name=None):
    x, mask = as_tensor(x), as_tensor(mask)
    if isinstance(value, Tensor):
        return eager_call(
            "masked_fill_t", lambda a, m, v: jnp.where(m, v.astype(a.dtype), a), [x, mask, value]
        )
    return eager_call(
        "masked_fill", lambda a, m, value: jnp.where(m, jnp.asarray(value, a.dtype), a), [x, mask], {"value": value}
    )


def where(condition, x=None, y=None, name=None):
    condition = as_tensor(condition)
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return eager_call(
        "where",
        lambda c, a, b: jnp.where(c, a, b),
        [condition, as_tensor(x), as_tensor(y)],
    )


def nonzero(x, as_tuple=False, name=None):
    x = as_tensor(x)
    nz = np.nonzero(np.asarray(x._data))  # dynamic shape → host
    if as_tuple:
        return tuple(Tensor(np.asarray(i, dtype=np.int64).reshape(-1, 1)) for i in nz)
    return Tensor(np.stack(nz, axis=1).astype(np.int64))


def slice(x, axes, starts, ends, name=None):
    x = as_tensor(x)
    axes = [int(a) for a in axes]
    starts = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in starts]
    ends = [int(e.item()) if isinstance(e, Tensor) else int(e) for e in ends]

    def fn(a, axes, starts, ends):
        for ax, st, en in zip(axes, starts, ends):
            dim = a.shape[ax]
            st2 = max(st + dim, 0) if st < 0 else min(st, dim)
            en2 = max(en + dim, 0) if en < 0 else min(en, dim)
            a = jax.lax.slice_in_dim(a, st2, en2, axis=ax)
        return a

    return eager_call("slice", fn, [x], {"axes": tuple(axes), "starts": tuple(starts), "ends": tuple(ends)})


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = as_tensor(x)
    import builtins as _b

    idx = [_b.slice(None)] * x.ndim
    for ax, st, en, sr in zip(axes, starts, ends, strides):
        st = int(st.item()) if isinstance(st, Tensor) else int(st)
        en = int(en.item()) if isinstance(en, Tensor) else int(en)
        sr = int(sr.item()) if isinstance(sr, Tensor) else int(sr)
        idx[int(ax)] = _b.slice(st, en, sr)
    return getitem(x, tuple(idx))


def crop(x, shape=None, offsets=None, name=None):
    x = as_tensor(x)
    shape = [int(s) for s in (shape or x.shape)]
    offsets = [int(o) for o in (offsets or [0] * x.ndim)]
    import builtins as _b

    idx = tuple(_b.slice(o, o + s) for o, s in zip(offsets, shape))
    return getitem(x, idx)


# -- python indexing ---------------------------------------------------------
def _norm_index(x, item):
    """Convert Tensors in an index expression to arrays; return hashability."""
    if not isinstance(item, tuple):
        item = (item,)
    tensors = []
    spec = []
    for it in item:
        if isinstance(it, Tensor):
            if it.dtype == np.dtype("bool"):
                spec.append(("bool_mask", np.asarray(it._data)))
            else:
                spec.append(("tensor", len(tensors)))
                tensors.append(it)
        elif isinstance(it, (list, np.ndarray)):
            arr = np.asarray(it)
            if arr.dtype == np.bool_:
                spec.append(("bool_mask", arr))
            else:
                spec.append(("array", arr))
        else:
            spec.append(("static", it))
    return spec, tensors


def getitem(x, item):
    x = as_tensor(x)
    spec, tensors = _norm_index(x, item)

    # Bool masks are concrete (dynamic output shape) so jnp resolves them at
    # trace time; keeping them inside the traced fn preserves the autograd
    # graph (reference: masked select is differentiable).
    def fn(a, *idx_arrays, spec=None):
        it = []
        for k, v in spec:
            if k == "static":
                it.append(v)
            elif k in ("array", "bool_mask"):
                it.append(v)
            else:
                it.append(idx_arrays[v])
        return a[tuple(it)]

    return eager_call("getitem", fn, [x] + tensors, {"spec": _FrozenSpec(spec)})


def setitem(x, item, value):
    """In-place assignment (reference: __setitem__ via the set_value op,
    ``paddle/fluid/operators/set_value_op.cc``).

    Functional under the hood: produces a new buffer and replaces ``x._data``;
    the autograd graph link is preserved by recording a scatter-style op.
    """
    spec, tensors = _norm_index(x, item)
    scalar = value if isinstance(value, (int, float, bool)) and not isinstance(value, Tensor) else None
    n_idx = len(tensors)

    def fn(a, *rest, spec=None, scalar=None, n_idx=0):
        it = []
        for k, v in spec:
            if k == "static":
                it.append(v)
            elif k in ("array", "bool_mask"):
                it.append(jnp.asarray(v))
            else:
                it.append(rest[v])
        if scalar is not None:
            val = jnp.asarray(scalar, a.dtype)
        else:
            val = rest[n_idx].astype(a.dtype)
        return a.at[tuple(it)].set(val)

    inputs = [x] + tensors
    if scalar is None:
        inputs = inputs + [as_tensor(value)]
    out = eager_call(
        "setitem", fn, inputs, {"spec": _FrozenSpec(spec), "scalar": scalar, "n_idx": n_idx}
    )
    x._data = out._data
    x._grad_node = out._grad_node
    x._out_index = out._out_index
    if out._grad_node is not None:
        x.stop_gradient = False
    return x


class _FrozenSpec:
    """Hashable wrapper for an index spec (may contain ndarrays/slices)."""

    def __init__(self, spec):
        self.spec = spec

    def __iter__(self):
        return iter(self.spec)

    def _key(self):
        import builtins as _b

        out = []
        for k, v in self.spec:
            if isinstance(v, np.ndarray):
                out.append((k, v.shape, v.tobytes()))
            elif isinstance(v, _b.slice):
                out.append((k, "slice", v.start, v.stop, v.step))
            elif v is Ellipsis:
                out.append((k, "ellipsis"))
            else:
                out.append((k, v))
        return tuple(out)

    def __hash__(self):
        return hash(self._key())

    def __eq__(self, other):
        return isinstance(other, _FrozenSpec) and self._key() == other._key()


def _freeze_spec(spec):
    return _FrozenSpec(spec)


# -- search / sort -----------------------------------------------------------
def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    x = as_tensor(x)
    if isinstance(k, Tensor):
        k = int(k.item())

    def fn(a, k, axis, largest):
        src = a if largest else -a
        src_m = jnp.moveaxis(src, axis, -1)
        vals, idx = jax.lax.top_k(src_m, k)
        if not largest:
            vals = -vals
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
        # re-gather values differentiably
        orig = jnp.take_along_axis(a, idx.astype(jnp.int32), axis=axis)
        return orig, idx.astype(np.int64)

    out = eager_call("topk", fn, [x], {"k": k, "axis": int(axis), "largest": largest}, nondiff_outputs=[1])
    return out[0], out[1]


def sort(x, axis=-1, descending=False, name=None):
    x = as_tensor(x)

    def fn(a, axis, descending):
        idx = jnp.argsort(a, axis=axis, descending=descending)
        return jnp.take_along_axis(a, idx, axis=axis)

    return eager_call("sort", fn, [x], {"axis": int(axis), "descending": descending})


def argsort(x, axis=-1, descending=False, name=None):
    x = as_tensor(x)
    return eager_call(
        "argsort",
        lambda a, axis, descending: jnp.argsort(a, axis=axis, descending=descending).astype(np.int64),
        [x],
        {"axis": int(axis), "descending": descending},
        differentiable=False,
    )


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    ss, v = as_tensor(sorted_sequence), as_tensor(values)

    def fn(a, b, right, out_int32):
        side = "right" if right else "left"
        if a.ndim == 1:
            r = jnp.searchsorted(a, b, side=side)
        else:
            r = jax.vmap(lambda row, val: jnp.searchsorted(row, val, side=side))(
                a.reshape(-1, a.shape[-1]), b.reshape(-1, b.shape[-1])
            ).reshape(b.shape)
        return r.astype(np.int32 if out_int32 else np.int64)

    return eager_call("searchsorted", fn, [ss, v], {"right": right, "out_int32": out_int32}, differentiable=False)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    x = as_tensor(x)
    res = np.unique(
        np.asarray(x._data), return_index=return_index, return_inverse=return_inverse, return_counts=return_counts, axis=axis
    )
    if not (return_index or return_inverse or return_counts):
        return Tensor(res)
    out = [Tensor(r if i == 0 else r.astype(np.int64)) for i, r in enumerate(res)]
    return tuple(out)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    x = np.asarray(as_tensor(x)._data)
    if axis is None:
        x = x.reshape(-1)
    mask = np.empty(x.shape[0], dtype=bool)
    mask[0] = True
    mask[1:] = np.any(x[1:] != x[:-1], axis=tuple(range(1, x.ndim))) if x.ndim > 1 else x[1:] != x[:-1]
    out = Tensor(x[mask])
    rets = [out]
    if return_inverse:
        rets.append(Tensor(np.cumsum(mask) - 1))
    if return_counts:
        idx = np.flatnonzero(mask)
        counts = np.diff(np.append(idx, x.shape[0]))
        rets.append(Tensor(counts.astype(np.int64)))
    return rets[0] if len(rets) == 1 else tuple(rets)


def bincount(x, weights=None, minlength=0, name=None):
    x = as_tensor(x)
    w = np.asarray(as_tensor(weights)._data) if weights is not None else None
    return Tensor(np.bincount(np.asarray(x._data), weights=w, minlength=minlength))


def histogram(input, bins=100, min=0, max=0, name=None):
    x = np.asarray(as_tensor(input)._data)
    if min == 0 and max == 0:
        min, max = float(x.min()), float(x.max())
    hist, _ = np.histogram(x, bins=bins, range=(min, max))
    return Tensor(hist.astype(np.int64))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = as_tensor(x)
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]

    def fn(a, pad, mode, value, data_format):
        nd = a.ndim
        if len(pad) == nd * 2:
            width = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # paddle nn.functional.pad convention: pad applies to last dims
            # (pairs, reversed for NCHW spatial dims)
            n_spatial = len(pad) // 2
            width = [(0, 0)] * (nd - n_spatial)
            if data_format.endswith("C") and nd - 2 == n_spatial:  # NHWC-style
                width = [(0, 0)] + [(pad[2 * i], pad[2 * i + 1]) for i in range(n_spatial)] + [(0, 0)]
            else:
                width += [(pad[2 * i], pad[2 * i + 1]) for i in range(n_spatial)]
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, width, mode=jmode, constant_values=value)
        return jnp.pad(a, width, mode=jmode)

    return eager_call(
        "pad", fn, [x], {"pad": tuple(pad), "mode": mode, "value": value, "data_format": data_format}
    )


def atleast_1d(*inputs):
    outs = [Tensor(jnp.atleast_1d(as_tensor(t)._data)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs):
    outs = [Tensor(jnp.atleast_2d(as_tensor(t)._data)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs):
    outs = [Tensor(jnp.atleast_3d(as_tensor(t)._data)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def as_real(x, name=None):
    x = as_tensor(x)
    xa = _concrete(x._data)
    return Tensor(jnp.stack([jnp.real(xa), jnp.imag(xa)], axis=-1))


def as_complex(x, name=None):
    x = as_tensor(x)
    return eager_call("as_complex", lambda a: jax.lax.complex(a[..., 0], a[..., 1]), [x])


def real(x, name=None):
    return eager_call("real", jnp.real, [as_tensor(x)])


def imag(x, name=None):
    return eager_call("imag", jnp.imag, [as_tensor(x)])


def conj(x, name=None):
    return eager_call("conj", jnp.conj, [as_tensor(x)])


def moveaxis(x, source, destination, name=None):
    return eager_call(
        "moveaxis",
        lambda a, s, d: jnp.moveaxis(a, s, d),
        [as_tensor(x)],
        {"s": _axes(source), "d": _axes(destination)},
    )


def swapaxes(x, axis0, axis1, name=None):
    return eager_call(
        "swapaxes", lambda a, a0, a1: jnp.swapaxes(a, a0, a1), [as_tensor(x)], {"a0": int(axis0), "a1": int(axis1)}
    )


transpose_ = swapaxes
