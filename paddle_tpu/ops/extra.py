"""Hand-written op additions that need more than a yaml one-liner.

einsum (reference ``python/paddle/tensor/einsum.py``), segment reductions
(reference ``paddle/fluid/operators/segment_ops/`` — paddle.incubate.segment_*
and paddle.geometric.segment_*), histogramdd.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import as_tensor, eager_call


def einsum(equation, *operands, **kwargs):
    """paddle.einsum — XLA contracts straight onto the MXU.
    Reference: python/paddle/tensor/einsum.py (1,000+ LoC planner); jnp's
    opt_einsum planner subsumes it."""
    if operands and isinstance(operands[0], (list, tuple)):
        operands = tuple(operands[0])
    tensors = [as_tensor(t) for t in operands]
    return eager_call(
        "einsum",
        lambda *arrays, equation=None: jnp.einsum(equation, *arrays),
        tensors, attrs={"equation": equation},
    )


def _segment(name, reducer):
    def op(data, segment_ids, name=None):
        t = as_tensor(data)
        seg = as_tensor(segment_ids)
        # num_segments must be static for XLA: read it from concrete ids
        # (matches the reference kernel, which sizes the output on host)
        ids = np.asarray(seg._data)
        num = int(ids.max()) + 1 if ids.size else 0
        return eager_call(
            f"segment_{name}",
            lambda d, s, num=0: reducer(d, s, num),
            [t, seg], attrs={"num": num}, nondiff_outputs=(),
        )

    op.__name__ = f"segment_{name}"
    op.__doc__ = (
        f"paddle.incubate.segment_{name} "
        "(reference paddle/fluid/operators/segment_ops)."
    )
    return op


def _seg_mean(d, s, num):
    tot = jax.ops.segment_sum(d, s, num_segments=num)
    cnt = jax.ops.segment_sum(jnp.ones_like(d), s, num_segments=num)
    return tot / jnp.maximum(cnt, 1)


segment_sum = _segment("sum", lambda d, s, num: jax.ops.segment_sum(d, s, num_segments=num))
segment_mean = _segment("mean", _seg_mean)
segment_max = _segment("max", lambda d, s, num: jax.ops.segment_max(d, s, num_segments=num))
segment_min = _segment("min", lambda d, s, num: jax.ops.segment_min(d, s, num_segments=num))


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    t = as_tensor(x)
    args = [t]
    if weights is not None:
        args.append(as_tensor(weights))

    def fn(a, *w, bins=10, ranges=None, density=False):
        h, edges = jnp.histogramdd(
            a, bins=bins, range=ranges, density=density,
            weights=w[0] if w else None,
        )
        return (h,) + tuple(edges)

    outs = eager_call(
        "histogramdd", fn, args,
        attrs={"bins": bins, "ranges": ranges, "density": density},
        differentiable=False,
    )
    return outs[0], list(outs[1:])


__all__ = ["einsum", "segment_sum", "segment_mean", "segment_max", "segment_min", "histogramdd"]
