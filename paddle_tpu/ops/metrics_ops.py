"""One-shot metric ops (reference ``operators/metrics/`` + ``edit_distance_op``
+ ``mean_iou_op`` + ``positive_negative_pair_op``).

The reference's metric ops are stateful accumulators driven by the trainer
loop; the streaming role here is filled by ``paddle_tpu.metric`` classes.
These are the OP-surface equivalents: pure functions over a batch, jit-safe
(static shapes, lax loops), usable inside compiled evaluation steps.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.dispatch import as_tensor, eager_call

__all__ = ["auc", "edit_distance", "mean_iou", "precision_recall",
           "positive_negative_pair"]


def auc(pred, label, name=None):
    """ROC-AUC of binary scores via the rank statistic (metrics/auc_op.cc
    computes the same integral from threshold buckets).
    pred: (N,) scores; label: (N,) {0,1}. Empty class -> 0.5."""
    pred, label = as_tensor(pred), as_tensor(label)

    def fn(p, y):
        p = p.reshape(-1).astype(jnp.float32)
        y = y.reshape(-1)
        # average ranks under ties (a tied pos/neg pair counts 0.5, like the
        # reference's bucketed integral): r_i = (#{p<p_i} + #{p<=p_i} + 1)/2.
        # searchsorted on the sorted scores gives both counts in O(N log N) —
        # the N x N comparison matrices would be ~10 GB at N ~ 1e5.
        sp = jnp.sort(p)
        less = jnp.searchsorted(sp, p, side="left").astype(jnp.float32)
        leq = jnp.searchsorted(sp, p, side="right").astype(jnp.float32)
        ranks = (less + leq + 1.0) / 2.0
        pos = (y > 0).astype(jnp.float32)
        npos = pos.sum()
        nneg = p.size - npos
        s = (ranks * pos).sum() - npos * (npos + 1) / 2.0
        return jnp.where(npos * nneg > 0, s / jnp.maximum(npos * nneg, 1.0), 0.5)

    return eager_call("metric_auc", fn, [pred, label], differentiable=False)


def edit_distance(hyp, hyp_length, ref, ref_length, normalized=True, name=None):
    """Batched Levenshtein distance over padded id sequences
    (edit_distance_op.cc). hyp: (B, Th), ref: (B, Tr) + lengths."""
    hyp, hyp_length = as_tensor(hyp), as_tensor(hyp_length)
    ref, ref_length = as_tensor(ref), as_tensor(ref_length)

    def fn(h, hl, r, rl, normalized):
        th, tr = h.shape[1], r.shape[1]

        def one(hrow, hn, rrow, rn):
            row0 = jnp.arange(tr + 1, dtype=jnp.float32)

            def step(i, row):
                # DP row i+1: d[i+1, j]
                def col(j, acc):
                    row_new, diag = acc
                    cost = jnp.where(
                        (hrow[i] == rrow[j]) | (j >= rn), 0.0, 1.0)
                    ins = row_new[j] + jnp.where(j < rn, 1.0, 0.0)
                    dele = row[j + 1] + 1.0
                    sub = diag + cost
                    v = jnp.where(j < rn, jnp.minimum(jnp.minimum(ins, dele), sub),
                                  row_new[j])
                    return row_new.at[j + 1].set(v), row[j + 1]

                init = row.at[0].set(row[0] + 1.0)
                row_new, _ = lax.fori_loop(0, tr, col, (init, row[0]))
                return jnp.where(i < hn, row_new, row)

            row = lax.fori_loop(0, th, step, row0)
            d = row[jnp.clip(rn, 0, tr)]
            return jnp.where(normalized, d / jnp.maximum(rn, 1).astype(jnp.float32), d)

        return jax.vmap(one)(h, hl, r, rl)

    return eager_call("edit_distance", fn, [hyp, hyp_length, ref, ref_length],
                      {"normalized": bool(normalized)}, differentiable=False)


def mean_iou(pred, label, num_classes, name=None):
    """Mean intersection-over-union across classes (mean_iou_op.cc).
    pred/label: int class maps of equal shape."""
    pred, label = as_tensor(pred), as_tensor(label)

    def fn(p, y, num_classes):
        p = p.reshape(-1)
        y = y.reshape(-1)
        oh_p = jax.nn.one_hot(p, num_classes, dtype=jnp.float32)
        oh_y = jax.nn.one_hot(y, num_classes, dtype=jnp.float32)
        inter = (oh_p * oh_y).sum(0)
        union = oh_p.sum(0) + oh_y.sum(0) - inter
        present = union > 0
        iou = jnp.where(present, inter / jnp.maximum(union, 1.0), 0.0)
        return iou.sum() / jnp.maximum(present.sum(), 1)

    return eager_call("mean_iou", fn, [pred, label],
                      {"num_classes": int(num_classes)}, differentiable=False)


def precision_recall(pred, label, num_classes, name=None):
    """Per-batch macro precision/recall/F1 (metrics/precision_recall_op.cc).
    pred: (N,) predicted classes; label: (N,). Returns (precision, recall, f1)."""
    pred, label = as_tensor(pred), as_tensor(label)

    def fn(p, y, num_classes):
        p = p.reshape(-1)
        y = y.reshape(-1)
        oh_p = jax.nn.one_hot(p, num_classes, dtype=jnp.float32)
        oh_y = jax.nn.one_hot(y, num_classes, dtype=jnp.float32)
        tp = (oh_p * oh_y).sum(0)
        fp = oh_p.sum(0) - tp
        fn_ = oh_y.sum(0) - tp
        present = oh_y.sum(0) > 0
        prec = jnp.where(present, tp / jnp.maximum(tp + fp, 1.0), 0.0)
        rec = jnp.where(present, tp / jnp.maximum(tp + fn_, 1.0), 0.0)
        npres = jnp.maximum(present.sum(), 1)
        mp, mr = prec.sum() / npres, rec.sum() / npres
        f1 = jnp.where(mp + mr > 0, 2 * mp * mr / jnp.maximum(mp + mr, 1e-12), 0.0)
        return mp, mr, f1

    return eager_call("precision_recall", fn, [pred, label],
                      {"num_classes": int(num_classes)}, differentiable=False)


def positive_negative_pair(score, label, query_id, name=None):
    """Count correctly/incorrectly ordered pairs within each query group
    (positive_negative_pair_op.cc). score/label/query_id: (N,).
    Returns (positive_pairs, negative_pairs, neutral_pairs)."""
    score, label = as_tensor(score), as_tensor(label)
    query_id = as_tensor(query_id)

    def fn(s, y, q):
        s = s.reshape(-1).astype(jnp.float32)
        y = y.reshape(-1).astype(jnp.float32)
        q = q.reshape(-1)
        same_q = q[:, None] == q[None, :]
        upper = jnp.triu(jnp.ones((s.size, s.size), bool), 1)
        pair = same_q & upper & (y[:, None] != y[None, :])
        better = (y[:, None] > y[None, :])
        s_diff = s[:, None] - s[None, :]
        pos = (pair & (jnp.sign(s_diff) == jnp.sign(jnp.where(better, 1.0, -1.0)))).sum()
        neu = (pair & (s_diff == 0)).sum()
        neg = pair.sum() - pos - neu
        return pos, neg, neu

    return eager_call("positive_negative_pair", fn, [score, label, query_id],
                      differentiable=False)
