"""Optimizers.

Parity: reference ``python/paddle/optimizer/`` (Adam/AdamW/SGD/Momentum/LAMB/
RMSProp/Adagrad/Adadelta/Adamax + lr schedulers) whose update rules are C++/
CUDA kernels (``paddle/fluid/operators/optimizers/``). Here each rule is one
pure XLA function over (param, grad, state) — usable both eagerly (jitted
per-param) and inside a fully-fused compiled train step
(paddle_tpu.jit.CompiledTrainStep), where forward+backward+update become a
single executable.
"""
from __future__ import annotations

import itertools
import weakref
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.engine import no_grad
from ..core.tensor import Tensor
from . import lr as lr_mod
from .lr import LRScheduler


class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


_optimizer_uid = itertools.count()


class Optimizer:
    # Whether _rule is a purely ELEMENTWISE map over (param, grad, state)
    # (no cross-element reductions like norms). Elementwise rules can run on
    # an arbitrary flat shard of the parameters, which is what the ZeRO-1
    # sharded weight update (fleet ShardedWeightUpdate) requires. Opt-IN:
    # the base defaults to False so a user-defined rule with norms/means
    # falls back to the replicated update instead of silently training
    # wrong on a flat shard; the shipped elementwise rules set it True.
    _elementwise_rule = False

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None, grad_clip=None, name=None, **kwargs):
        self._parameter_list = list(parameters) if parameters is not None else None
        self._learning_rate = learning_rate
        if isinstance(weight_decay, (float, int)):
            self.regularization = L2Decay(float(weight_decay))
        else:
            self.regularization = weight_decay
        self._grad_clip = grad_clip
        self._accumulators: Dict[int, dict] = {}
        self._step_count = 0
        self._jitted_rule = None
        self._uid = next(_optimizer_uid)  # lazy-flush cache key (id() can be reused)
        # per-param lazy-step plan memo (async runtime host-work cut): the
        # record key and rule closure are rebuilt only when the plan inputs
        # (state keys, wd gate, per-param lr scale) actually change
        self._lazy_plans: Dict[int, tuple] = {}

    # -- lr ---------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # -- pure rule API (implemented by subclasses) -------------------------
    def _init_accums(self, p_arr) -> dict:
        return {}

    def _rule(self, p, g, st: dict, lr, t, wd_scale=1.0):
        """Pure: (param, grad, state, lr, step, wd on/off) → (new_p, new_state)."""
        raise NotImplementedError

    def _wd_on(self, p) -> float:
        """Per-parameter decay gate (AdamW apply_decay_param_fun parity)."""
        return 1.0

    # -- state ------------------------------------------------------------
    def _state(self, p) -> dict:
        return self._accumulators.setdefault(id(p), {})

    def state_dict(self):
        out = {}
        for p in self._parameter_list or []:
            st = self._accumulators.get(id(p))
            if st:
                for k, v in st.items():
                    out[f"{p.name}.{k}"] = v if isinstance(v, Tensor) else Tensor(v)
        out["@step"] = self._step_count
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state_dict):
        self._step_count = int(state_dict.get("@step", 0))
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        for p in self._parameter_list or []:
            st = self._state(p)
            prefix = f"{p.name}."
            for k, v in state_dict.items():
                if isinstance(k, str) and k.startswith(prefix):
                    st[k[len(prefix):]] = v._data if isinstance(v, Tensor) else jnp.asarray(v)

    load_state_dict = set_state_dict

    # -- eager step --------------------------------------------------------
    def _collect(self):
        params = self._parameter_list or []
        pg = [(p, p.grad) for p in params if not p.stop_gradient and p.grad is not None]
        if self._grad_clip is not None:
            pg = self._grad_clip(pg)
        return pg

    def _regularize_arr(self, p_arr, g):
        if isinstance(self.regularization, L2Decay) and self.regularization.coeff:
            return g + self.regularization.coeff * p_arr
        if isinstance(self.regularization, L1Decay) and self.regularization.coeff:
            return g + self.regularization.coeff * jnp.sign(p_arr)
        return g

    @no_grad()
    def step(self):
        self._step_count += 1
        from ..core import lazy as lazy_mod

        if lazy_mod.lazy_enabled():
            return self._lazy_step()
        lr = jnp.asarray(self.get_lr(), dtype=jnp.float32)
        t = jnp.asarray(float(self._step_count), dtype=jnp.float32)
        if self._jitted_rule is None:
            def full_rule(p, g, st, lr, t, wd_scale):
                g = self._regularize_arr(p, g)
                return self._rule(p, g, st, lr, t, wd_scale)

            self._jitted_rule = jax.jit(full_rule)
        for p, grad in self._collect():
            g = grad._data if isinstance(grad, Tensor) else grad
            if g.dtype != p._data.dtype:
                g = g.astype(p._data.dtype)
            st = self._state(p)
            if not st:
                st.update(self._init_accums(p._data))
            p_lr = lr * p.optimize_attr.get("learning_rate", 1.0) if hasattr(p, "optimize_attr") else lr
            new_p, new_st = self._jitted_rule(p._data, g, st, p_lr, t, self._wd_on(p))
            st.update(new_st)
            p._set_data(new_p)

    def _lazy_plan(self, p, keys, wd, plr):
        """Memoized per-param lazy-step plan: the rule closure + record key
        survive across steps (the async runtime's host-work cut — rebuilding
        them per step was ~1/3 of the optimizer's per-step Python). The plan
        is invalidated when its inputs (state keys, wd gate, per-param lr
        scale) change, and an id()-reuse collision is caught by the weakref
        identity check."""
        plan = self._lazy_plans.get(id(p))
        if (
            plan is not None
            and plan[0]() is p
            and plan[1] == (keys, wd, plr)
        ):
            return plan
        # close over a WEAKREF: the flush-executable cache retains node
        # fns, and a strong `self` here would pin the whole optimizer
        # (params + moments) long after the user discards it
        wself = weakref.ref(self)

        def rule_flat(p_a, g_a, lr_a, t_a, *stv, _keys=keys, _wd=wd, _s=plr):
            opt_ = wself()
            if g_a.dtype != p_a.dtype:
                g_a = g_a.astype(p_a.dtype)
            g_a = opt_._regularize_arr(p_a, g_a)
            new_p, new_st = opt_._rule(
                p_a, g_a, dict(zip(_keys, stv)), lr_a * _s, t_a, _wd
            )
            return (new_p,) + tuple(new_st[k] for k in _keys)

        plan = (
            weakref.ref(p),
            (keys, wd, plr),
            rule_flat,
            ("opt", type(self).__name__, self._uid, keys, wd, plr),
        )
        self._lazy_plans[id(p)] = plan
        return plan

    def _lazy_step(self):
        """Record the update rule into the lazy graph per parameter, so the
        whole optimizer step fuses into the same flushed XLA computation as
        the backward pass (one executable per train iteration)."""
        from ..core import lazy as lazy_mod

        lr = np.float32(self.get_lr())
        t = np.float32(self._step_count)
        for p, grad in self._collect():
            g = grad._data if isinstance(grad, Tensor) else grad
            st = self._state(p)
            if not st:
                # first step: params are still concrete (freshly initialized)
                st.update(self._init_accums(
                    jax.ShapeDtypeStruct(tuple(p._data.shape), p._data.dtype)
                ))
            keys = tuple(sorted(st))
            wd = float(self._wd_on(p))
            plr = float(p.optimize_attr.get("learning_rate", 1.0)) if hasattr(p, "optimize_attr") else 1.0
            _, _, rule_flat, rec_key = self._lazy_plan(p, keys, wd, plr)
            outs, _ = lazy_mod.record(
                "opt_" + type(self).__name__,
                rule_flat,
                [p._data, g, lr, t] + [st[k] for k in keys],
                key=rec_key,
            )
            # rebind param + moments through the graph: the displaced buffers
            # become donation candidates, so the flushed executable updates
            # weights and optimizer state in place (no ~3x-model-size copy)
            p._set_data(outs[0])
            for k, v in zip(keys, outs[1:]):
                lazy_mod.note_rebound(st[k])
                st[k] = v
        # step boundary: flush now so every train iteration is ONE stable
        # graph signature ([fwd+bwd+opt]) that hits the executable cache,
        # instead of an ever-growing pending graph compiled once per flush
        lazy_mod.flush()

    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list or []:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    # -- functional (fused-train-step) API ---------------------------------
    def _functional_state(self, params):
        from ..core import lazy as lazy_mod

        accums = []
        for p in params:
            st = self._state(p)
            if not st:
                st.update(self._init_accums(lazy_mod.concrete(p._data)))
            # materialize: jit callers (CompiledTrainStep/engines) require
            # real buffers, and eager lazy steps store LazyArrays here
            accums.append({k: lazy_mod.concrete(v) for k, v in st.items()})
        return {"t": jnp.asarray(float(self._step_count + 1), jnp.float32), "accums": accums}

    def _functional_update(self, param_arrays, grads, state, lr, params=None):
        """Pure; traceable inside jit/pjit. ``params`` is static metadata."""
        t = state["t"]
        new_params, new_accums = [], []
        for i, (p, g, st) in enumerate(zip(param_arrays, grads, state["accums"])):
            if g is None:
                new_params.append(p)
                new_accums.append(st)
                continue
            g = g.astype(p.dtype) if g.dtype != p.dtype else g
            g = self._regularize_arr(p, g)
            wd = self._wd_on(params[i]) if params is not None else 1.0
            plr = lr
            if params is not None and hasattr(params[i], "optimize_attr"):
                plr = lr * params[i].optimize_attr.get("learning_rate", 1.0)
            new_p, new_st = self._rule(p, g, st, plr, t, wd)
            new_params.append(new_p)
            new_accums.append(new_st)
        return new_params, {"t": t + 1.0, "accums": new_accums}

    def _functional_restore(self, params, state):
        for p, st in zip(params, state["accums"]):
            self._accumulators[id(p)] = dict(st)


class SGD(Optimizer):
    _elementwise_rule = True
    def _rule(self, p, g, st, lr, t, wd_scale=1.0):
        return p - lr.astype(p.dtype) * g, st


class Momentum(Optimizer):
    _elementwise_rule = True
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None, use_nesterov=False, weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _init_accums(self, p_arr):
        return {"velocity": jnp.zeros_like(p_arr)}

    def _rule(self, p, g, st, lr, t, wd_scale=1.0):
        v = self._momentum * st["velocity"] + g
        if self._use_nesterov:
            new_p = p - (g + self._momentum * v) * lr.astype(p.dtype)
        else:
            new_p = p - lr.astype(p.dtype) * v
        return new_p, {"velocity": v}


class Adam(Optimizer):
    _elementwise_rule = True
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08, parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False, multi_precision=False, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1 = float(beta1.item()) if isinstance(beta1, Tensor) else beta1
        self._beta2 = float(beta2.item()) if isinstance(beta2, Tensor) else beta2
        self._epsilon = epsilon

    def _init_accums(self, p_arr):
        return {"moment1": jnp.zeros_like(p_arr), "moment2": jnp.zeros_like(p_arr)}

    def _rule(self, p, g, st, lr, t, wd_scale=1.0):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * st["moment1"] + (1 - b1) * g
        v = b2 * st["moment2"] + (1 - b2) * jnp.square(g)
        bc1 = 1 - jnp.power(b1, t)
        bc2 = 1 - jnp.power(b2, t)
        lr_t = (lr * jnp.sqrt(bc2) / bc1).astype(p.dtype)
        new_p = p - lr_t * m / (jnp.sqrt(v) + eps)
        return new_p, {"moment1": m, "moment2": v}


class AdamW(Adam):
    """Decoupled weight decay (reference adamw_op.cc: decay applied to param
    before the Adam update)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08, parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None, grad_clip=None, lazy_mode=False, multi_precision=False, name=None, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters, None, grad_clip, name=name)
        self._wd = weight_decay.coeff if isinstance(weight_decay, (L1Decay, L2Decay)) else float(weight_decay)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _wd_on(self, p):
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(p.name):
            return 0.0
        return 1.0

    def _rule(self, p, g, st, lr, t, wd_scale=1.0):
        p = p * (1 - lr.astype(p.dtype) * self._wd * wd_scale)
        return super()._rule(p, g, st, lr, t)


class Adamax(Optimizer):
    _elementwise_rule = True
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08, parameters=None, weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_accums(self, p_arr):
        return {"moment": jnp.zeros_like(p_arr), "inf_norm": jnp.zeros_like(p_arr)}

    def _rule(self, p, g, st, lr, t, wd_scale=1.0):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * st["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * st["inf_norm"], jnp.abs(g))
        lr_t = (lr / (1 - jnp.power(b1, t))).astype(p.dtype)
        return p - lr_t * m / (u + eps), {"moment": m, "inf_norm": u}


class RMSProp(Optimizer):
    _elementwise_rule = True
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-06, momentum=0.0, centered=False, parameters=None, weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon, self._momentum, self._centered = rho, epsilon, momentum, centered

    def _init_accums(self, p_arr):
        return {
            "mean_square": jnp.zeros_like(p_arr),
            "momentum": jnp.zeros_like(p_arr),
            "mean_grad": jnp.zeros_like(p_arr),
        }

    def _rule(self, p, g, st, lr, t, wd_scale=1.0):
        rho, eps = self._rho, self._epsilon
        ms = rho * st["mean_square"] + (1 - rho) * jnp.square(g)
        mg = rho * st["mean_grad"] + (1 - rho) * g if self._centered else st["mean_grad"]
        denom = ms - jnp.square(mg) if self._centered else ms
        mom = self._momentum * st["momentum"] + lr.astype(p.dtype) * g / jnp.sqrt(denom + eps)
        return p - mom, {"mean_square": ms, "momentum": mom, "mean_grad": mg}


class Adagrad(Optimizer):
    _elementwise_rule = True
    def __init__(self, learning_rate, epsilon=1e-06, parameters=None, weight_decay=None, grad_clip=None, initial_accumulator_value=0.0, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_accums(self, p_arr):
        return {"moment": jnp.full_like(p_arr, self._init_acc)}

    def _rule(self, p, g, st, lr, t, wd_scale=1.0):
        m = st["moment"] + jnp.square(g)
        return p - lr.astype(p.dtype) * g / (jnp.sqrt(m) + self._epsilon), {"moment": m}


class Adadelta(Optimizer):
    _elementwise_rule = True
    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95, parameters=None, weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon, self._rho = epsilon, rho

    def _init_accums(self, p_arr):
        return {"avg_squared_grad": jnp.zeros_like(p_arr), "avg_squared_update": jnp.zeros_like(p_arr)}

    def _rule(self, p, g, st, lr, t, wd_scale=1.0):
        rho, eps = self._rho, self._epsilon
        asg = rho * st["avg_squared_grad"] + (1 - rho) * jnp.square(g)
        update = jnp.sqrt(st["avg_squared_update"] + eps) / jnp.sqrt(asg + eps) * g
        asu = rho * st["avg_squared_update"] + (1 - rho) * jnp.square(update)
        return p - lr.astype(p.dtype) * update, {"avg_squared_grad": asg, "avg_squared_update": asu}


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999, epsilon=1e-06, parameters=None, grad_clip=None, exclude_from_weight_decay_fn=None, name=None, **kw):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _wd_on(self, p):
        if self._exclude_fn is not None and self._exclude_fn(p):
            return 0.0
        return 1.0

    def _init_accums(self, p_arr):
        return {"moment1": jnp.zeros_like(p_arr), "moment2": jnp.zeros_like(p_arr)}

    def _rule(self, p, g, st, lr, t, wd_scale=1.0):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * st["moment1"] + (1 - b1) * g
        v = b2 * st["moment2"] + (1 - b2) * jnp.square(g)
        m_hat = m / (1 - jnp.power(b1, t))
        v_hat = v / (1 - jnp.power(b2, t))
        r = m_hat / (jnp.sqrt(v_hat) + eps) + self._wd * wd_scale * p
        w_norm = jnp.linalg.norm(p.astype(jnp.float32))
        r_norm = jnp.linalg.norm(r.astype(jnp.float32))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0).astype(p.dtype)
        return p - lr.astype(p.dtype) * trust * r, {"moment1": m, "moment2": v}


class LarsMomentum(Optimizer):
    """LARS (reference lars_momentum_op.cc)."""


    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001, lars_weight_decay=0.0005, parameters=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay

    def _init_accums(self, p_arr):
        return {"velocity": jnp.zeros_like(p_arr)}

    def _rule(self, p, g, st, lr, t, wd_scale=1.0):
        w_norm = jnp.linalg.norm(p.astype(jnp.float32))
        g_norm = jnp.linalg.norm(g.astype(jnp.float32))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._lars_coeff * w_norm / (g_norm + self._lars_wd * w_norm + 1e-12),
            1.0,
        )
        g = g + self._lars_wd * p
        v = self._momentum * st["velocity"] + (lr * local_lr).astype(p.dtype) * g
        return p - v, {"velocity": v}
