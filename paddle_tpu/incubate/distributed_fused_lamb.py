"""DistributedFusedLamb — flat-buffer LAMB with global-norm clipping.

Reference: ``python/paddle/incubate/optimizer/distributed_fused_lamb.py`` +
``operators/optimizers/distributed_fused_lamb_op.cu`` — LAMB over ONE fused
parameter buffer with a global gradient-norm clip, moments sharded across
data-parallel ranks. The CUDA implementation exists to launch one kernel
instead of hundreds and to overlap the sharded moment update with NCCL;
on TPU the same goals are met differently:

* FUSION: all params concatenate into one flat f32 master buffer; the whole
  update (clip → moments → per-param trust ratios → write-back) is ONE
  jitted program, so XLA fuses it exactly like the hand-fused CUDA kernel.
* SHARDING: the flat buffers carry an optional ``jax.sharding`` spec over
  the 'dp' axis — under pjit/GSPMD the moment state then lives 1/N per
  device (the ZeRO-style moment sharding the reference gets from its
  manual shard bookkeeping). Composes with ShardingOptimizerStage1.
* CLIPPING: global grad norm over the flat buffer (the reference's
  fused_clip path), applied before the LAMB rule.

Per-parameter trust ratios use segment reductions over the flat buffer via
precomputed segment ids (static shapes; no ragged ops).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.engine import no_grad
from ..core.tensor import Tensor

__all__ = ["DistributedFusedLamb"]


class DistributedFusedLamb:
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None,
                 clip_after_allreduce=True, is_grad_scaled_by_nranks=False,
                 max_global_grad_norm=1.0, exclude_from_weight_decay_fn=None,
                 sharding_spec=None, name=None, **kw):
        self._lr = learning_rate
        self._wd = float(lamb_weight_decay)
        self._b1, self._b2, self._eps = float(beta1), float(beta2), float(epsilon)
        self._max_norm = float(max_global_grad_norm)
        self._exclude_fn = exclude_from_weight_decay_fn
        self._parameter_list = list(parameters) if parameters is not None else []
        self._sharding_spec = sharding_spec  # optional NamedSharding for states
        self._step_count = 0
        # flat layout: offsets per param into the fused buffer
        self._shapes = [tuple(p._data.shape) for p in self._parameter_list]
        sizes = [int(np.prod(s)) if s else 1 for s in self._shapes]
        self._offsets = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)
        self._total = int(self._offsets[-1])
        self._seg_ids = np.repeat(np.arange(len(sizes)), sizes)
        self._wd_mask = np.concatenate([
            np.full(sz, 0.0 if (self._exclude_fn and self._exclude_fn(p)) else 1.0,
                    np.float32)
            for p, sz in zip(self._parameter_list, sizes)
        ]) if sizes else np.zeros((0,), np.float32)
        self._m = None
        self._v = None
        self._master = None  # f32 master copy of params (bf16-safe)
        self._jit_step = None

    def get_lr(self):
        return float(self._lr() if callable(self._lr) else self._lr)

    def set_lr(self, lr):
        self._lr = float(lr)

    def _flatten(self, arrays):
        from ..core.lazy import concrete as _concrete

        return jnp.concatenate(
            [jnp.ravel(jnp.asarray(_concrete(a))).astype(jnp.float32) for a in arrays]
        ) if arrays else jnp.zeros((0,), jnp.float32)

    def _device_put(self, arr):
        if self._sharding_spec is not None:
            return jax.device_put(arr, self._sharding_spec)
        return arr

    def _build_step(self):
        seg = jnp.asarray(self._seg_ids)
        n_seg = len(self._shapes)
        wd_mask = jnp.asarray(self._wd_mask)
        b1, b2, eps, wd = self._b1, self._b2, self._eps, self._wd
        max_norm = self._max_norm

        def step(master, m, v, flat_g, lr, t):
            gn = jnp.sqrt(jnp.sum(flat_g * flat_g))
            if max_norm > 0:
                flat_g = flat_g * jnp.minimum(1.0, max_norm / (gn + 1e-12))
            m = b1 * m + (1 - b1) * flat_g
            v = b2 * v + (1 - b2) * flat_g * flat_g
            m_hat = m / (1 - jnp.power(b1, t))
            v_hat = v / (1 - jnp.power(b2, t))
            r = m_hat / (jnp.sqrt(v_hat) + eps) + wd * wd_mask * master
            # per-param trust ratios via segment reductions on the flat buffer
            w_sq = jax.ops.segment_sum(master * master, seg, num_segments=n_seg)
            r_sq = jax.ops.segment_sum(r * r, seg, num_segments=n_seg)
            w_n, r_n = jnp.sqrt(w_sq), jnp.sqrt(r_sq)
            trust = jnp.where((w_n > 0) & (r_n > 0), w_n / jnp.maximum(r_n, 1e-12), 1.0)
            master = master - lr * trust[seg] * r
            return master, m, v

        return jax.jit(step, donate_argnums=(0, 1, 2))

    @no_grad()
    def step(self):
        self._step_count += 1
        grads = []
        for p, sh in zip(self._parameter_list, self._shapes):
            g = p.grad._data if p.grad is not None else jnp.zeros(sh, p._data.dtype)
            grads.append(g)
        flat_g = self._flatten(grads)
        if self._master is None:
            self._master = self._device_put(
                self._flatten([p._data for p in self._parameter_list]))
            self._m = self._device_put(jnp.zeros_like(self._master))
            self._v = self._device_put(jnp.zeros_like(self._master))
        if self._jit_step is None:
            self._jit_step = self._build_step()
        self._master, self._m, self._v = self._jit_step(
            self._master, self._m, self._v, flat_g,
            jnp.float32(self.get_lr()), jnp.float32(self._step_count))
        for p, (lo, hi), sh in zip(
                self._parameter_list,
                zip(self._offsets[:-1], self._offsets[1:]), self._shapes):
            p._set_data(self._master[int(lo):int(hi)].reshape(sh).astype(p._data.dtype))

    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list:
            p.clear_grad()

    def state_dict(self):
        from ..core.lazy import concrete as _concrete

        out = {"@step": self._step_count}
        if self._m is not None:
            # COPIES: the live buffers are donated to the next jitted step,
            # which would delete a checkpoint that aliased them
            out["fused_moment1"] = Tensor(jnp.array(_concrete(self._m), copy=True))
            out["fused_moment2"] = Tensor(jnp.array(_concrete(self._v), copy=True))
            out["fused_master"] = Tensor(jnp.array(_concrete(self._master), copy=True))
        return out

    def set_state_dict(self, state):
        self._step_count = int(state.get("@step", 0))
        for key, attr in (("fused_moment1", "_m"), ("fused_moment2", "_v"),
                          ("fused_master", "_master")):
            if key in state:
                v = state[key]
                # COPY: the installed buffer gets donated by the next step;
                # aliasing the caller's checkpoint would delete it
                arr = jnp.array(v._data if isinstance(v, Tensor) else v, copy=True)
                if arr.shape != (self._total,):
                    raise ValueError(
                        f"{key} has {arr.shape}, expected ({self._total},) — "
                        "parameter layout changed since the checkpoint")
                setattr(self, attr, self._device_put(arr))
