"""paddle.incubate.operators (reference python/paddle/incubate/operators/).

- ``softmax_mask_fuse`` / ``softmax_mask_fuse_upper_triangle``: the fused
  CUDA kernels' role is filled by the yaml-generated ops (XLA fuses the
  mask+softmax into one pass on TPU).
- ``graph_send_recv``: message passing as gather + segment reduction —
  jit-safe, static output size.
- ``graph_khop_sampler``: neighborhood sampling is host-side index work
  (dynamic shapes), like the reference's CPU kernel.
- ``ResNetUnit``: the fused conv+bn(+add)+relu block as a layer; on TPU the
  fusion itself is XLA's (conv epilogues), the class provides the API.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .. import nn
from ..core.dispatch import as_tensor, eager_call
from ..core.tensor import Tensor
from ..ops.generated import GENERATED

__all__ = ["softmax_mask_fuse", "softmax_mask_fuse_upper_triangle",
           "graph_send_recv", "graph_khop_sampler", "ResNetUnit"]


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) in one fused pass (fused_softmax_mask_op.cu role)."""
    return GENERATED["fused_softmax_mask"](x, mask)


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Causal-masked softmax (fused_softmax_mask_upper_triangle_op.cu role)."""
    return GENERATED["fused_softmax_mask_upper_triangle"](x)


def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None,
                    name=None):
    """Gather rows at ``src_index``, reduce them onto ``dst_index``
    (graph_send_recv_op.cc). pool_type: sum | mean | max | min."""
    pt = pool_type.lower()
    if pt not in ("sum", "mean", "max", "min"):
        raise ValueError(f"pool_type must be sum|mean|max|min, got {pool_type}")
    x, src_index, dst_index = as_tensor(x), as_tensor(src_index), as_tensor(dst_index)
    n_out = int(out_size) if out_size is not None else int(x._data.shape[0])

    def fn(xv, si, di, pt, n_out):
        msgs = xv[si]
        seg = {"sum": jax.ops.segment_sum, "mean": jax.ops.segment_sum,
               "max": jax.ops.segment_max, "min": jax.ops.segment_min}[pt]
        out = seg(msgs, di, num_segments=n_out)
        if pt == "mean":
            cnt = jax.ops.segment_sum(jnp.ones_like(di, xv.dtype), di,
                                      num_segments=n_out)
            out = out / jnp.maximum(cnt, 1)[(...,) + (None,) * (xv.ndim - 1)]
        if pt in ("max", "min"):
            # untouched destinations hold +-inf sentinels: zero them like the
            # reference (empty receive -> 0)
            touched = jax.ops.segment_sum(jnp.ones_like(di, jnp.float32), di,
                                          num_segments=n_out) > 0
            out = jnp.where(touched[(...,) + (None,) * (xv.ndim - 1)], out, 0)
        return out

    return eager_call("graph_send_recv", fn, [x, src_index, dst_index],
                      {"pt": pt, "n_out": n_out})


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       return_eids=False, name=None):
    """K-hop neighbor sampling over a CSC graph (graph_khop_sampler_op.cc).
    Host-side (dynamic output shapes, like the reference CPU kernel).
    Returns (edge_src, edge_dst, sample_index, reindex_nodes)."""
    if return_eids:
        raise NotImplementedError(
            "return_eids=True is not supported by this build's sampler")
    row_v = np.asarray(as_tensor(row)._data)
    colptr_v = np.asarray(as_tensor(colptr)._data)
    seeds = np.asarray(as_tensor(input_nodes)._data).reshape(-1)
    # fresh randomness per call (stochastic neighborhoods, like the
    # reference op), seeded from the framework RNG stream
    from ..core import random as random_state

    rng = np.random.RandomState(
        int(np.asarray(random_state.next_key())[-1]) % (2 ** 31))
    cur = seeds
    all_src, all_dst = [], []
    for k in sample_sizes:
        nxt_src, nxt_dst = [], []
        for v in cur:
            beg, end = int(colptr_v[v]), int(colptr_v[v + 1])
            neigh = row_v[beg:end]
            if len(neigh) > k:
                neigh = rng.choice(neigh, size=k, replace=False)
            nxt_src.extend(int(u) for u in neigh)
            nxt_dst.extend(int(v) for _ in range(len(neigh)))
        all_src.extend(nxt_src)
        all_dst.extend(nxt_dst)
        cur = np.unique(np.asarray(nxt_src, np.int64)) if nxt_src else np.empty(0, np.int64)
    src = np.asarray(all_src, np.int64)
    dst = np.asarray(all_dst, np.int64)
    uniq = np.unique(np.concatenate([seeds, src, dst])) if src.size else seeds
    remap = {int(g): i for i, g in enumerate(uniq)}
    r_src = np.asarray([remap[int(u)] for u in src], np.int64)
    r_dst = np.asarray([remap[int(u)] for u in dst], np.int64)
    sample_index = Tensor(uniq)
    return Tensor(r_src), Tensor(r_dst), sample_index, Tensor(
        np.asarray([remap[int(s)] for s in seeds], np.int64))


class ResNetUnit(nn.Layer):
    """Fused conv+BN(+residual add)+ReLU block (resnet_unit.py / the
    cuDNN-fused resnet_unit op). On TPU the fusion is XLA's conv-epilogue
    job; this class carries the API (optionally a second conv+BN branch on
    the shortcut, like the reference's has_shortcut mode)."""

    def __init__(self, num_channels_x, num_filters, filter_size, stride=1,
                 momentum=0.9, eps=1e-5, data_format="NCHW", act="relu",
                 has_shortcut=False, num_channels_z=None, **kw):
        super().__init__()
        if data_format != "NCHW":
            raise NotImplementedError("ResNetUnit supports NCHW here")
        if act not in ("relu", None, ""):
            raise ValueError(f"unsupported act {act!r}; this unit fuses 'relu'")
        pad = (filter_size - 1) // 2
        self.conv = nn.Conv2D(num_channels_x, num_filters, filter_size,
                              stride=stride, padding=pad, bias_attr=False)
        self.bn = nn.BatchNorm2D(num_filters, momentum=momentum, epsilon=eps)
        self.has_shortcut = bool(has_shortcut)
        if self.has_shortcut:
            self.conv_z = nn.Conv2D(num_channels_z or num_channels_x,
                                    num_filters, 1, stride=stride,
                                    bias_attr=False)
            self.bn_z = nn.BatchNorm2D(num_filters, momentum=momentum,
                                       epsilon=eps)
        self.act = act

    def forward(self, x, z=None):
        out = self.bn(self.conv(x))
        if z is not None:
            out = out + (self.bn_z(self.conv_z(z)) if self.has_shortcut else z)
        if self.act == "relu":
            out = nn.functional.relu(out)
        return out
