"""paddle.incubate parity (reference python/paddle/incubate/) — fused layers."""
from . import nn  # noqa: F401


def softmax_mask_fuse(x, mask, name=None):
    from ..core.dispatch import as_tensor, eager_call
    import jax
    import jax.numpy as jnp

    return eager_call(
        "softmax_mask_fuse",
        lambda a, m: jax.nn.softmax(a + m, axis=-1),
        [as_tensor(x), as_tensor(mask)],
    )


def softmax_mask_fuse_upper_triangle(x, name=None):
    from ..core.dispatch import as_tensor, eager_call
    import jax
    import jax.numpy as jnp

    def fn(a):
        T = a.shape[-1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        return jax.nn.softmax(jnp.where(mask, a, -1e30), axis=-1)

    return eager_call("softmax_mask_fuse_upper_triangle", fn, [as_tensor(x)])

from . import asp  # noqa: F401
from .custom_op import register_custom_op, get_custom_op, registered_custom_ops  # noqa: F401
from .. import sparse  # noqa: F401 (paddle.incubate.sparse, the v2.3 namespace)
from ..ops.extra import segment_sum, segment_mean, segment_max, segment_min  # noqa: F401
from .distributed_fused_lamb import DistributedFusedLamb  # noqa: F401
from .optimizer_extras import LookAhead, ModelAverage  # noqa: F401
from .operators import (  # noqa: F401
    softmax_mask_fuse, softmax_mask_fuse_upper_triangle, graph_send_recv,
    graph_khop_sampler, ResNetUnit,
)
from .host_embedding import (  # noqa: F401
    HostEmbedding, HostEmbeddingTable, HotRowCache,
    ShardedHostEmbeddingTable, sharded_host_embedding,
)
