"""ASP — 2:4 structured sparsity.

Parity: reference ``python/paddle/fluid/contrib/sparsity/asp.py:286``
(prune_model / ASPHelper / OptimizerWithSparsityGuarantee) + ``utils.py``
mask algorithms (mask_1d / mask_2d_greedy / check_mask_1d). TPU-native: the
n:m mask is computed with a top-k over reshaped groups and re-applied after
every optimizer step so training stays on the sparse support.
"""
from __future__ import annotations

import weakref
from typing import Dict, List, Tuple

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor

# id -> (weakref to the param, mask): the weakref guards against id reuse
# after GC and lets dead entries be dropped
_MASKS: Dict[int, Tuple["weakref.ref", "jnp.ndarray"]] = {}
_SUPPORTED = ("Linear",)


def compute_mask_nm(arr, n=2, m=4):
    """Keep the n largest-magnitude entries of every m-group along the last
    axis (reference sparsity/utils.py get_mask_1d)."""
    w = jnp.asarray(arr)
    last = w.shape[-1]
    if last % m:
        return jnp.ones_like(w)  # non-divisible tails stay dense (ref behavior)
    g = w.reshape(-1, m)
    kth = jnp.sort(jnp.abs(g), axis=-1)[:, m - n]  # n-th largest per group
    mask = (jnp.abs(g) >= kth[:, None]).astype(w.dtype)
    # break ties deterministically: cap at n kept per group
    idx = jnp.argsort(-jnp.abs(g), axis=-1)
    rank = jnp.zeros_like(g).at[jnp.arange(g.shape[0])[:, None], idx].set(
        jnp.broadcast_to(jnp.arange(m, dtype=w.dtype), g.shape)
    )
    mask = mask * (rank < n)
    return mask.reshape(w.shape)


def check_mask_nm(arr, n=2, m=4) -> bool:
    """True iff every m-group has at most n nonzeros (reference check_mask_1d)."""
    w = np.asarray(arr)
    if w.shape[-1] % m:
        return True
    g = (w.reshape(-1, m) != 0).sum(axis=-1)
    return bool((g <= n).all())


def _prunable_params(model, supported_types) -> List[Tensor]:
    out = []
    for _, sub in model.named_sublayers():
        if type(sub).__name__ in supported_types and hasattr(sub, "weight"):
            out.append(sub.weight)
    return out


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True, supported_types=_SUPPORTED):
    """Compute + apply n:m masks on supported layers (reference asp.py:286
    prune_model). Masks are remembered so decorated optimizers re-apply them."""
    for p in _prunable_params(model, supported_types):
        mask = compute_mask_nm(p._data, n, m)
        _MASKS[id(p)] = (weakref.ref(p), mask)
        p._set_data(p._data * mask)
    return model


def apply_masks(params):
    dead = [k for k, (ref, _) in _MASKS.items() if ref() is None]
    for k in dead:
        del _MASKS[k]
    for p in params:
        entry = _MASKS.get(id(p))
        if entry is None:
            continue
        ref, mask = entry
        if ref() is not p:  # id recycled onto a different tensor
            del _MASKS[id(p)]
            continue
        p._set_data(p._data * mask.astype(p._data.dtype))


class OptimizerWithSparsityGuarantee:
    """Optimizer decorator (reference asp.py ASPHelper.decorate): after each
    step, project pruned weights back onto their mask support."""

    def __init__(self, optimizer):
        self._inner = optimizer

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def step(self):
        self._inner.step()
        apply_masks(self._inner._parameter_list or [])

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()  # mask re-projection included
        return None, None

    def clear_grad(self, *a, **k):
        self._inner.clear_grad(*a, **k)


def decorate(optimizer):
    return OptimizerWithSparsityGuarantee(optimizer)


__all__ = [
    "compute_mask_nm", "check_mask_nm", "prune_model", "decorate",
    "apply_masks", "OptimizerWithSparsityGuarantee",
]
