"""Incubate optimizers: LookAhead and ModelAverage.

Reference: ``python/paddle/incubate/optimizer/lookahead.py`` (Zhang et al.
2019 — fast weights advance k steps, slow weights interpolate toward them)
and ``modelaverage.py`` (evaluation-time parameter averaging over a sliding
window, with apply()/restore() swap). Both wrap any inner optimizer and keep
their statistics as device arrays, so the k-step interpolation and the
running sums stay on-chip.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.engine import no_grad
from ..core.lazy import concrete as _concrete
from ..core.tensor import Tensor

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """lookahead.py:35 — ``slow += alpha * (fast - slow)`` every k steps,
    then fast weights reset to the slow weights."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not 0.0 <= float(alpha) <= 1.0:
            raise ValueError(f"alpha must be in [0, 1], got {alpha}")
        if int(k) < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._parameter_list = list(getattr(inner_optimizer, "_parameter_list", []))
        # reference lookahead.py seeds the slow copy from the BUILD-time
        # parameters, so the first k-step sync interpolates the fast weights
        # back toward the initial point (seeding lazily at the first sync
        # from the current fast weights would make it a no-op)
        self._slow = {
            p.name: jnp.asarray(_concrete(p._data)) for p in self._parameter_list
        }
        self._step_count = 0

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def set_lr(self, lr):
        self.inner_optimizer.set_lr(lr)

    @no_grad()
    def step(self):
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k:
            return
        for p in self._parameter_list:
            fast = p._data
            slow = self._slow.get(p.name)
            if slow is None:  # param added after construction: adopt fast
                slow = fast
            # explicit dtype: a bare python float promotes to f64 under the
            # framework's x64 mode when it passes through the lazy recorder
            alpha = jnp.asarray(self.alpha, dtype=fast.dtype)
            slow = slow + alpha * (fast - slow)
            self._slow[p.name] = slow
            p._set_data(slow)

    def clear_grad(self, set_to_zero=True):
        self.inner_optimizer.clear_grad(set_to_zero)

    def state_dict(self):
        out = {f"slow@{k}": Tensor(_concrete(v)) for k, v in self._slow.items()}
        out["@lookahead_step"] = self._step_count
        out["inner"] = self.inner_optimizer.state_dict()
        return out

    def set_state_dict(self, state):
        self._step_count = int(state.get("@lookahead_step", 0))
        slow = {
            k[len("slow@"):]: jnp.asarray(v._data if isinstance(v, Tensor) else v)
            for k, v in state.items() if isinstance(k, str) and k.startswith("slow@")
        }
        # a key matching no parameter would silently restart interpolation
        # from scratch — fail loudly instead (same contract as DGC)
        names = {p.name for p in self._parameter_list}
        stale = set(slow) - names
        if stale:
            raise ValueError(
                f"LookAhead slow-weight keys {sorted(stale)} match no "
                f"parameter of this optimizer (have {sorted(names)})")
        self._slow = slow
        if "inner" in state:
            self.inner_optimizer.set_state_dict(state["inner"])

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        # base Optimizer.minimize contract (optimizer/__init__.py:202)
        loss.backward()
        self.step()
        return None, None


class ModelAverage:
    """modelaverage.py:33 — running parameter sums over a sliding window;
    ``apply()`` swaps averaged weights in for evaluation, ``restore()``
    swaps the training weights back."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000, name=None):
        self.rate = float(average_window_rate)
        self.min_w = int(min_average_window)
        self.max_w = int(max_average_window)
        self._parameter_list = list(parameters) if parameters is not None else []
        # per-param: (sum, num) with periodic fold-down like the reference's
        # sum_1/sum_2/sum_3 cascade (bounded window without storing history);
        # _num is FLOAT so fold halving keeps sum and divisor consistent
        self._sum = {}
        self._num = 0.0
        self._backup = None

    @no_grad()
    def step(self):
        """Accumulate the current weights (call after optimizer.step())."""
        self._num += 1.0
        window = max(self.min_w, min(self.max_w, int(self._num * self.rate) or 1))
        for p in self._parameter_list:
            cur = jnp.asarray(_concrete(p._data)).astype(jnp.float32)  # f32 accumulation (flush pending lazy)
            s = self._sum.get(p.name)
            self._sum[p.name] = cur if s is None else s + cur
        if self._num > window:
            # fold: halve the window's weight so old samples decay (the
            # reference restarts its sum_1 cascade the same bounded way)
            for k in self._sum:
                self._sum[k] = self._sum[k] * jnp.float32(0.5)
            self._num = self._num * 0.5  # same factor as the sums

    @no_grad()
    def apply(self, executor=None, need_restore=True):
        if self._num == 0:
            return
        if self._backup is not None:
            raise RuntimeError(
                "ModelAverage.apply() called twice without restore(): the "
                "training weights would be overwritten by averaged ones")
        self._backup = {p.name: p._data for p in self._parameter_list}
        for p in self._parameter_list:
            avg = self._sum[p.name] / jnp.float32(self._num)
            p._set_data(avg.astype(p._data.dtype))
        if not need_restore:
            self._backup = None

    @no_grad()
    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._parameter_list:
            p._set_data(self._backup[p.name])
        self._backup = None

    def state_dict(self):
        out = {f"sum@{k}": Tensor(_concrete(v)) for k, v in self._sum.items()}
        out["@ma_num"] = self._num
        return out

    def set_state_dict(self, state):
        self._num = float(state.get("@ma_num", 0.0))
        sums = {
            k[len("sum@"):]: jnp.asarray(v._data if isinstance(v, Tensor) else v)
            for k, v in state.items() if isinstance(k, str) and k.startswith("sum@")
        }
        names = {p.name for p in self._parameter_list}
        stale = set(sums) - names
        if stale:
            raise ValueError(
                f"ModelAverage sum keys {sorted(stale)} match no parameter "
                f"of this optimizer (have {sorted(names)})")
        self._sum = sums
