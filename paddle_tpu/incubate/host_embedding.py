"""Host-offloaded giant embedding — the TPU-first parameter-server answer.

Parity (capability, not design): the reference's brpc PS serves embedding
tables far larger than device memory — in-RAM
``distributed/ps/table/memory_sparse_table.cc``, disk-backed
``ssd_sparse_table.cc``, runtime ``fleet/runtime/the_one_ps.py:606``, lookup
``operators/pscore/distributed_lookup_table_op``, and SelectedRows sparse
optimizer rules (``table/sparse_sgd_rule.cc``). On TPU the idiomatic
replacement is not an RPC server: the table lives in HOST memory (plain RAM
or a numpy memmap, which makes the LOGICAL size disk-bound, like the SSD
table), each step gathers only the touched rows to HBM, and the sparse
optimizer update is applied host-side to exactly those rows
(SelectedRows-style). HBM holds O(unique ids per batch × dim), never the
table.

Flow per step (mirrors PS pull → dense compute → push):
    ids → unique (host) → table.gather(uniq) → device leaf tensor `rows`
    → out = rows[inverse]  (differentiable gather on device)
    → backward gives rows.grad (dense, small)
    → apply_gradients(): host scatter-update of the touched rows
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np
import jax.numpy as jnp

from ..core.dispatch import as_tensor, eager_call
from ..core.lazy import concrete as _concrete
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["HostEmbeddingTable", "HostEmbedding"]


class HostEmbeddingTable:
    """Row store in host RAM or a memmap file (logical size disk-bound; the
    file is sparse, so untouched rows occupy no physical pages)."""

    def __init__(
        self,
        num_embeddings: int,
        embedding_dim: int,
        dtype="float32",
        path: Optional[str] = None,
        init_std: float = 0.01,
        seed: int = 0,
        optimizer: str = "sgd",
        adagrad_eps: float = 1e-8,
    ):
        self.num_embeddings = int(num_embeddings)
        self.embedding_dim = int(embedding_dim)
        self.dtype = np.dtype(dtype)
        self.init_std = float(init_std)
        self.seed = int(seed)
        self.optimizer = optimizer
        self.adagrad_eps = float(adagrad_eps)
        shape = (self.num_embeddings, self.embedding_dim)
        if path is not None:
            self.table = np.lib.format.open_memmap(
                path, mode="w+", dtype=self.dtype, shape=shape
            )
            if optimizer == "adagrad":
                self._accum = np.lib.format.open_memmap(
                    path + ".accum", mode="w+", dtype=np.float32,
                    shape=(self.num_embeddings,),
                )
            else:
                self._accum = None
        else:
            self.table = np.zeros(shape, self.dtype)
            self._accum = (
                np.zeros((self.num_embeddings,), np.float32)
                if optimizer == "adagrad"
                else None
            )
        # lazy per-row init: rows materialize with N(0, init_std) on first
        # touch (deterministic per row), so a 20GB-logical table costs
        # nothing until used — the reference's sparse tables create entries
        # on first feature occurrence the same way
        self._initialized = np.zeros(self.num_embeddings, bool)

    def _ensure_init(self, ids: np.ndarray):
        fresh = ids[~self._initialized[ids]]
        if fresh.size == 0:
            return
        for r in fresh:
            rng = np.random.default_rng(self.seed * 0x9E3779B1 + int(r))
            self.table[r] = rng.normal(0.0, self.init_std, self.embedding_dim).astype(self.dtype)
        self._initialized[fresh] = True

    def gather(self, ids: np.ndarray) -> np.ndarray:
        ids = np.asarray(ids, np.int64)
        self._ensure_init(ids)
        return np.asarray(self.table[ids])

    def apply_update(self, ids: np.ndarray, grad: np.ndarray, lr: float):
        """SelectedRows-style sparse optimizer step on the touched rows
        (reference sparse_sgd_rule.cc: SGD / rowwise Adagrad)."""
        ids = np.asarray(ids, np.int64)
        grad = np.asarray(grad, np.float32)
        if self.optimizer == "adagrad":
            g2 = (grad * grad).mean(axis=1)
            self._accum[ids] += g2
            scale = lr / (np.sqrt(self._accum[ids]) + self.adagrad_eps)
            self.table[ids] = (
                self.table[ids].astype(np.float32) - scale[:, None] * grad
            ).astype(self.dtype)
        else:  # sgd
            self.table[ids] = (
                self.table[ids].astype(np.float32) - lr * grad
            ).astype(self.dtype)

    def state_nbytes_physical(self) -> int:
        """Resident bytes of the backing file (0 blocks for untouched rows)."""
        if isinstance(self.table, np.memmap):
            st = os.stat(self.table.filename)
            return st.st_blocks * 512
        return self.table.nbytes


class HostEmbedding(Layer):
    """Embedding layer over a HostEmbeddingTable.

    Eager-mode by design: the gather crosses the host boundary, exactly like
    the reference's PS pull — the dense model around it can still run
    compiled. Call ``apply_gradients(lr)`` after ``backward()`` (the role of
    the PS push / SelectedRows optimizer)."""

    def __init__(self, num_embeddings, embedding_dim, path=None, optimizer="sgd",
                 init_std=0.01, seed=0, sparse=True, name=None):
        super().__init__()
        self.table = HostEmbeddingTable(
            num_embeddings, embedding_dim, path=path, optimizer=optimizer,
            init_std=init_std, seed=seed,
        )
        self._pending = []  # (unique_ids, rows_tensor) awaiting push

    def forward(self, x):
        xt = as_tensor(x)
        ids = np.asarray(_concrete(xt._data)).astype(np.int64)
        uniq, inverse = np.unique(ids.ravel(), return_inverse=True)
        rows = Tensor(jnp.asarray(self.table.gather(uniq)), stop_gradient=False)
        if self.training:
            self._pending.append((uniq, rows))
        inv = Tensor(jnp.asarray(inverse.reshape(ids.shape)))

        out = eager_call(
            "host_embedding_select",
            lambda r, iv: r[iv],
            [rows, inv],
        )
        return out

    def apply_gradients(self, lr: float):
        """Push: apply accumulated sparse grads to the host table."""
        for uniq, rows in self._pending:
            if rows.grad is not None:
                self.table.apply_update(uniq, np.asarray(_concrete(rows.grad._data)), lr)
        self._pending = []

    def embedding_dim(self):
        return self.table.embedding_dim
