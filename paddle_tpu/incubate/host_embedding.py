"""Host-offloaded giant embedding — the TPU-first parameter-server answer.

Parity (capability, not design): the reference's brpc PS serves embedding
tables far larger than device memory — in-RAM
``distributed/ps/table/memory_sparse_table.cc``, disk-backed
``ssd_sparse_table.cc``, runtime ``fleet/runtime/the_one_ps.py:606``, lookup
``operators/pscore/distributed_lookup_table_op``, and SelectedRows sparse
optimizer rules (``table/sparse_sgd_rule.cc``). On TPU the idiomatic
replacement is not an RPC server: the table lives in HOST memory (plain RAM
or a numpy memmap, which makes the LOGICAL size disk-bound, like the SSD
table), each step gathers only the touched rows to HBM, and the sparse
optimizer update is applied host-side to exactly those rows
(SelectedRows-style). HBM holds O(unique ids per batch × dim), never the
table.

Flow per step (mirrors PS pull → dense compute → push):
    ids → unique (host) → table.gather(uniq) → device leaf tensor `rows`
    → out = rows[inverse]  (differentiable gather on device)
    → backward gives rows.grad (dense, small)
    → apply_gradients(): host scatter-update of the touched rows
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np
import jax.numpy as jnp

from ..core.dispatch import as_tensor, eager_call
from ..core.lazy import concrete as _concrete
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = [
    "HostEmbeddingTable", "HostEmbedding", "ShardedHostEmbeddingTable",
    "sharded_host_embedding",
]


def sharded_host_embedding(num_embeddings, embedding_dim, store=None, **kw):
    """Fleet-integrated constructor: build a HostEmbedding whose table is
    sharded across the trainer processes of the current fleet job (reads
    PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM; rendezvous through the given
    TCPStore or one bootstrapped from PADDLE_EMB_STORE_PORT). Single-process
    jobs fall back to a plain host table — same code path either way, like
    ``the_one_ps.py`` switching between local and distributed tables."""
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if world <= 1:
        return HostEmbedding(num_embeddings, embedding_dim, **kw)
    if store is None:
        from ..core.native import TCPStore

        host = os.environ.get("PADDLE_EMB_STORE_HOST", "127.0.0.1")
        port = int(os.environ.get("PADDLE_EMB_STORE_PORT", "23461"))
        store = TCPStore(host=host, port=port, is_master=(rank == 0))
    table = ShardedHostEmbeddingTable(
        num_embeddings, embedding_dim, store=store, rank=rank, world_size=world,
        optimizer=kw.pop("optimizer", "sgd"), init_std=kw.pop("init_std", 0.01),
        seed=kw.pop("seed", 0), path=kw.pop("path", None),
        name=kw.pop("name", None),
    )
    return HostEmbedding(num_embeddings, embedding_dim, table=table)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer (counter-based hashing RNG core)."""
    with np.errstate(over="ignore"):
        z = (x + np.uint64(0x9E3779B97F4A7C15)).astype(np.uint64)
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))


def _merge_sparse_grads(ids_list, grads_list, dim: int):
    """Coalesce sparse grad pushes: concatenate, merge duplicate ids by
    SUMMING their rows. Returns (unique_ids, merged_grads)."""
    cat_ids = np.concatenate(ids_list) if ids_list else np.empty((0,), np.int64)
    if cat_ids.size == 0:
        return cat_ids, np.empty((0, dim), np.float32)
    cat_grads = np.concatenate(grads_list, axis=0)
    uniq, inv = np.unique(cat_ids, return_inverse=True)
    if uniq.size == cat_ids.size:  # no duplicates: reorder only
        return uniq, cat_grads[np.argsort(cat_ids, kind="stable")]
    merged = np.zeros((uniq.size, dim), np.float32)
    np.add.at(merged, inv, cat_grads)
    return uniq, merged


def _hash_normal_rows(rows: np.ndarray, dim: int, seed: int, std: float) -> np.ndarray:
    """N(0, std) values for the given row ids, deterministic per (row, col):
    splitmix64 counters → two uniforms → Box–Muller. Fully vectorized."""
    idx = rows.astype(np.uint64)[:, None] * np.uint64(dim) + np.arange(dim, dtype=np.uint64)[None, :]
    with np.errstate(over="ignore"):
        h1 = _splitmix64(idx ^ np.uint64(seed & 0xFFFFFFFFFFFFFFFF))
        h2 = _splitmix64(h1)
    # top 53 bits → uniform in (0, 1]; u1 kept away from 0 for the log
    u1 = ((h1 >> np.uint64(11)).astype(np.float64) + 1.0) / 9007199254740993.0
    u2 = (h2 >> np.uint64(11)).astype(np.float64) / 9007199254740992.0
    return (std * np.sqrt(-2.0 * np.log(u1)) * np.cos(2.0 * np.pi * u2)).astype(np.float32)


class HostEmbeddingTable:
    """Row store in host RAM or a memmap file (logical size disk-bound; the
    file is sparse, so untouched rows occupy no physical pages)."""

    def __init__(
        self,
        num_embeddings: int,
        embedding_dim: int,
        dtype="float32",
        path: Optional[str] = None,
        init_std: float = 0.01,
        seed: int = 0,
        optimizer: str = "sgd",
        adagrad_eps: float = 1e-8,
    ):
        self.num_embeddings = int(num_embeddings)
        self.embedding_dim = int(embedding_dim)
        self.dtype = np.dtype(dtype)
        self.init_std = float(init_std)
        self.seed = int(seed)
        self.optimizer = optimizer
        self.adagrad_eps = float(adagrad_eps)
        shape = (self.num_embeddings, self.embedding_dim)
        if path is not None:
            self.table = np.lib.format.open_memmap(
                path, mode="w+", dtype=self.dtype, shape=shape
            )
            if optimizer == "adagrad":
                self._accum = np.lib.format.open_memmap(
                    path + ".accum", mode="w+", dtype=np.float32,
                    shape=(self.num_embeddings,),
                )
            else:
                self._accum = None
        else:
            self.table = np.zeros(shape, self.dtype)
            self._accum = (
                np.zeros((self.num_embeddings,), np.float32)
                if optimizer == "adagrad"
                else None
            )
        # lazy per-row init: rows materialize with N(0, init_std) on first
        # touch (deterministic per row), so a 20GB-logical table costs
        # nothing until used — the reference's sparse tables create entries
        # on first feature occurrence the same way
        self._initialized = np.zeros(self.num_embeddings, bool)

    def _ensure_init(self, ids: np.ndarray):
        fresh = np.unique(ids[~self._initialized[ids]])
        if fresh.size == 0:
            return
        # vectorized counter-based init (one splitmix64+Box-Muller pass over
        # the whole fresh block): a cold batch with 50k new ids costs two
        # numpy kernels, not 50k python RNG constructions — and stays
        # deterministic PER ROW, so values don't depend on touch order or on
        # how the table is sharded across processes
        self.table[fresh] = _hash_normal_rows(
            fresh, self.embedding_dim, self.seed, self.init_std
        ).astype(self.dtype)
        self._initialized[fresh] = True

    def gather(self, ids: np.ndarray) -> np.ndarray:
        ids = np.asarray(ids, np.int64)
        self._ensure_init(ids)
        return np.asarray(self.table[ids])

    def apply_update(self, ids: np.ndarray, grad: np.ndarray, lr: float):
        """SelectedRows-style sparse optimizer step on the touched rows
        (reference sparse_sgd_rule.cc: SGD / rowwise Adagrad)."""
        ids = np.asarray(ids, np.int64)
        grad = np.asarray(grad, np.float32)
        if self.optimizer == "adagrad":
            g2 = (grad * grad).mean(axis=1)
            self._accum[ids] += g2
            scale = lr / (np.sqrt(self._accum[ids]) + self.adagrad_eps)
            self.table[ids] = (
                self.table[ids].astype(np.float32) - scale[:, None] * grad
            ).astype(self.dtype)
        else:  # sgd
            self.table[ids] = (
                self.table[ids].astype(np.float32) - lr * grad
            ).astype(self.dtype)

    def state_nbytes_physical(self) -> int:
        """Resident bytes of the backing file (0 blocks for untouched rows)."""
        if isinstance(self.table, np.memmap):
            st = os.stat(self.table.filename)
            return st.st_blocks * 512
        return self.table.nbytes


class ShardedHostEmbeddingTable:
    """Embedding table SHARDED BY ID across processes (id % world == owner),
    with pull/push over the native TCPStore — the distributed capability of
    the reference's brpc PS (``memory_sparse_table.cc`` shards by feature
    hash across servers; ``the_one_ps.py:606`` wires pull/push into train).
    Every rank is both worker and server: a gather is a collective exchange
    (all ranks request → serve owned rows → read replies), a push routes
    grads to the owners, which merge duplicate ids and apply ONE sparse
    update — sync-PS semantics, deterministic regardless of sharding.

    Transport chunks rows through the store in ≤512 KB messages; per-row
    deterministic lazy init means a row's value is identical no matter which
    shard materializes it.
    """

    CHUNK = 512 * 1024
    # per-process construction counter: ranks build their tables in the same
    # program order, so the index is a deterministic cross-rank identity
    _instance_counter = 0

    def __init__(self, num_embeddings, embedding_dim, store, rank, world_size,
                 dtype="float32", path=None, init_std=0.01, seed=0,
                 optimizer="sgd", adagrad_eps=1e-8, name=None):
        self.num_embeddings = int(num_embeddings)
        self.embedding_dim = int(embedding_dim)
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.store = store
        # namespace every store key by table identity: two tables sharing one
        # TCPStore each count gens from 0, and without this a fast rank's
        # table-2 request could be consumed as a peer's table-1 traffic
        idx = ShardedHostEmbeddingTable._instance_counter
        ShardedHostEmbeddingTable._instance_counter += 1
        self.name = name if name is not None else f"t{idx}"
        self._prefix = f"he/{self.name}"
        # local shard holds global ids {rank, rank+world, rank+2*world, …}
        n_local = (self.num_embeddings - self.rank + self.world_size - 1) // self.world_size
        self.local = HostEmbeddingTable(
            n_local, embedding_dim, dtype=dtype, path=path,
            init_std=init_std, seed=seed, optimizer=optimizer,
            adagrad_eps=adagrad_eps,
        )
        # per-row determinism across shardings: local row i is global id
        # i*world+rank, so init must hash the GLOBAL id
        self.local._ensure_init = self._ensure_init_local  # type: ignore
        self._seed = int(seed)
        self._std = float(init_std)
        self._gen = 0

    def _ensure_init_local(self, local_ids: np.ndarray):
        t = self.local
        fresh = np.unique(local_ids[~t._initialized[local_ids]])
        if fresh.size == 0:
            return
        global_ids = fresh * self.world_size + self.rank
        t.table[fresh] = _hash_normal_rows(
            global_ids, t.embedding_dim, self._seed, self._std
        ).astype(t.dtype)
        t._initialized[fresh] = True

    # -- store transport ---------------------------------------------------
    def _put(self, key: str, payload: bytes):
        n = (len(payload) + self.CHUNK - 1) // self.CHUNK or 1
        for i in range(n):
            self.store.set(f"{key}/{i}", payload[i * self.CHUNK:(i + 1) * self.CHUNK])
        self.store.set(key + "/n", str(n))

    def _take(self, key: str) -> bytes:
        n = int(self.store.wait(key + "/n"))
        parts = [self.store.wait(f"{key}/{i}") for i in range(n)]
        for i in range(n):
            self.store.delete_key(f"{key}/{i}")
        self.store.delete_key(key + "/n")
        return b"".join(parts)

    # -- collective pull ---------------------------------------------------
    def gather(self, ids: np.ndarray) -> np.ndarray:
        """Pull rows for (globally) unique ids; COLLECTIVE — every rank must
        call this the same number of times (data-parallel lockstep, like the
        reference's synchronous PS pull)."""
        ids = np.asarray(ids, np.int64)
        gen = self._gen
        self._gen += 1
        owner = ids % self.world_size
        out = np.empty((ids.size, self.embedding_dim), np.float32)
        # 1. send requests (own ids resolve locally)
        for o in range(self.world_size):
            if o == self.rank:
                continue
            want = ids[owner == o]
            self._put(f"{self._prefix}/{gen}/req/{self.rank}/{o}", want.tobytes())
        mine = ids[owner == self.rank]
        if mine.size:
            out[owner == self.rank] = self.local.gather(mine // self.world_size)
        # 2. serve every other rank's request against the local shard
        for r in range(self.world_size):
            if r == self.rank:
                continue
            req = np.frombuffer(self._take(f"{self._prefix}/{gen}/req/{r}/{self.rank}"), np.int64)
            rows = self.local.gather(req // self.world_size) if req.size else np.empty((0, self.embedding_dim), np.float32)
            self._put(f"{self._prefix}/{gen}/rep/{self.rank}/{r}", np.ascontiguousarray(rows, np.float32).tobytes())
        # 3. read replies
        for o in range(self.world_size):
            if o == self.rank:
                continue
            rows = np.frombuffer(self._take(f"{self._prefix}/{gen}/rep/{o}/{self.rank}"), np.float32)
            out[owner == o] = rows.reshape(-1, self.embedding_dim)
        return out

    # -- collective push ---------------------------------------------------
    def apply_update(self, ids: np.ndarray, grad: np.ndarray, lr: float):
        """Push sparse grads to their owners; owners merge duplicates across
        ranks (sum, like gradient accumulation) then apply ONE update."""
        ids = np.asarray(ids, np.int64)
        grad = np.asarray(grad, np.float32)
        gen = self._gen
        self._gen += 1
        owner = ids % self.world_size
        for o in range(self.world_size):
            if o == self.rank:
                continue
            sel = owner == o
            self._put(f"{self._prefix}/{gen}/gid/{self.rank}/{o}", ids[sel].tobytes())
            self._put(f"{self._prefix}/{gen}/g/{self.rank}/{o}", np.ascontiguousarray(grad[sel]).tobytes())
        all_ids = [ids[owner == self.rank]]
        all_grads = [grad[owner == self.rank]]
        for r in range(self.world_size):
            if r == self.rank:
                continue
            gi = np.frombuffer(self._take(f"{self._prefix}/{gen}/gid/{r}/{self.rank}"), np.int64)
            gg = np.frombuffer(self._take(f"{self._prefix}/{gen}/g/{r}/{self.rank}"), np.float32).reshape(-1, self.embedding_dim)
            all_ids.append(gi)
            all_grads.append(gg)
        uniq, merged = _merge_sparse_grads(all_ids, all_grads, self.embedding_dim)
        if uniq.size == 0:
            return
        self.local.apply_update(uniq // self.world_size, merged, lr)


class HostEmbedding(Layer):
    """Embedding layer over a HostEmbeddingTable.

    Eager-mode by design: the gather crosses the host boundary, exactly like
    the reference's PS pull — the dense model around it can still run
    compiled. Call ``apply_gradients(lr)`` after ``backward()`` (the role of
    the PS push / SelectedRows optimizer)."""

    def __init__(self, num_embeddings, embedding_dim, path=None, optimizer="sgd",
                 init_std=0.01, seed=0, sparse=True, name=None, table=None):
        super().__init__()
        # table=ShardedHostEmbeddingTable(...) makes this layer the worker
        # side of a multi-process PS (fleet wires this up from env)
        self.table = table or HostEmbeddingTable(
            num_embeddings, embedding_dim, path=path, optimizer=optimizer,
            init_std=init_std, seed=seed,
        )
        self._pending = []  # (unique_ids, rows_tensor) awaiting push
        self._prefetched = None  # (uniq_key_bytes, rows ndarray, thread)
        import threading

        # one lock serializes table reads (prefetch thread) against the
        # sparse updates (apply_gradients) — torn rows are silent corruption
        self._table_lock = threading.Lock()

    def prefetch(self, x):
        """Start the host gather for the NEXT batch on a worker thread so it
        overlaps the current device step (the reference's PS prefetch /
        buffered pull). forward() consumes the result when ids match.

        No-op on a SHARDED table: its gather is a lockstep collective across
        ranks, and an extra/mismatched gather from a background thread would
        desynchronize the exchange protocol."""
        import threading

        if isinstance(self.table, ShardedHostEmbeddingTable):
            return
        ids = np.asarray(x._data if isinstance(x, Tensor) else x).astype(np.int64)
        uniq = np.unique(ids.ravel())
        slot = {"key": uniq.tobytes(), "rows": None}

        def work():
            with self._table_lock:
                slot["rows"] = self.table.gather(uniq)

        th = threading.Thread(target=work, daemon=True)
        th.start()
        self._prefetched = (slot, th)

    def _gather(self, uniq: np.ndarray) -> np.ndarray:
        if self._prefetched is not None:
            slot, th = self._prefetched
            th.join()
            self._prefetched = None
            if slot["key"] == uniq.tobytes():
                return slot["rows"]
        with self._table_lock:
            return self.table.gather(uniq)

    def forward(self, x):
        xt = as_tensor(x)
        ids = np.asarray(_concrete(xt._data)).astype(np.int64)
        uniq, inverse = np.unique(ids.ravel(), return_inverse=True)
        rows = Tensor(jnp.asarray(self._gather(uniq)), stop_gradient=False)
        if self.training:
            self._pending.append((uniq, rows))
        inv = Tensor(jnp.asarray(inverse.reshape(ids.shape)))

        out = eager_call(
            "host_embedding_select",
            lambda r, iv: r[iv],
            [rows, inv],
        )
        return out

    def apply_gradients(self, lr: float):
        """Push: apply accumulated sparse grads to the host table. Pending
        microbatches are COALESCED first — duplicate ids across microbatches
        merge into one row update (one gather/scatter on the table, and for
        the sharded table one pull/push round instead of one per microbatch)."""
        ids_list, grad_list = [], []
        for uniq, rows in self._pending:
            if rows.grad is not None:
                ids_list.append(uniq)
                grad_list.append(np.asarray(_concrete(rows.grad._data), np.float32))
        self._pending = []
        sharded = isinstance(self.table, ShardedHostEmbeddingTable)
        if not ids_list and not sharded:
            return
        # a SHARDED push is a lockstep collective: a rank with nothing to
        # push must still participate (empty payload), or peers deadlock in
        # store.wait() and the _gen counters diverge
        dim = self.table.embedding_dim
        # adagrad's accumulator is step-count sensitive: one update with the
        # summed grad != one update per microbatch. For a LOCAL table the
        # coalescing buys nothing (no comm round), so keep per-microbatch
        # semantics there; the sharded table coalesces (one pull/push round)
        # and documents the summed-grad semantics as the distributed contract.
        if not sharded and getattr(self.table, "optimizer", "sgd") == "adagrad":
            with self._table_lock:
                for ids_i, grad_i in zip(ids_list, grad_list):
                    self.table.apply_update(ids_i, grad_i, lr)
            self._prefetched = None
            return
        uniq, merged = _merge_sparse_grads(ids_list, grad_list, dim)
        if uniq.size == 0 and not sharded:
            return
        with self._table_lock:
            self.table.apply_update(uniq, merged, lr)
        # rows prefetched BEFORE this update are stale now (frequent ids
        # recur batch-to-batch); drop them so forward re-gathers fresh rows
        self._prefetched = None

    def embedding_dim(self):
        return self.table.embedding_dim
