"""Host-offloaded giant embedding — the TPU-first parameter-server answer.

Parity (capability, not design): the reference's brpc PS serves embedding
tables far larger than device memory — in-RAM
``distributed/ps/table/memory_sparse_table.cc``, disk-backed
``ssd_sparse_table.cc``, runtime ``fleet/runtime/the_one_ps.py:606``, lookup
``operators/pscore/distributed_lookup_table_op``, and SelectedRows sparse
optimizer rules (``table/sparse_sgd_rule.cc``). On TPU the idiomatic
replacement is not an RPC server: the table lives in HOST memory (plain RAM
or a numpy memmap, which makes the LOGICAL size disk-bound, like the SSD
table), each step gathers only the touched rows to HBM, and the sparse
optimizer update is applied host-side to exactly those rows
(SelectedRows-style). HBM holds O(unique ids per batch × dim), never the
table.

Flow per step (mirrors PS pull → dense compute → push):
    ids → unique (host) → table.gather(uniq) → device leaf tensor `rows`
    → out = rows[inverse]  (differentiable gather on device)
    → backward gives rows.grad (dense, small)
    → apply_gradients(): host scatter-update of the touched rows

The hot path has three accelerations, each independently kill-switched and
bit-exact against the pure-numpy fallback (the fallback IS the pre-PR
per-step code, kept as the portable reference semantics):

* **Native batched gather/scatter** (``FLAGS_host_emb_native``, default on):
  ``runtime_cpp/embed.cc`` does the multi-threaded unique → gather-rows →
  pack, the duplicate-id grad merge (np.add.at order preserved) and the
  fused SelectedRows SGD / rowwise-Adagrad scatter directly on the
  RAM/memmap table.

* **HBM hot-row cache** (``FLAGS_host_emb_cache_rows`` > 0 or
  ``HostEmbedding(cache_rows=)``): a device-resident cache for the head of
  the id distribution with count-min frequency admission. Cached rows are
  pulled from HBM and updated in place by the sparse push, so the hot head
  never crosses PCIe again (grads still do — they already live on device);
  eviction writes rows (and Adagrad accumulators) back to the host table.
  The cache is clamped to ``FLAGS_host_emb_cache_frac`` of the PR 14 HBM
  budget when one is resolvable, its buffers are ordinary live arrays the
  admission census counts, and it registers a ``fault.memory``
  free_pressure handler that halves it under memory pressure — it can
  never cause an unmanaged OOM. Local tables only: a sharded table's rows
  are owned by their rank and peers' pushes merge owner-side, which a
  worker-local device copy would break.

* **Pipelined pull/push**: next-batch ids are known at enqueue time —
  ``prefetch(ids)`` (or the ``prefetch_iter`` wrapper) hands the unique +
  gather + H2D to a persistent PS worker thread so the pull overlaps the
  current step, and ``FLAGS_host_emb_async_push`` makes
  ``apply_gradients`` enqueue the D2H + merge + scatter to the same
  worker. The worker runs jobs in FIFO submission order, so a gather
  submitted after a push always sees the updated table, and a push patches
  any already-prefetched pack it overlaps (the prefetched rows are
  re-gathered post-update and re-staged), keeping pipelined semantics
  bit-identical to the synchronous path. The worker holds only a weakref
  to the layer (PR 6 DevicePrefetcher discipline): abandoning the layer
  releases the thread.

The sharded table's pull/push transport is coalesced (one ids+grads
payload per peer) and chunk-parallel (``FLAGS_host_emb_chunk_bytes`` per
store message over ``FLAGS_host_emb_transport_threads`` dedicated store
connections) instead of the pre-PR serial ≤512 KiB round trips;
``FLAGS_host_emb_push_fp16`` optionally halves cross-rank push bytes.
"""
from __future__ import annotations

import os
import queue as _queue
import struct
import threading
import time
import weakref
from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import as_tensor, eager_call
from ..core.lazy import concrete as _concrete
from ..core.tensor import Tensor
from ..framework import flags as _flags
from ..nn.layer.layers import Layer
from .. import profiler as _prof
from ..profiler.spans import span as _span

__all__ = [
    "HostEmbeddingTable", "HostEmbedding", "ShardedHostEmbeddingTable",
    "HotRowCache", "sharded_host_embedding",
]


def sharded_host_embedding(num_embeddings, embedding_dim, store=None, **kw):
    """Fleet-integrated constructor: build a HostEmbedding whose table is
    sharded across the trainer processes of the current fleet job (reads
    PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM; rendezvous through the given
    TCPStore or one bootstrapped from PADDLE_EMB_STORE_PORT). Single-process
    jobs fall back to a plain host table — same code path either way, like
    ``the_one_ps.py`` switching between local and distributed tables."""
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if world <= 1:
        return HostEmbedding(num_embeddings, embedding_dim, **kw)
    store_addr = None
    if store is None:
        from ..core.native import TCPStore

        host = os.environ.get("PADDLE_EMB_STORE_HOST", "127.0.0.1")
        port = int(os.environ.get("PADDLE_EMB_STORE_PORT", "23461"))
        store = TCPStore(host=host, port=port, is_master=(rank == 0))
        # the table can open extra parallel-transport connections only when
        # it knows the endpoint; a caller-provided store stays serial
        store_addr = (host, port)
    table = ShardedHostEmbeddingTable(
        num_embeddings, embedding_dim, store=store, rank=rank, world_size=world,
        optimizer=kw.pop("optimizer", "sgd"), init_std=kw.pop("init_std", 0.01),
        seed=kw.pop("seed", 0), path=kw.pop("path", None),
        name=kw.pop("name", None), store_addr=store_addr,
    )
    return HostEmbedding(num_embeddings, embedding_dim, table=table)


# -- native kernel dispatch ---------------------------------------------------
def _native_ops():
    """The embed.cc kernel library, or None when unbuilt/stale/disabled."""
    if not _flags.flag("FLAGS_host_emb_native", True):
        return None
    from ..core import native

    L = native.lib()
    return L if (L is not None and native.HAS_EMBED) else None


def _nthreads() -> int:
    n = int(_flags.flag("FLAGS_host_emb_threads", 16) or 0)
    return n if n > 0 else (os.cpu_count() or 1)


def _c_f32(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a, np.float32)


def _unique(ids: np.ndarray):
    """np.unique(ids, return_inverse=True), natively when available."""
    ids = np.ascontiguousarray(ids, np.int64)
    L = _native_ops()
    if L is None or ids.size == 0:
        return np.unique(ids, return_inverse=True)
    uniq = np.empty(ids.size, np.int64)
    inv = np.empty(ids.size, np.int64)
    n = L.pte_unique(ids.ctypes.data, ids.size, uniq.ctypes.data,
                     inv.ctypes.data, _nthreads())
    if n < 0:
        raise IndexError("host embedding: negative id in lookup batch")
    return uniq[:n].copy(), inv


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer (counter-based hashing RNG core)."""
    with np.errstate(over="ignore"):
        z = (x + np.uint64(0x9E3779B97F4A7C15)).astype(np.uint64)
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))


def _merge_sparse_grads(ids_list, grads_list, dim: int):
    """Coalesce sparse grad pushes: concatenate, merge duplicate ids by
    SUMMING their rows (in input order — np.add.at semantics, which the
    native kernel reproduces bitwise). Returns (unique_ids, merged_grads)."""
    cat_ids = np.concatenate(ids_list) if ids_list else np.empty((0,), np.int64)
    if cat_ids.size == 0:
        return cat_ids, np.empty((0, dim), np.float32)
    cat_grads = np.concatenate(grads_list, axis=0)
    L = _native_ops()
    if L is not None and cat_grads.dtype == np.float32:
        cat_ids = np.ascontiguousarray(cat_ids, np.int64)
        cat_grads = np.ascontiguousarray(cat_grads)
        uniq = np.empty(cat_ids.size, np.int64)
        merged = np.empty((cat_ids.size, dim), np.float32)
        n = L.pte_merge_f32(cat_ids.ctypes.data, cat_ids.size,
                            cat_grads.ctypes.data, dim, uniq.ctypes.data,
                            merged.ctypes.data, _nthreads())
        if n < 0:
            raise IndexError("host embedding: negative id in grad push")
        return uniq[:n].copy(), merged[:n].copy()
    uniq, inv = np.unique(cat_ids, return_inverse=True)
    if uniq.size == cat_ids.size:  # no duplicates: reorder only
        return uniq, cat_grads[np.argsort(cat_ids, kind="stable")]
    merged = np.zeros((uniq.size, dim), np.float32)
    np.add.at(merged, inv, cat_grads)
    return uniq, merged


def _pad_pow2(n: int, minimum: int = 16) -> int:
    """Bucket a data-dependent length to a power of two: the device-side
    cache ops (gather/concat/scatter) would otherwise compile one XLA
    program per distinct unique-id count — unbounded recompilation on real
    id streams. Pow-2 padding bounds the compile count logarithmically."""
    return max(minimum, 1 << max(0, int(n - 1).bit_length()))


def _hash_normal_rows(rows: np.ndarray, dim: int, seed: int, std: float) -> np.ndarray:
    """N(0, std) values for the given row ids, deterministic per (row, col):
    splitmix64 counters → two uniforms → Box–Muller. Fully vectorized."""
    idx = rows.astype(np.uint64)[:, None] * np.uint64(dim) + np.arange(dim, dtype=np.uint64)[None, :]
    with np.errstate(over="ignore"):
        h1 = _splitmix64(idx ^ np.uint64(seed & 0xFFFFFFFFFFFFFFFF))
        h2 = _splitmix64(h1)
    # top 53 bits → uniform in (0, 1]; u1 kept away from 0 for the log
    u1 = ((h1 >> np.uint64(11)).astype(np.float64) + 1.0) / 9007199254740993.0
    u2 = (h2 >> np.uint64(11)).astype(np.float64) / 9007199254740992.0
    return (std * np.sqrt(-2.0 * np.log(u1)) * np.cos(2.0 * np.pi * u2)).astype(np.float32)


# One cached probe: does this filesystem report hole blocks honestly?
# Overlay-backed CI containers report st_blocks == file size from the
# moment of truncation (while still materializing lazily), which makes the
# st_blocks reading useless for the "lazy init keeps the table sparse"
# assertion — the fallback accounts initialized rows instead.
_fs_sparse_probe = {}


def _fs_reports_sparse_blocks(probe_dir: str) -> bool:
    probe_dir = probe_dir or "/tmp"
    if probe_dir in _fs_sparse_probe:
        return _fs_sparse_probe[probe_dir]
    import tempfile

    ok = False
    try:
        with tempfile.NamedTemporaryFile(dir=probe_dir) as f:
            f.truncate(4 * 1024 * 1024)
            # write ONE page through a mapping, like the table does: an fs
            # may report holes honestly at truncation yet materialize them
            # on first write-through (the failure the pre-PR skipif
            # guarded); only "holes stayed holes after a write" makes the
            # st_blocks reading trustworthy
            m = np.memmap(f.name, dtype=np.float32, mode="r+",
                          shape=(1024, 1024))
            m[0] = 1.0
            m.flush()
            del m
            ok = os.fstat(f.fileno()).st_blocks * 512 < 2 * 1024 * 1024
    except Exception:
        ok = False
    _fs_sparse_probe[probe_dir] = ok
    return ok


class HostEmbeddingTable:
    """Row store in host RAM or a memmap file (logical size disk-bound; the
    file is sparse, so untouched rows occupy no physical pages)."""

    def __init__(
        self,
        num_embeddings: int,
        embedding_dim: int,
        dtype="float32",
        path: Optional[str] = None,
        init_std: float = 0.01,
        seed: int = 0,
        optimizer: str = "sgd",
        adagrad_eps: float = 1e-8,
    ):
        self.num_embeddings = int(num_embeddings)
        self.embedding_dim = int(embedding_dim)
        self.dtype = np.dtype(dtype)
        self.init_std = float(init_std)
        self.seed = int(seed)
        self.optimizer = optimizer
        self.adagrad_eps = float(adagrad_eps)
        shape = (self.num_embeddings, self.embedding_dim)
        if path is not None:
            self.table = np.lib.format.open_memmap(
                path, mode="w+", dtype=self.dtype, shape=shape
            )
            if optimizer == "adagrad":
                self._accum = np.lib.format.open_memmap(
                    path + ".accum", mode="w+", dtype=np.float32,
                    shape=(self.num_embeddings,),
                )
            else:
                self._accum = None
        else:
            self.table = np.zeros(shape, self.dtype)
            self._accum = (
                np.zeros((self.num_embeddings,), np.float32)
                if optimizer == "adagrad"
                else None
            )
        # lazy per-row init: rows materialize with N(0, init_std) on first
        # touch (deterministic per row), so a 20GB-logical table costs
        # nothing until used — the reference's sparse tables create entries
        # on first feature occurrence the same way
        self._initialized = np.zeros(self.num_embeddings, bool)
        self._n_initialized = 0

    def _ensure_init(self, ids: np.ndarray):
        fresh = np.unique(ids[~self._initialized[ids]])
        if fresh.size == 0:
            return
        # vectorized counter-based init (one splitmix64+Box-Muller pass over
        # the whole fresh block): a cold batch with 50k new ids costs two
        # numpy kernels, not 50k python RNG constructions — and stays
        # deterministic PER ROW, so values don't depend on touch order or on
        # how the table is sharded across processes
        self.table[fresh] = _hash_normal_rows(
            fresh, self.embedding_dim, self.seed, self.init_std
        ).astype(self.dtype)
        self._initialized[fresh] = True
        self._n_initialized += int(fresh.size)

    def _native_table(self):
        """The kernel library when it can operate on this table directly
        (float32, C-contiguous — RAM or memmap alike), else None."""
        if self.dtype != np.float32 or not self.table.flags.c_contiguous:
            return None
        return _native_ops()

    def gather(self, ids: np.ndarray) -> np.ndarray:
        ids = np.ascontiguousarray(ids, np.int64)
        self._ensure_init(ids)
        L = self._native_table()
        if L is None or ids.size == 0:
            return np.asarray(self.table[ids])
        out = np.empty((ids.size, self.embedding_dim), np.float32)
        rc = L.pte_gather_f32(self.table.ctypes.data, self.num_embeddings,
                              self.embedding_dim, ids.ctypes.data, ids.size,
                              out.ctypes.data, _nthreads())
        if rc != 0:
            raise IndexError("host embedding: id out of range in gather")
        return out

    def apply_update(self, ids: np.ndarray, grad: np.ndarray, lr: float):
        """SelectedRows-style sparse optimizer step on the touched rows
        (reference sparse_sgd_rule.cc: SGD / rowwise Adagrad). ``ids`` must
        be unique (callers merge duplicates first)."""
        ids = np.ascontiguousarray(ids, np.int64)
        grad = _c_f32(grad)
        if ids.size == 0:
            return
        L = self._native_table()
        if L is not None:
            import ctypes

            if self.optimizer == "adagrad":
                rc = L.pte_adagrad_f32(
                    self.table.ctypes.data, self._accum.ctypes.data,
                    self.num_embeddings, self.embedding_dim, ids.ctypes.data,
                    ids.size, grad.ctypes.data, ctypes.c_float(lr),
                    ctypes.c_float(self.adagrad_eps), _nthreads())
            else:
                rc = L.pte_sgd_f32(
                    self.table.ctypes.data, self.num_embeddings,
                    self.embedding_dim, ids.ctypes.data, ids.size,
                    grad.ctypes.data, ctypes.c_float(lr), _nthreads())
            if rc != 0:
                raise IndexError("host embedding: id out of range in update")
            return
        if self.optimizer == "adagrad":
            # float64 cumsum forces a SEQUENTIAL per-row sum — the one numpy
            # reduction order the native kernel can reproduce bitwise at any
            # dim (np.mean's pairwise blocking would diverge past dim 128)
            g2 = (grad.astype(np.float64) ** 2).cumsum(axis=1)[:, -1]
            g2 = (g2 / float(self.embedding_dim)).astype(np.float32)
            self._accum[ids] += g2
            scale = lr / (np.sqrt(self._accum[ids]) + self.adagrad_eps)
            self.table[ids] = (
                self.table[ids].astype(np.float32) - scale[:, None] * grad
            ).astype(self.dtype)
        else:  # sgd
            self.table[ids] = (
                self.table[ids].astype(np.float32) - lr * grad
            ).astype(self.dtype)

    def state_nbytes_physical(self) -> int:
        """Resident bytes of the backing file (0 blocks for untouched rows).
        On filesystems whose ``st_blocks`` can't see holes (overlay/tmpfs CI
        mounts report full allocation at truncation), fall back to the
        lazy-init accounting: initialized rows × row bytes + header page."""
        if isinstance(self.table, np.memmap):
            if _fs_reports_sparse_blocks(os.path.dirname(self.table.filename)):
                return os.stat(self.table.filename).st_blocks * 512
            row = self.embedding_dim * self.dtype.itemsize
            return self._n_initialized * row + 4096
        return self.table.nbytes


# -- fused device helpers -----------------------------------------------------
# One jitted call per staging/update instead of an eager-op chain: on a busy
# host each eager dispatch costs as much as the whole kernel, and the PS
# worker issues several per microbatch. Shapes are HWM-bucketed, so each
# compiles a handful of times; lr rides as a traced scalar (no per-value
# recompiles).
@jax.jit
def _jit_pack(buf, slots, cold):
    return jnp.concatenate([buf[slots], cold], axis=0)


@jax.jit
def _jit_gather_rows(buf, slots):
    return buf[slots]


@jax.jit
def _jit_sgd_cache(buf, slots, g, lr):
    return buf.at[slots].add(-(lr * g))


@jax.jit
def _jit_row_set(buf, pos, vals):
    # pad lanes carry pos == len(buf): 'drop' discards them instead of the
    # default out-of-bounds clamp (which would corrupt the last row)
    return buf.at[pos].set(vals, mode="drop")


@jax.jit
def _jit_dense_sgd(buf, g, lr):
    # dense SGD over the whole cache buffer: rows with zero grad are
    # bitwise unchanged (x - 0.0 == x), touched rows match the scatter
    # rule exactly (x + -(lr*g) == x - lr*g)
    return buf - lr * g


@jax.jit
def _jit_ada_cache(buf, acc, slots, g, lr, eps):
    acc = acc.at[slots].add(jnp.mean(g * g, axis=1))
    scale = lr / (jnp.sqrt(acc[slots]) + eps)
    return buf.at[slots].add(-scale[:, None] * g), acc


# -- HBM hot-row cache --------------------------------------------------------
class HotRowCache:
    """Device-resident cache for the head of the id distribution.

    Admission is frequency-based: a 2-row count-min sketch tracks how often
    each missed id appears across steps; ids seen at least ``min_count``
    times are admitted (into free slots first, then over colder occupants).
    Cached rows are read from the device buffer on pull and updated in
    place by the sparse push; eviction and :meth:`flush` write rows (and
    Adagrad accumulators) back to the host table, so host and device
    together always hold exactly one authoritative copy per row.

    Sizing is budget-aware (PR 14): when ``fault.memory.budget_bytes()``
    resolves, capacity is clamped to ``FLAGS_host_emb_cache_frac`` of it,
    and a ``free_pressure`` handler (weakly owned, auto-unregistered) halves
    the cache under memory pressure — the shrink itself happens on the
    owner's thread at the next touch, like the serving pool's handler.
    """

    def __init__(self, table: HostEmbeddingTable, capacity: int,
                 min_count: Optional[int] = None):
        self.table = table
        self.dim = table.embedding_dim
        self.min_count = int(min_count if min_count is not None
                             else _flags.flag("FLAGS_host_emb_cache_min_count", 3))
        cap = int(capacity)
        bytes_per_row = self.dim * 4 + (4 if table.optimizer == "adagrad" else 0)
        budget = 0
        try:
            from ..fault import memory as _mem

            budget = int(_mem.budget_bytes() or 0)
            if budget > 0:
                frac = float(_flags.flag("FLAGS_host_emb_cache_frac", 0.25))
                cap = max(1, min(cap, int(budget * frac / bytes_per_row)))
            _mem.register_pressure_handler(
                f"host_emb_cache:{id(self):x}",
                lambda o: o._request_shrink(), owner=self)
        except Exception:
            pass
        self.capacity = cap
        self.budget_bytes = budget
        # one extra DUMMY row (index == capacity, never indexed by a real
        # slot): shape-padded gathers/scatters aim their pad lanes at it,
        # the serving PagePool's trash-block trick
        self._rows = jnp.zeros((cap + 1, self.dim), jnp.float32)
        self._accum = (jnp.zeros((cap + 1,), jnp.float32)
                       if table.optimizer == "adagrad" else None)
        # SGD runs the cache in DENSE-LEAF mode: the buffer is an autograd
        # leaf the forward graph gathers from, so hot-row grads accumulate
        # on it across microbatches (coalescing for free, summed in the
        # same per-row order np.add.at uses) and the push is ONE dense
        # in-graph update — hot rows AND their grads never leave the
        # device. Adagrad keeps the scatter path (its per-microbatch accum
        # semantics need per-microbatch grads).
        self.dense = table.optimizer == "sgd"
        self.rows_t: Optional[Tensor] = (
            Tensor(self._rows, stop_gradient=False) if self.dense else None)
        self._slot_ids = np.full(cap, -1, np.int64)
        self._slot_hits = np.zeros(cap, np.int64)
        self._free: List[int] = list(range(cap - 1, -1, -1))
        self._ids_sorted = np.empty(0, np.int64)
        self._slots_sorted = np.empty(0, np.int64)
        # count-min sketch for admission (uint32 saturating is irrelevant at
        # step scale; two independent splitmix streams)
        self._cmw = 1 << max(10, (cap * 4).bit_length())
        self._cms = np.zeros((2, self._cmw), np.int64)
        self.hits = 0
        self.misses = 0
        self._shrink_req = False  # set from the pressure handler's thread

    def _set_rows(self, new_rows):
        self._rows = new_rows
        if self.dense:
            self.rows_t = Tensor(new_rows, stop_gradient=False)

    def dense_update(self, grad, lr: float):
        """Apply the accumulated dense hot-grad (one jitted op; grads stay
        device-resident end to end)."""
        from ..core.lazy import concrete as _conc

        g = _conc(grad._data) if isinstance(grad, Tensor) else grad
        self._set_rows(_jit_dense_sgd(self._rows, g, np.float32(lr)))

    # -- membership --------------------------------------------------------
    def lookup(self, uniq: np.ndarray, count_stats: bool = True):
        """(hit_mask, slots_of_hits) for sorted-or-not unique ids.
        ``count_stats=False`` for push-side routing lookups: only the PULL
        defines hit-rate and eviction heat, or every id would be counted
        twice per step.

        A pending pressure shrink is NOT applied here: renumbering slots
        and swapping the dense leaf mid-step would orphan staged prefetch
        packs and in-step accumulated grads — the owning layer applies it
        at the push (post-grad-consumption) and invalidates its staging."""
        if self._ids_sorted.size == 0:
            return np.zeros(uniq.shape, bool), np.empty(0, np.int64)
        pos = np.searchsorted(self._ids_sorted, uniq)
        pos_c = np.minimum(pos, self._ids_sorted.size - 1)
        hit = self._ids_sorted[pos_c] == uniq
        slots = self._slots_sorted[pos_c[hit]]
        if count_stats:
            self._slot_hits[slots] += 1
            self.hits += int(hit.sum())
            self.misses += int(uniq.size - hit.sum())
        return hit, slots

    def _cm_hashes(self, ids: np.ndarray):
        """The count-min sketch's two bucket streams — ONE definition, or a
        drifted edit would write sightings to different buckets than
        admission reads (a cache that silently never admits)."""
        u = ids.astype(np.uint64)
        h0 = (_splitmix64(u) & np.uint64(self._cmw - 1)).astype(np.int64)
        h1 = (_splitmix64(u ^ np.uint64(0xD6E8FEB86659FD93)) &
              np.uint64(self._cmw - 1)).astype(np.int64)
        return h0, h1

    def observe_misses(self, missed_uniq: np.ndarray):
        """Count-min update for missed ids (one sighting per step each)."""
        if missed_uniq.size == 0:
            return
        h0, h1 = self._cm_hashes(missed_uniq)
        self._cms[0] += np.bincount(h0, minlength=self._cmw)
        self._cms[1] += np.bincount(h1, minlength=self._cmw)

    def admission_candidates(self, missed_uniq: np.ndarray) -> np.ndarray:
        if missed_uniq.size == 0 or not self._free:
            return missed_uniq[:0]
        h0, h1 = self._cm_hashes(missed_uniq)
        est = np.minimum(self._cms[0][h0], self._cms[1][h1])
        cand = missed_uniq[est >= self.min_count]
        return cand[:len(self._free)]

    # -- admission / eviction ---------------------------------------------
    def _pad_slots(self, slots: np.ndarray) -> np.ndarray:
        """Pad a slot vector to a grow-only pow-2 length with the dummy
        slot (stable scatter shapes, one compile after warmup)."""
        self._pad_hwm = max(getattr(self, "_pad_hwm", 16), _pad_pow2(slots.size))
        p = self._pad_hwm
        if p == slots.size:
            return slots
        out = np.full(p, self.capacity, np.int64)
        out[: slots.size] = slots
        return out

    def admit(self, ids: np.ndarray, rows: np.ndarray,
              accum: Optional[np.ndarray] = None):
        """Install host rows (one H2D — the last PCIe crossing these rows
        make until eviction). Caller passes post-update values."""
        k = min(int(ids.size), len(self._free))
        if k == 0:
            return
        ids = ids[:k]
        slots = np.array([self._free.pop() for _ in range(k)], np.int64)
        self._slot_ids[slots] = ids
        self._slot_hits[slots] = 1
        padded = self._pad_slots(slots)
        vals = np.zeros((padded.size, self.dim), np.float32)
        vals[:k] = _c_f32(rows[:k])
        sl = jnp.asarray(padded)
        self._set_rows(self._rows.at[sl].set(jnp.asarray(vals)))
        if self._accum is not None:
            a = np.zeros(padded.size, np.float32)
            if accum is not None:
                a[:k] = _c_f32(accum[:k])
            self._accum = self._accum.at[sl].set(jnp.asarray(a))
        self._rebuild_index()
        _prof.counter_inc("host_emb_cache_admitted", k)

    def evict(self, slots: np.ndarray):
        """Write back and free the given slots."""
        slots = np.asarray(slots, np.int64)
        slots = slots[self._slot_ids[slots] >= 0]
        if slots.size == 0:
            return
        ids = self._slot_ids[slots]
        rows = np.asarray(self._rows[jnp.asarray(slots)])
        self.table._ensure_init(ids)  # row may predate its first host touch
        self.table.table[ids] = rows.astype(self.table.dtype)
        if self._accum is not None:
            self.table._accum[ids] = np.asarray(self._accum[jnp.asarray(slots)])
        self._slot_ids[slots] = -1
        self._slot_hits[slots] = 0
        self._free.extend(int(s) for s in slots)
        self._rebuild_index()
        _prof.counter_inc("host_emb_cache_evicted", int(slots.size))

    def flush(self):
        """Write every cached row back to the host table (rows STAY cached;
        the device remains authoritative for future updates). Gives
        checkpoint/eval readers a coherent host snapshot."""
        occ = np.nonzero(self._slot_ids >= 0)[0]
        if occ.size == 0:
            return
        ids = self._slot_ids[occ]
        rows = np.asarray(self._rows[jnp.asarray(occ)])
        self.table._ensure_init(ids)
        self.table.table[ids] = rows.astype(self.table.dtype)
        if self._accum is not None:
            self.table._accum[ids] = np.asarray(self._accum[jnp.asarray(occ)])

    # -- sparse update ------------------------------------------------------
    def update(self, slots: np.ndarray, grad: np.ndarray, lr: float):
        """Device-side SelectedRows update of cached rows (the push's hot
        half). SGD is bitwise-identical to the host rule; Adagrad matches to
        reduction-order rounding (device mean vs sequential host sum). Pad
        lanes aim zero grads at the dummy row (zero update, and the dummy's
        accum stays finite so its scale can't NaN)."""
        k = int(np.asarray(slots).size)
        padded = self._pad_slots(np.asarray(slots, np.int64))
        gp = np.zeros((padded.size, self.dim), np.float32)
        gp[:k] = _c_f32(grad)
        sl = jnp.asarray(padded)
        g = jnp.asarray(gp)
        if self._accum is not None:
            rows, self._accum = _jit_ada_cache(
                self._rows, self._accum, sl, g, np.float32(lr),
                np.float32(self.table.adagrad_eps))
            self._set_rows(rows)
        else:
            self._set_rows(_jit_sgd_cache(self._rows, sl, g, np.float32(lr)))

    def rows_device(self, slots: np.ndarray):
        """Device gather of cached rows (no host crossing)."""
        return _jit_gather_rows(self._rows,
                                jnp.asarray(np.asarray(slots, np.int64)))

    # -- pressure ----------------------------------------------------------
    def _request_shrink(self):
        # called on the free_pressure caller's thread: cheap flag only, the
        # owner applies it at its next touch (serving-pool discipline)
        self._shrink_req = True
        occ = int((self._slot_ids >= 0).sum())
        return {"requested": True, "occupied_rows": occ,
                "capacity_rows": self.capacity}

    def _apply_shrink(self):
        self._shrink_req = False
        new_cap = max(1, self.capacity // 2)
        occ = np.nonzero(self._slot_ids >= 0)[0]
        if occ.size > new_cap:
            # keep the hottest; write the cold half back
            order = np.argsort(self._slot_hits[occ], kind="stable")
            self.evict(occ[order[: occ.size - new_cap]])
            occ = np.nonzero(self._slot_ids >= 0)[0]
        # rebuild smaller device buffers (frees the old allocation); keep
        # the extra dummy row at index == new capacity
        keep_ids = self._slot_ids[occ]
        keep_rows = self._rows[jnp.asarray(occ)][:new_cap]
        rows = jnp.zeros((new_cap + 1, self.dim), jnp.float32)
        self._set_rows(rows.at[jnp.arange(keep_ids.size)].set(keep_rows))
        if self._accum is not None:
            keep_acc = self._accum[jnp.asarray(occ)][:new_cap]
            acc = jnp.zeros((new_cap + 1,), jnp.float32)
            self._accum = acc.at[jnp.arange(keep_ids.size)].set(keep_acc)
        hits = self._slot_hits[occ]
        self.capacity = new_cap
        self._slot_ids = np.full(new_cap, -1, np.int64)
        self._slot_hits = np.zeros(new_cap, np.int64)
        self._slot_ids[: keep_ids.size] = keep_ids
        self._slot_hits[: keep_ids.size] = hits
        self._free = list(range(new_cap - 1, keep_ids.size - 1, -1))
        self._rebuild_index()
        _prof.counter_inc("host_emb_cache_shrinks")

    def _rebuild_index(self):
        occ = np.nonzero(self._slot_ids >= 0)[0]
        ids = self._slot_ids[occ]
        order = np.argsort(ids)
        self._ids_sorted = ids[order]
        self._slots_sorted = occ[order].astype(np.int64)

    def stats(self) -> dict:
        total = self.hits + self.misses
        return {
            "capacity_rows": self.capacity,
            "occupied_rows": int((self._slot_ids >= 0).sum()),
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": (self.hits / total) if total else 0.0,
        }


# -- pipelined PS worker ------------------------------------------------------
class _PSWorker:
    """One persistent daemon thread running the layer's host-side PS jobs
    (prefetch gathers, async pushes) in FIFO order. Holds only a WEAKREF to
    the owning layer: abandoning the layer fires a finalizer that wakes the
    queue with a sentinel so the thread exits instead of pinning the table
    (the PR 6 DevicePrefetcher discipline)."""

    _SENTINEL = object()

    def __init__(self, owner):
        self._q: _queue.Queue = _queue.Queue()
        self._thread = threading.Thread(
            target=_PSWorker._loop, args=(weakref.ref(owner), self._q),
            daemon=True, name="host-emb-ps",
        )
        self._finalizer = weakref.finalize(owner, self._q.put, _PSWorker._SENTINEL)
        self._thread.start()

    def submit(self, kind: str, payload: dict):
        self._q.put((kind, payload))

    def join(self):
        self._q.join()

    @staticmethod
    def _loop(owner_ref, q):
        while True:
            job = q.get()
            try:
                if job is _PSWorker._SENTINEL:
                    return
                owner = owner_ref()
                if owner is None:
                    return
                kind, payload = job
                try:
                    if kind == "gather":
                        owner._job_gather(payload)
                    else:
                        owner._job_apply(payload)
                except Exception as e:  # surfaced at consume/sync
                    payload["err"] = e
                    ev = payload.get("done")
                    if ev is not None:
                        ev.set()
                    owner._async_err = e
                finally:
                    del owner
            finally:
                q.task_done()


class HostEmbedding(Layer):
    """Embedding layer over a HostEmbeddingTable.

    Eager-mode by design: the gather crosses the host boundary, exactly like
    the reference's PS pull — the dense model around it can still run
    compiled. Call ``apply_gradients(lr)`` after ``backward()`` (the role of
    the PS push / SelectedRows optimizer).

    ``cache_rows`` (or ``FLAGS_host_emb_cache_rows``) arms the HBM hot-row
    cache; ``prefetch``/``prefetch_iter`` and ``FLAGS_host_emb_async_push``
    pipeline the pull/push through the PS worker thread. With everything at
    defaults the layer is the plain synchronous host path: no threads, no
    cache, no native entry points beyond the flag probe.
    """

    def __init__(self, num_embeddings, embedding_dim, path=None, optimizer="sgd",
                 init_std=0.01, seed=0, sparse=True, name=None, table=None,
                 cache_rows=None):
        super().__init__()
        # table=ShardedHostEmbeddingTable(...) makes this layer the worker
        # side of a multi-process PS (fleet wires this up from env)
        self.table = table or HostEmbeddingTable(
            num_embeddings, embedding_dim, path=path, optimizer=optimizer,
            init_std=init_std, seed=seed,
        )
        self._pending = []  # (pack_order_ids, rows_tensor) awaiting push
        # one lock serializes table reads (PS worker thread) against the
        # sparse updates — torn rows are silent corruption
        self._table_lock = threading.Lock()
        self._worker: Optional[_PSWorker] = None
        self._slots: List[dict] = []  # in-flight prefetch slots, FIFO
        self._async_err: Optional[BaseException] = None
        # ordering barrier for async pushes: staged packs may be patched by
        # an in-flight push, so a pull consumes them only after the LAST
        # submitted push (and its patches) completed. _push_seq counts async
        # submissions; a slot prefetched at the current seq needs no barrier
        # (worker FIFO already ran every earlier push before its gather).
        self._last_push_done: Optional[threading.Event] = None
        self._push_seq = 0
        if cache_rows is None:
            cache_rows = int(_flags.flag("FLAGS_host_emb_cache_rows", 0) or 0)
        self.cache: Optional[HotRowCache] = None
        if cache_rows > 0 and not isinstance(self.table, ShardedHostEmbeddingTable):
            self.cache = HotRowCache(self.table, cache_rows)
        # high-water-mark shape buckets per pack segment: grow-only pow-2
        # padding converges on ONE stable shape per segment, so the traced
        # step graph (keyed by every microbatch's pack shape) compiles a
        # handful of times instead of once per unique-count combination
        self._pad_hwm = {"hot": 16, "cold": 16, "plain": 16, "patch": 16}

    # -- PS worker ----------------------------------------------------------
    def _ensure_worker(self) -> _PSWorker:
        if self._worker is None:
            self._worker = _PSWorker(self)
        return self._worker

    def _check_async_err(self):
        if self._async_err is not None:
            err, self._async_err = self._async_err, None
            raise RuntimeError("host embedding PS worker failed") from err

    def sync(self):
        """Drain the PS worker (pending prefetches + async pushes). Call
        before reading table state externally (checkpoint, eval snapshots).
        Flushes the hot-row cache to the host table as well."""
        if self._worker is not None:
            t0 = time.perf_counter_ns()
            self._worker.join()
            _prof.counter_inc("host_emb_block_ns",
                              time.perf_counter_ns() - t0)
        self._check_async_err()
        if self.cache is not None:
            with self._table_lock:
                self.cache.flush()

    # -- pipelined pull -----------------------------------------------------
    def prefetch(self, x):
        """Start the host pull for upcoming batches on the PS worker thread
        so it overlaps the current device step (the reference's buffered PS
        pull): unique → cold-row gather → device_put, all off the critical
        path. ``x`` is one id batch or a LIST of them (a whole step's
        microbatches): a list stages ONE union pack in ONE worker job —
        next-step ids are all known at enqueue time, so an 8-microbatch
        step costs one queue round trip and one unique/gather instead of
        eight. forward() consumes the staged sub-batches in order as ids
        match.

        No-op on a SHARDED table: its gather is a lockstep collective across
        ranks, and an extra/mismatched gather from a background thread would
        desynchronize the exchange protocol."""
        if isinstance(self.table, ShardedHostEmbeddingTable):
            return
        self._check_async_err()
        batches = x if isinstance(x, (list, tuple)) else [x]
        ids_list = [
            np.ascontiguousarray(
                np.asarray(b._data if isinstance(b, Tensor) else b),
                np.int64).ravel()
            for b in batches
        ]
        # keyed on the RAW id bytes: the trainer-side cost of a prefetch (and
        # of consuming one) is a memcpy + dict fields — the unique/inverse
        # run on the worker with everything else
        slot = {"keys": [i.tobytes() for i in ids_list], "ids_list": ids_list,
                "cursor": 0, "uniq": None, "stage": None, "invs": None,
                "inverse_u": None, "stale": False, "seq": self._push_seq,
                "done": threading.Event(), "err": None}
        self._slots.append(slot)
        # bound the queue: a caller whose forwards never match its
        # prefetches (wrong batch handed in) must not accumulate staged
        # packs without limit — drop the oldest instead
        while len(self._slots) > 8:
            self._slots.pop(0)
            _prof.counter_inc("host_emb_prefetch_drops")
        self._ensure_worker().submit("gather", slot)

    def prefetch_iter(self, it, lookahead: int = 1):
        """Wrap an iterator of id batches: keeps ``lookahead`` batches'
        pulls in flight so every ``forward`` consumes a staged pack.
        Abandoning the (half-consumed) generator drops the layer's slot
        refs; the worker thread itself is owned by the layer, not the
        iterator, and dies with the layer (weakref discipline)."""
        it = iter(it)
        ahead = []
        try:
            for _ in range(max(1, lookahead)):
                nxt = next(it, None)
                if nxt is None:
                    break
                self.prefetch(nxt)
                ahead.append(nxt)
            while ahead:
                cur = ahead.pop(0)
                nxt = next(it, None)
                if nxt is not None:
                    self.prefetch(nxt)
                    ahead.append(nxt)
                yield cur
        finally:
            ahead.clear()

    def _job_gather(self, slot):
        """(worker thread) unique the slot's ids, gather the rows and stage
        them device-side: cache hits are read on device, cold rows gathered
        from the host table (native kernels) and device_put, the final
        inverse precomputed — consuming the slot costs the trainer a key
        compare. Slot fields are assigned under the table lock so a
        concurrent push's patch pass and this staging can never interleave
        half-written."""
        cat = (np.concatenate(slot["ids_list"])
               if len(slot["ids_list"]) > 1 else slot["ids_list"][0])
        with _span("host_emb.prefetch", rows=int(cat.size),
                   batches=len(slot["ids_list"])):
            uniq, inverse = _unique(cat)
            with self._table_lock:
                stage = self._build_pack(uniq, pad=True)
                slot["uniq"] = uniq
                slot["inverse_u"] = inverse  # uniq-space; patch reuses it
                slot["stage"] = stage
                slot["invs"] = self._split_invs(slot, stage, inverse)
        slot["done"].set()

    @staticmethod
    def _split_invs(slot, stage, inverse):
        """Per-sub-batch inverse vectors (pack-space) out of the union
        inverse."""
        inv = (stage["perm"][inverse] if stage["perm"] is not None
               else inverse)
        out, off = [], 0
        for ids in slot["ids_list"]:
            out.append(inv[off:off + ids.size])
            off += ids.size
        return out

    def _bucket(self, segment: str, n: int) -> int:
        hwm = max(self._pad_hwm[segment], _pad_pow2(n))
        self._pad_hwm[segment] = hwm
        return hwm

    def _build_pack(self, uniq: np.ndarray, pad: bool = False):
        """Stage rows for unique ids; returns a STAGE dict the trainer turns
        into tensors with :meth:`_stage_to_rows`. Caller holds the table
        lock.

        Modes: ``dense`` — SGD hot-row cache; only the cold rows and the
        (padded) hot slot vector are staged, the hot gather + concat are
        recorded into the step graph at forward time against the cache's
        LEAF buffer (grads accumulate densely on it, hot rows and grads
        never leave the device). ``packed`` — Adagrad cache: the combined
        pack is computed here (one jitted call). ``plain`` — no cache.

        ``pad`` buckets the hot/cold segment lengths to powers of two
        (dummy-slot gathers, zero rows, ``-1`` order_ids filtered at push)
        so the device ops and the traced step graph see a bounded shape
        vocabulary instead of one compile per distinct unique-count — the
        cache and prefetch paths always pad; the plain synchronous fallback
        keeps the exact pre-PR unpadded shapes."""
        cache = self.cache
        dim = self.table.embedding_dim
        if cache is not None:
            pad = True
            hit, slots = cache.lookup(uniq)
            nh = int(hit.sum())
        else:
            hit, slots, nh = None, None, 0
        if nh:
            cold_uniq = uniq[~hit]
            nc = int(cold_uniq.size)
            cache.observe_misses(cold_uniq)
            hp = self._bucket("hot", nh)
            hot_slots = np.full(hp, cache.capacity, np.int64)
            hot_slots[:nh] = slots
            sl = jnp.asarray(hot_slots)
            if nc:
                cp = self._bucket("cold", nc)
                cold_p = np.zeros((cp, dim), np.float32)
                cold_p[:nc] = self.table.gather(cold_uniq)
                cold_ids = np.full(cp, -1, np.int64)
                cold_ids[:nc] = cold_uniq
                cold_dev = jnp.asarray(cold_p)
            else:
                cp, cold_ids, cold_dev, cold_p = 0, None, None, None
            perm = np.empty(uniq.size, np.int64)
            perm[hit] = np.arange(nh)
            perm[~hit] = hp + np.arange(nc)
            _prof.counter_inc("host_emb_hot_hits", nh)
            _prof.counter_inc("host_emb_hot_misses", nc)
            if cache.dense:
                return {"mode": "dense", "hot_slots_dev": sl,
                        "cold_dev": cold_dev, "cold_ids": cold_ids,
                        "perm": perm}
            pack = (_jit_pack(cache._rows, sl, cold_dev) if nc
                    else _jit_gather_rows(cache._rows, sl))
            order_ids = np.full(hp + cp, -1, np.int64)
            order_ids[:nh] = uniq[hit]
            order_ids[hp:hp + nc] = cold_uniq
            return {"mode": "packed", "pack": pack, "order_ids": order_ids,
                    "perm": perm}
        if cache is not None:
            cache.observe_misses(uniq)
            _prof.counter_inc("host_emb_hot_misses", int(uniq.size))
        nu = int(uniq.size)
        if pad:
            p = self._bucket("plain", nu)
            rows_p = np.zeros((p, dim), np.float32)
            rows_p[:nu] = self.table.gather(uniq)
            pack = jnp.asarray(rows_p)
            order_ids = np.full(p, -1, np.int64)
            order_ids[:nu] = uniq
        else:
            pack = jnp.asarray(self.table.gather(uniq))
            order_ids = uniq
        mode = "dense_cold" if (cache is not None and cache.dense) else "plain"
        return {"mode": mode, "pack": pack, "order_ids": order_ids,
                "perm": None}

    def _stage_to_rows(self, stage):
        """(trainer) turn a stage into the differentiable rows tensor plus
        the push-pending entry. Dense stages RECORD the hot gather + concat
        lazily against the cache's leaf buffer — pure graph bookkeeping, no
        device dispatch — so the combine executes fused into the step's
        flush; grads land densely on the buffer (hot) and on the cold leaf
        (pushed to the host table)."""
        mode = stage["mode"]
        if mode == "dense":
            # SGD: ONE cold LEAF per stage, shared by every sub-batch that
            # consumes it — cold grads (like the dense buffer's hot grads)
            # accumulate across microbatches, so the push moves one leaf's
            # worth of bytes, not one per microbatch. The pack op itself is
            # re-recorded per consume: backward frees graph NODES, only
            # leaves survive across microbatch backwards.
            buf_t = self.cache.rows_t
            if "slots_t" not in stage:
                stage["slots_t"] = Tensor(stage["hot_slots_dev"])
            if stage["cold_dev"] is not None:
                pend = None
                if "cold_t" not in stage:
                    stage["cold_t"] = Tensor(stage["cold_dev"],
                                             stop_gradient=False)
                    pend = (stage["cold_ids"], stage["cold_t"])
                rows = eager_call(
                    "host_emb_pack",
                    lambda b, s, c: jnp.concatenate([b[s], c], axis=0),
                    [buf_t, stage["slots_t"], stage["cold_t"]],
                )
                return rows, pend
            rows = eager_call(
                "host_emb_pack_hot", lambda b, s: b[s],
                [buf_t, stage["slots_t"]])
            return rows, None
        if mode == "dense_cold":
            # a leaf survives repeated backwards: share it (grads accumulate)
            if "rows_cached" in stage:
                return stage["rows_cached"], None
            rows = Tensor(stage["pack"], stop_gradient=False)
            stage["rows_cached"] = rows
            return rows, (stage["order_ids"], rows)
        rows = Tensor(stage["pack"], stop_gradient=False)
        return rows, (stage["order_ids"], rows)

    def _consume_prefetch(self, key: bytes):
        """Find the slot whose NEXT unconsumed sub-batch matches ``key``
        (prefetch ordering contract: sub-batches are consumed in submission
        order, so slots staged BEFORE the match were skipped by the caller
        and are dropped, as are slots a mid-step push marked stale). Slots
        ahead of the consumer stay queued. No match leaves the queue intact
        and the pull falls back to synchronous. Returns (slot, inverse)."""
        if any(s["stale"] for s in self._slots):
            self._slots = [s for s in self._slots if not s["stale"]]
        for j, slot in enumerate(self._slots):
            if slot["keys"][slot["cursor"]] != key:
                continue
            if slot["seq"] != self._push_seq:
                # staged before a later push: wait for that push's patch
                # pass BEFORE unlisting the slot (the patch pass can only
                # repair slots it can still see). A slot prefetched after
                # the push needs no barrier — the worker FIFO ran the push
                # before its gather.
                self._await_pushes()
                if slot["stale"]:
                    self._slots.remove(slot)
                    return None
            # drop skipped older slots; their packs were read-only staging
            _prof.counter_inc("host_emb_prefetch_drops", j)
            del self._slots[:j]
            # waits here land inside forward's host_emb_block_ns window —
            # no separate counting, or blocking time would be billed twice
            slot["done"].wait()
            if slot["err"] is not None:
                raise RuntimeError("host embedding prefetch failed") from slot["err"]
            inverse = slot["invs"][slot["cursor"]]
            slot["cursor"] += 1
            if slot["cursor"] >= len(slot["keys"]):
                self._slots.remove(slot)
            _prof.counter_inc("host_emb_prefetch_hits")
            return slot, inverse
        return None

    def _await_pushes(self):
        """Block until the last async push (and its staged-pack patches)
        landed — a pull must observe every push submitted before it, exactly
        like the synchronous path. Callers are inside forward's
        host_emb_block_ns window; counting here too would double-bill."""
        ev = self._last_push_done
        if ev is not None and not ev.is_set():
            ev.wait()

    # -- forward ------------------------------------------------------------
    def forward(self, x):
        self._check_async_err()
        xt = as_tensor(x)
        ids = np.ascontiguousarray(np.asarray(_concrete(xt._data)), np.int64)
        t0 = time.perf_counter_ns()
        hit = self._consume_prefetch(ids.ravel().tobytes()) if self._slots else None
        if hit is not None:
            slot, inverse = hit
            stage = slot["stage"]
        else:
            self._await_pushes()
            uniq, inverse = _unique(ids.ravel())
            with self._table_lock:
                stage = self._build_pack(uniq)
            if stage["perm"] is not None:
                inverse = stage["perm"][inverse]
        _prof.counter_inc("host_emb_lookups", int(ids.size))
        _prof.counter_inc("host_emb_block_ns", time.perf_counter_ns() - t0)
        rows, pend = self._stage_to_rows(stage)
        if self.training and pend is not None:
            self._pending.append(pend)
        inv = Tensor(jnp.asarray(inverse.reshape(ids.shape)))

        out = eager_call(
            "host_embedding_select",
            lambda r, iv: r[iv],
            [rows, inv],
        )
        return out

    # -- push ---------------------------------------------------------------
    def apply_gradients(self, lr: float):
        """Push: apply accumulated sparse grads to the host table. Pending
        microbatches are COALESCED first — duplicate ids across microbatches
        merge into one row update (one gather/scatter on the table, and for
        the sharded table one pull/push round instead of one per microbatch).
        Under ``FLAGS_host_emb_async_push`` the D2H + merge + scatter run on
        the PS worker; ordering against later pulls/prefetches is the
        worker's FIFO, and staged packs the push overlaps are re-gathered."""
        self._check_async_err()
        ids_list, grad_list = [], []
        for order_ids, rows in self._pending:
            if rows.grad is not None:
                ids_list.append(order_ids)
                # keep the lazy/async handle: np.asarray happens at apply
                # time (worker thread under async push), not here — the
                # _concrete here only dispatches the pending flush
                grad_list.append(_concrete(rows.grad._data))
        self._pending = []
        # dense-leaf hot half (SGD cache): autograd already coalesced every
        # microbatch's hot grads onto the buffer; ONE jitted dense update
        # applies them, device-resident end to end. Runs after the flush
        # dispatch above, so the grad handle is an async future, and the
        # trainer pays a single dispatch — counted as PS-blocking time.
        cache = self.cache
        if cache is not None and cache.dense and cache.rows_t is not None \
                and cache.rows_t.grad is not None:
            t0 = time.perf_counter_ns()
            with self._table_lock:
                cache.dense_update(cache.rows_t.grad, lr)
            _prof.counter_inc("host_emb_block_ns",
                              time.perf_counter_ns() - t0)
        if cache is not None and cache._shrink_req:
            # the all-hot step never reaches _apply_local's check below
            with self._table_lock:
                self._maybe_shrink_cache()
        sharded = isinstance(self.table, ShardedHostEmbeddingTable)
        if not ids_list and not sharded:
            return
        # a SHARDED push is a lockstep collective: a rank with nothing to
        # push must still participate (empty payload), or peers deadlock in
        # store.wait() and the _gen counters diverge
        payload = {"ids_list": ids_list, "grad_list": grad_list, "lr": lr}
        if (_flags.flag("FLAGS_host_emb_async_push", False) and not sharded):
            t0 = time.perf_counter_ns()
            payload["done"] = threading.Event()
            self._last_push_done = payload["done"]
            self._push_seq += 1
            self._ensure_worker().submit("apply", payload)
            _prof.counter_inc("host_emb_block_ns",
                              time.perf_counter_ns() - t0)
            return
        t0 = time.perf_counter_ns()
        self._job_apply(payload)
        _prof.counter_inc("host_emb_block_ns", time.perf_counter_ns() - t0)

    def _job_apply(self, payload):
        """Apply one coalesced push (trainer thread, or PS worker under
        async push)."""
        try:
            ids_list = payload["ids_list"]
            grad_list = [np.asarray(g, np.float32) for g in payload["grad_list"]]
            # drop shape-padding lanes (order_ids == -1, zero grads; pads
            # sit after each hot/cold segment, not only at the tail)
            for i, ids_i in enumerate(ids_list):
                if ids_i.size and (ids_i < 0).any():
                    keep = ids_i >= 0
                    ids_list[i] = ids_i[keep]
                    grad_list[i] = grad_list[i][keep]
            lr = payload["lr"]
            dim = self.table.embedding_dim
            sharded = isinstance(self.table, ShardedHostEmbeddingTable)
            with _span("host_emb.push",
                       rows=int(sum(i.size for i in ids_list)) if ids_list else 0):
                # adagrad's accumulator is step-count sensitive: one update
                # with the summed grad != one update per microbatch. For a
                # LOCAL table the coalescing buys nothing (no comm round), so
                # keep per-microbatch semantics there; the sharded table
                # coalesces (one pull/push round) and documents the
                # summed-grad semantics as the distributed contract.
                if not sharded and getattr(self.table, "optimizer", "sgd") == "adagrad":
                    with self._table_lock:
                        for ids_i, grad_i in zip(ids_list, grad_list):
                            self._apply_local(ids_i, grad_i, lr)
                    self._patch_slots(np.concatenate(ids_list) if ids_list else None)
                    return
                uniq, merged = _merge_sparse_grads(ids_list, grad_list, dim)
                if uniq.size == 0 and not sharded:
                    return
                with self._table_lock:
                    if sharded:
                        self.table.apply_update(uniq, merged, lr)
                    else:
                        self._apply_local(uniq, merged, lr)
                self._patch_slots(uniq)
        finally:
            ev = payload.get("done")
            if ev is not None:
                ev.set()

    def _maybe_shrink_cache(self):
        """Apply a requested pressure shrink at a PUSH boundary (the dense
        grad is already consumed) and invalidate staged packs holding the
        old slot numbering — their consumers fall back to a synchronous
        pull. Caller holds the table lock."""
        cache = self.cache
        if cache is None or not cache._shrink_req:
            return
        cache._apply_shrink()
        for slot in list(self._slots):
            if slot["stage"] is not None:
                slot["stale"] = True

    def _apply_local(self, uniq: np.ndarray, merged: np.ndarray, lr: float):
        """Split one merged update between the device cache (hot rows,
        updated in place — no PCIe crossing for the rows) and the host
        table (cold rows, native scatter); then admit newly-frequent ids
        with their post-update values. Caller holds the table lock."""
        cache = self.cache
        if cache is None:
            self.table.apply_update(uniq, merged, lr)
            return
        self._maybe_shrink_cache()
        hit, slots = cache.lookup(uniq, count_stats=False)
        nh = int(hit.sum())
        if nh:
            cache.update(slots, merged[hit], lr)
        cold = uniq[~hit]
        if cold.size:
            self.table.apply_update(cold, merged[~hit], lr)
            cand = cache.admission_candidates(cold)
            if cand.size:
                rows = self.table.gather(cand)
                acc = (self.table._accum[cand]
                       if self.table._accum is not None else None)
                cache.admit(cand, rows, acc)

    def _patch_slots(self, updated_ids: Optional[np.ndarray]):
        """A push that lands while later batches' packs are already staged
        must not leave them stale: re-stage any in-flight slot whose ids
        intersect the update (frequent ids recur batch-to-batch, so this is
        the common case, and the re-gather still runs on whichever thread
        applied the push — off the trainer under async push)."""
        if updated_ids is None or not self._slots:
            return
        upd = np.unique(updated_ids)
        for slot in list(self._slots):
            if slot["stage"] is None:
                continue  # gather still queued: FIFO runs it after this push
            if np.intersect1d(slot["uniq"], upd, assume_unique=True).size == 0:
                continue
            if slot["cursor"] > 0:
                # partially consumed: earlier sub-batches' tensors already
                # feed live graphs, so the staging can't be swapped out —
                # mark stale; the consumer drops it and pulls synchronously
                slot["stale"] = True
                continue
            # value-only patch: refresh just the pushed rows inside the
            # staged block (hot rows read the live buffer at consume time
            # and never go stale; membership drift is routed by the push's
            # live lookup). One small H2D + one jitted row scatter — far
            # cheaper than a full re-stage; this runs inside the push.
            stage = slot["stage"]
            if stage["mode"] == "packed":
                # adagrad pack: hot and cold interleave in pack order, so a
                # positional value-patch doesn't apply — rebuild (rare path)
                with self._table_lock:
                    stage = self._build_pack(slot["uniq"], pad=True)
                    slot["stage"] = stage
                    slot["invs"] = self._split_invs(slot, stage,
                                                    slot["inverse_u"])
                _prof.counter_inc("host_emb_prefetch_patched")
                continue
            staged_ids = (stage["cold_ids"] if stage["mode"] == "dense"
                          else stage["order_ids"])
            if staged_ids is None:
                continue  # hot-only stage: nothing host-backed to refresh
            valid = staged_ids[staged_ids >= 0]  # sorted (uniq order)
            isect = np.intersect1d(valid, upd, assume_unique=True)
            if isect.size == 0:
                continue
            with self._table_lock:
                # positions within the staged block; -1 pads sit after the
                # valid prefix in every mode's id vector
                base = np.searchsorted(valid, isect)
                rows = None
                if self.cache is not None and self.cache._ids_sorted.size:
                    # ids staged COLD but cache members by now (admitted by
                    # this or an earlier push) have their AUTHORITATIVE copy
                    # on the device — the host row goes stale after the
                    # next device-side update — so refresh those from the
                    # cache buffer and only the rest from the host table
                    srt = self.cache._ids_sorted
                    p = np.minimum(np.searchsorted(srt, isect), srt.size - 1)
                    member = srt[p] == isect
                    if member.any():
                        rows = np.empty((isect.size, self.table.embedding_dim),
                                        np.float32)
                        buf = np.asarray(self.cache._rows)
                        rows[member] = buf[self.cache._slots_sorted[p[member]]]
                        if (~member).any():
                            rows[~member] = self.table.gather(isect[~member])
                if rows is None:
                    rows = self.table.gather(isect)
                pl = self._bucket("patch", isect.size)
                buf_len = int((stage["cold_dev"] if stage["mode"] == "dense"
                               else stage["pack"]).shape[0])
                # pad sentinel = one past the end: dropped by mode="drop",
                # and small enough to survive XLA's int32 index cast (a
                # huge sentinel would wrap and corrupt row 0)
                pos = np.full(pl, buf_len, np.int64)
                pos[: isect.size] = base
                vals = np.zeros((pl, self.table.embedding_dim), np.float32)
                vals[: isect.size] = rows
                if stage["mode"] == "dense":
                    stage["cold_dev"] = _jit_row_set(
                        stage["cold_dev"], jnp.asarray(pos), jnp.asarray(vals))
                else:
                    stage["pack"] = _jit_row_set(
                        stage["pack"], jnp.asarray(pos), jnp.asarray(vals))
            _prof.counter_inc("host_emb_prefetch_patched")

    def embedding_dim(self):
        return self.table.embedding_dim


# per-process construction counter: ranks build their tables in the same
# program order, so the index is a deterministic cross-rank identity
_instance_lock = threading.Lock()
_instance_count = 0  # guarded_by: _instance_lock


class ShardedHostEmbeddingTable:
    """Embedding table SHARDED BY ID across processes (id % world == owner),
    with pull/push over the native TCPStore — the distributed capability of
    the reference's brpc PS (``memory_sparse_table.cc`` shards by feature
    hash across servers; ``the_one_ps.py:606`` wires pull/push into train).
    Every rank is both worker and server: a gather is a collective exchange
    (all ranks request → serve owned rows → read replies), a push routes
    grads to the owners, which merge duplicate ids and apply ONE sparse
    update — sync-PS semantics, deterministic regardless of sharding.

    Transport: each (src, dst) exchange is ONE coalesced payload (push
    packs ids + grads together) split into ``FLAGS_host_emb_chunk_bytes``
    store messages moved by a pool of ``FLAGS_host_emb_transport_threads``
    dedicated store connections in parallel (the pre-PR path was one
    serial ≤512 KiB round trip at a time). ``FLAGS_host_emb_push_fp16``
    sends push grads as float16 (half the bytes; lossy, opt-in). Per-row
    deterministic lazy init means a row's value is identical no matter
    which shard materializes it.
    """

    def __init__(self, num_embeddings, embedding_dim, store, rank, world_size,
                 dtype="float32", path=None, init_std=0.01, seed=0,
                 optimizer="sgd", adagrad_eps=1e-8, name=None, store_addr=None):
        global _instance_count
        self.num_embeddings = int(num_embeddings)
        self.embedding_dim = int(embedding_dim)
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.store = store
        self.store_addr = store_addr
        # namespace every store key by table identity: two tables sharing one
        # TCPStore each count gens from 0, and without this a fast rank's
        # table-2 request could be consumed as a peer's table-1 traffic.
        # Two THREADS constructing tables concurrently must also get distinct
        # indices, or their tables would collide on one store namespace.
        with _instance_lock:
            idx = _instance_count
            _instance_count += 1
        self.name = name if name is not None else f"t{idx}"
        self._prefix = f"he/{self.name}"
        # local shard holds global ids {rank, rank+world, rank+2*world, …}
        n_local = (self.num_embeddings - self.rank + self.world_size - 1) // self.world_size
        self.local = HostEmbeddingTable(
            n_local, embedding_dim, dtype=dtype, path=path,
            init_std=init_std, seed=seed, optimizer=optimizer,
            adagrad_eps=adagrad_eps,
        )
        # per-row determinism across shardings: local row i is global id
        # i*world+rank, so init must hash the GLOBAL id
        self.local._ensure_init = self._ensure_init_local  # type: ignore
        self._seed = int(seed)
        self._std = float(init_std)
        self._gen = 0
        self._pool = None  # lazily-built parallel transport (client pool)

    def _ensure_init_local(self, local_ids: np.ndarray):
        t = self.local
        fresh = np.unique(local_ids[~t._initialized[local_ids]])
        if fresh.size == 0:
            return
        global_ids = fresh * self.world_size + self.rank
        t.table[fresh] = _hash_normal_rows(
            global_ids, t.embedding_dim, self._seed, self._std
        ).astype(t.dtype)
        t._initialized[fresh] = True
        t._n_initialized += int(fresh.size)

    # -- store transport ---------------------------------------------------
    @property
    def CHUNK(self) -> int:
        return int(_flags.flag("FLAGS_host_emb_chunk_bytes", 4 * 1024 * 1024)
                   or 512 * 1024)

    def _transport(self):
        """(clients, executors) for parallel chunk transport, or None for
        the serial path (no endpoint known / threads disabled). Each worker
        owns ONE dedicated connection — a TCPStore client is a single
        socket, and interleaving two requests on it would corrupt both."""
        nthreads = int(_flags.flag("FLAGS_host_emb_transport_threads", 4) or 0)
        if self._pool is None and nthreads > 0 and self.store_addr is not None:
            try:
                from concurrent.futures import ThreadPoolExecutor
                from ..core.native import TCPStore

                host, port = self.store_addr
                clients = [TCPStore(host=host, port=port, is_master=False)
                           for _ in range(nthreads)]
                execs = [ThreadPoolExecutor(max_workers=1,
                                            thread_name_prefix=f"he-tx{i}")
                         for i in range(nthreads)]
                self._pool = (clients, execs)
            except Exception:
                self._pool = False  # endpoint unusable: stay serial
        return self._pool or None

    def _put(self, key: str, payload: bytes):
        chunk = self.CHUNK
        n = (len(payload) + chunk - 1) // chunk or 1
        pool = self._transport() if n > 1 else None
        if pool is None:
            for i in range(n):
                self.store.set(f"{key}/{i}", payload[i * chunk:(i + 1) * chunk])
        else:
            clients, execs = pool
            futs = [
                execs[i % len(execs)].submit(
                    clients[i % len(clients)].set, f"{key}/{i}",
                    payload[i * chunk:(i + 1) * chunk])
                for i in range(n)
            ]
            for f in futs:
                f.result()
        self.store.set(key + "/n", str(n))

    def _take(self, key: str) -> bytes:
        chunk = self.CHUNK
        n = int(self.store.wait(key + "/n"))
        pool = self._transport() if n > 1 else None
        if pool is None:
            parts = [self.store.wait(f"{key}/{i}", max_bytes=chunk + 64)
                     for i in range(n)]
            for i in range(n):
                self.store.delete_key(f"{key}/{i}")
        else:
            clients, execs = pool

            def fetch(i):
                c = clients[i % len(clients)]
                part = c.wait(f"{key}/{i}", max_bytes=chunk + 64)
                c.delete_key(f"{key}/{i}")
                return part

            futs = [execs[i % len(execs)].submit(fetch, i) for i in range(n)]
            parts = [f.result() for f in futs]
        self.store.delete_key(key + "/n")
        return b"".join(parts)

    # push payloads coalesce ids + grads into one message:
    #   u64 n_ids | u8 fp16 | ids (n*8B) | grads (n*dim*4B or *2B)
    def _pack_push(self, ids: np.ndarray, grad: np.ndarray) -> bytes:
        fp16 = bool(_flags.flag("FLAGS_host_emb_push_fp16", False))
        g = np.ascontiguousarray(grad, np.float16 if fp16 else np.float32)
        return (struct.pack("<QB", ids.size, int(fp16))
                + np.ascontiguousarray(ids, np.int64).tobytes() + g.tobytes())

    def _unpack_push(self, payload: bytes):
        n, fp16 = struct.unpack_from("<QB", payload)
        off = 9
        ids = np.frombuffer(payload, np.int64, count=n, offset=off)
        off += n * 8
        dt = np.float16 if fp16 else np.float32
        grad = np.frombuffer(payload, dt, offset=off).reshape(-1, self.embedding_dim)
        return ids, np.ascontiguousarray(grad, np.float32)

    # -- collective pull ---------------------------------------------------
    def gather(self, ids: np.ndarray) -> np.ndarray:
        """Pull rows for (globally) unique ids; COLLECTIVE — every rank must
        call this the same number of times (data-parallel lockstep, like the
        reference's synchronous PS pull)."""
        ids = np.asarray(ids, np.int64)
        gen = self._gen
        self._gen += 1
        owner = ids % self.world_size
        out = np.empty((ids.size, self.embedding_dim), np.float32)
        with _span("host_emb.shard_pull", rows=int(ids.size)):
            # 1. send requests (own ids resolve locally)
            for o in range(self.world_size):
                if o == self.rank:
                    continue
                want = ids[owner == o]
                self._put(f"{self._prefix}/{gen}/req/{self.rank}/{o}", want.tobytes())
            mine = ids[owner == self.rank]
            if mine.size:
                out[owner == self.rank] = self.local.gather(mine // self.world_size)
            # 2. serve every other rank's request against the local shard
            for r in range(self.world_size):
                if r == self.rank:
                    continue
                req = np.frombuffer(self._take(f"{self._prefix}/{gen}/req/{r}/{self.rank}"), np.int64)
                rows = self.local.gather(req // self.world_size) if req.size else np.empty((0, self.embedding_dim), np.float32)
                self._put(f"{self._prefix}/{gen}/rep/{self.rank}/{r}", _c_f32(rows).tobytes())
            # 3. read replies
            for o in range(self.world_size):
                if o == self.rank:
                    continue
                rows = np.frombuffer(self._take(f"{self._prefix}/{gen}/rep/{o}/{self.rank}"), np.float32)
                out[owner == o] = rows.reshape(-1, self.embedding_dim)
        return out

    # -- collective push ---------------------------------------------------
    def apply_update(self, ids: np.ndarray, grad: np.ndarray, lr: float):
        """Push sparse grads to their owners; owners merge duplicates across
        ranks (sum, like gradient accumulation) then apply ONE update."""
        ids = np.asarray(ids, np.int64)
        grad = np.asarray(grad, np.float32)
        gen = self._gen
        self._gen += 1
        owner = ids % self.world_size
        with _span("host_emb.shard_push", rows=int(ids.size)):
            for o in range(self.world_size):
                if o == self.rank:
                    continue
                sel = owner == o
                payload = self._pack_push(ids[sel], grad[sel])
                # PUSH bytes only: pull req/rep traffic through the same
                # transport must not dilute the EQuARX-motivated metric
                _prof.counter_inc("host_emb_push_bytes", len(payload))
                self._put(f"{self._prefix}/{gen}/push/{self.rank}/{o}",
                          payload)
            all_ids = [ids[owner == self.rank]]
            all_grads = [grad[owner == self.rank]]
            for r in range(self.world_size):
                if r == self.rank:
                    continue
                gi, gg = self._unpack_push(
                    self._take(f"{self._prefix}/{gen}/push/{r}/{self.rank}"))
                all_ids.append(gi)
                all_grads.append(gg)
            uniq, merged = _merge_sparse_grads(all_ids, all_grads, self.embedding_dim)
            if uniq.size == 0:
                return
            self.local.apply_update(uniq // self.world_size, merged, lr)

    def close(self):
        if self._pool:
            clients, execs = self._pool
            for e in execs:
                e.shutdown(wait=False)
            for c in clients:
                try:
                    c.close()
                except Exception:
                    pass
            self._pool = None
