"""Fused transformer layers.

Parity: reference ``python/paddle/incubate/nn/layer/fused_transformer.py:25``
(FusedMultiHeadAttention) and ``:216`` (FusedFeedForward) backed by
``operators/fused/fused_attention_op.cu`` / ``fused_feedforward_op.cu``.
TPU-native: the "fusion" is XLA's job — these wrappers present the same API
over the already-fused functional path.
"""
from __future__ import annotations

from ...nn import functional as F
from ...nn.layer.common import Dropout, Linear
from ...nn.layer.layers import Layer
from ...nn.layer.norm import LayerNorm


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5, attn_dropout_rate=0.5, kdim=None, vdim=None, normalize_before=False, need_weights=False, qkv_weight_attr=None, qkv_bias_attr=None, linear_weight_attr=None, linear_bias_attr=None, pre_ln_scale_attr=None, pre_ln_bias_attr=None, ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-05, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.qkv_proj = Linear(embed_dim, 3 * embed_dim)
        self.out_proj = Linear(embed_dim, embed_dim)
        self.norm = LayerNorm(embed_dim, epsilon=epsilon)
        self.dropout = Dropout(dropout_rate)
        self.attn_dropout_rate = attn_dropout_rate

    def forward(self, query, attn_mask=None, cache=None):
        residual = query
        x = self.norm(query) if self.normalize_before else query
        qkv = self.qkv_proj(x)
        B, T = qkv.shape[0], qkv.shape[1]
        qkv = qkv.reshape([B, T, 3, self.num_heads, self.head_dim])
        q, k, v = qkv.unbind(axis=2)
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask, training=self.training)
        out = self.out_proj(out.reshape([B, T, self.embed_dim]))
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-05, activation="relu", act_dropout_rate=None, normalize_before=False, linear1_weight_attr=None, linear1_bias_attr=None, linear2_weight_attr=None, linear2_bias_attr=None, ln1_scale_attr=None, ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm = LayerNorm(d_model, epsilon=epsilon)
        self.dropout1 = Dropout(act_dropout_rate if act_dropout_rate is not None else dropout_rate)
        self.dropout2 = Dropout(dropout_rate)
        self.activation = getattr(F, activation)

    def forward(self, src, cache=None):
        residual = src
        x = self.norm(src) if self.normalize_before else src
        x = self.linear2(self.dropout1(self.activation(self.linear1(x))))
        out = residual + self.dropout2(x)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedTransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1, activation="relu", attn_dropout_rate=None, act_dropout_rate=None, normalize_before=False, name=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate, attn_dropout_rate or dropout_rate, normalize_before=normalize_before
        )
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate, activation=activation,
            act_dropout_rate=act_dropout_rate, normalize_before=normalize_before,
        )

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)
