"""Custom op / custom kernel registration.

Parity: reference ``paddle/fluid/framework/custom_operator.cc`` +
``phi/core/custom_kernel.cc`` + ``python/paddle/utils/cpp_extension`` — the
plugin path for user-defined ops. TPU-native: a user op is a jnp/Pallas
function (optionally with a custom vjp); registering it wires it through
``eager_call`` so it gets autograd/AMP/jit/nan-scan like built-ins, attaches
to the ``paddle`` namespace and (optionally) as a Tensor method.

    def my_gelu(x):
        return 0.5 * x * (1 + jnp.tanh(0.79788456 * (x + 0.044715 * x**3)))

    paddle.incubate.register_custom_op("my_gelu", my_gelu)
    y = paddle.my_gelu(t)          # autograd-ready

    # Pallas kernel with hand-written vjp:
    paddle.incubate.register_custom_op(
        "fused_thing", fwd_fn, vjp=(fwd_res_fn, bwd_fn))
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax

from ..core.dispatch import as_tensor, eager_call

_REGISTRY = {}


def register_custom_op(
    name: str,
    fn: Callable,
    vjp: Optional[Tuple[Callable, Callable]] = None,
    n_inputs: Optional[int] = None,
    differentiable: bool = True,
    nondiff_outputs: Sequence[int] = (),
    tensor_method: bool = False,
):
    """Register ``fn(*arrays, **attrs)`` as op ``name``.

    ``vjp``: optional (fwd, bwd) pair per ``jax.custom_vjp`` — fwd returns
    (out, residuals), bwd(residuals, cotangent) returns input cotangents.
    Returns the wrapper (also installed as ``paddle.<name>``).
    """
    if name in _REGISTRY:
        raise ValueError(f"custom op {name!r} already registered")

    impl = fn
    if vjp is not None:
        fwd, bwd = vjp
        impl = jax.custom_vjp(fn)
        impl.defvjp(fwd, bwd)

    import inspect

    param_names = list(inspect.signature(fn).parameters)

    def op(*inputs, **attrs):
        attrs.pop("name", None)
        k = n_inputs if n_inputs is not None else len(inputs)
        tensors = [as_tensor(t) for t in inputs[:k]]
        # trailing positionals are non-tensor attrs: map them onto fn's
        # remaining parameter names so fn(*arrays, **attrs) receives them
        for pname, val in zip(param_names[k:], inputs[k:]):
            attrs.setdefault(pname, val)
        return eager_call(
            f"custom.{name}", impl, tensors, attrs=attrs,
            differentiable=differentiable, nondiff_outputs=tuple(nondiff_outputs),
        )

    op.__name__ = name
    op.__doc__ = f"Custom op {name!r} (reference custom_operator.cc plugin path)."
    _REGISTRY[name] = op

    import paddle_tpu as _p

    if not hasattr(_p, name):
        setattr(_p, name, op)
    if tensor_method:
        from ..core.tensor import Tensor

        if not hasattr(Tensor, name):
            setattr(Tensor, name, op)
    return op


def get_custom_op(name: str):
    return _REGISTRY.get(name)


def registered_custom_ops():
    return sorted(_REGISTRY)


__all__ = ["register_custom_op", "get_custom_op", "registered_custom_ops"]
