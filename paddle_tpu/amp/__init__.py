"""AMP — automatic mixed precision.

Parity: reference dygraph AMP (``python/paddle/fluid/dygraph/amp/auto_cast.py``
O1/O2 op lists, ``paddle/fluid/imperative/amp_auto_cast.*`` tracer casts;
``paddle.amp.GradScaler`` over check_finite_and_unscale/update_loss_scaling
ops). TPU-native: bf16 is the default low-precision dtype (MXU-native, no
loss scaling needed); fp16 + dynamic loss scaling is kept for parity.
"""
from __future__ import annotations

import contextlib
from typing import Optional

import numpy as np
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core import dispatch
from ..core.tensor import Tensor

# O1 lists (reference fluid/dygraph/amp/auto_cast.py:33-79)
WHITE_LIST = {
    "matmul", "linear", "conv1d", "conv2d", "conv3d", "bmm", "mm", "addmm",
    "scaled_dot_product_attention", "einsum",
}
BLACK_LIST = {
    "exp", "square", "log", "mean", "sum", "cos_sim", "softmax",
    "softmax_with_cross_entropy", "sigmoid_cross_entropy_with_logits",
    "cross_entropy", "c_softmax_with_cross_entropy", "layer_norm", "norm",
    "batch_norm", "group_norm", "instance_norm", "logsumexp", "erf", "erfinv",
    "log_softmax", "mse_loss", "l1_loss", "nll_loss", "bce", "bce_with_logits",
}


class _AmpState:
    enabled = False
    dtype = dtypes.bfloat16
    level = "O1"


_state = _AmpState()


def _cast_tensors(tensors, dt):
    out = []
    for t in tensors:
        if dtypes.is_floating_point(t.dtype) and t.dtype != dt:
            from ..ops.math import cast

            out.append(cast(t, dt))
        else:
            out.append(t)
    return out


def _amp_hook(op_name, tensors):
    if not _state.enabled:
        return tensors
    if _state.level == "O2":
        if op_name in BLACK_LIST:
            return _cast_tensors(tensors, dtypes.float32)
        return _cast_tensors(tensors, _state.dtype)
    # O1: white list → low precision; black list → fp32; else follow inputs
    if op_name in WHITE_LIST:
        return _cast_tensors(tensors, _state.dtype)
    if op_name in BLACK_LIST:
        return _cast_tensors(tensors, dtypes.float32)
    return tensors


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None, level="O1", dtype="bfloat16"):
    """paddle.amp.auto_cast (reference amp_guard fluid/dygraph/amp/auto_cast.py:196)."""
    prev = (_state.enabled, _state.dtype, _state.level)
    prev_white = set(WHITE_LIST)
    prev_black = set(BLACK_LIST)
    if custom_white_list:
        WHITE_LIST.update(custom_white_list)
        BLACK_LIST.difference_update(custom_white_list)
    if custom_black_list:
        BLACK_LIST.update(custom_black_list)
        WHITE_LIST.difference_update(custom_black_list)
    _state.enabled = enable
    _state.dtype = dtypes.convert_dtype(dtype)
    _state.level = level
    dispatch.set_amp_hook(_amp_hook if enable else None)
    try:
        yield
    finally:
        _state.enabled, _state.dtype, _state.level = prev
        WHITE_LIST.clear()
        WHITE_LIST.update(prev_white)
        BLACK_LIST.clear()
        BLACK_LIST.update(prev_black)
        dispatch.set_amp_hook(_amp_hook if _state.enabled else None)


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16", master_weight=None, save_dtype=None):
    """Cast model params to low precision for O2 (reference amp_decorate)."""
    dt = dtypes.convert_dtype(dtype)
    singleton = not isinstance(models, (list, tuple))
    model_list = [models] if singleton else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=dt)
    if optimizers is None:
        return models
    return models, optimizers


class GradScaler:
    """Dynamic loss scaling (reference paddle/amp/grad_scaler.py:26 backed by
    check_finite_and_unscale + update_loss_scaling ops)."""

    def __init__(
        self,
        enable=True,
        init_loss_scaling=2.0**15,
        incr_ratio=2.0,
        decr_ratio=0.5,
        incr_every_n_steps=1000,
        decr_every_n_nan_or_inf=2,
        use_dynamic_loss_scaling=True,
    ):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = set()  # id(optimizer) already unscaled this step

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        if id(optimizer) in self._unscaled:
            return  # already unscaled this step (e.g. explicit unscale_ for
            # grad clipping followed by step()) — the reference tracks
            # OptimizerState.UNSCALED for exactly this
        self._unscaled.add(id(optimizer))
        found = False
        for p in optimizer._parameter_list or []:
            if p.grad is not None:
                from ..core.lazy import concrete

                # isfinite needs a real buffer — the arithmetic above would
                # otherwise stay lazy and jnp.* rejects LazyArray operands
                g = concrete(p.grad._data.astype(jnp.float32) / self._scale)
                found = bool(found or not bool(jnp.isfinite(g).all()))
                p.grad._set_data(g.astype(p.grad._data.dtype) if p.grad._data.dtype != jnp.float32 else g)
        self._found_inf = found

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()

    def update(self):
        self._unscaled.clear()  # next iteration may unscale again (even when
        # dynamic scaling is off — the early return below must not skip this)
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False
        self._unscaled.clear()

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_scale(self):
        return self._scale

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every_n_steps,
            "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
            "good_steps": self._good_steps,
            "bad_steps": self._bad_steps,
        }

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)

    set_state_dict = load_state_dict
