"""paddle.fft — discrete Fourier transform op family.

Parity: reference ``python/paddle/fft.py`` (fft/ifft/…/fftshift, backed by
cuFFT kernels ``paddle/fluid/operators/spectral_op.cu``). TPU-native: jnp.fft
lowers to XLA's FFT HLO which maps onto the TPU's dedicated FFT path; all ops
route through ``eager_call`` so they participate in autograd and jit capture.
"""
from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import as_tensor, eager_call


def _mk(name, fn, differentiable=True):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        t = as_tensor(x)
        return eager_call(
            f"fft.{name}", fn, [t],
            attrs={"n": n, "axis": axis, "norm": norm},
            differentiable=differentiable,
        )

    op.__name__ = name
    op.__doc__ = f"paddle.fft.{name} (reference python/paddle/fft.py)."
    return op


def _mk2(name, fn, differentiable=True):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        t = as_tensor(x)
        return eager_call(
            f"fft.{name}", fn, [t],
            attrs={"s": s, "axes": tuple(axes), "norm": norm},
            differentiable=differentiable,
        )

    op.__name__ = name
    return op


fft = _mk("fft", lambda a, n=None, axis=-1, norm="backward": jnp.fft.fft(a, n=n, axis=axis, norm=norm))
ifft = _mk("ifft", lambda a, n=None, axis=-1, norm="backward": jnp.fft.ifft(a, n=n, axis=axis, norm=norm))
rfft = _mk("rfft", lambda a, n=None, axis=-1, norm="backward": jnp.fft.rfft(a, n=n, axis=axis, norm=norm))
irfft = _mk("irfft", lambda a, n=None, axis=-1, norm="backward": jnp.fft.irfft(a, n=n, axis=axis, norm=norm))
hfft = _mk("hfft", lambda a, n=None, axis=-1, norm="backward": jnp.fft.hfft(a, n=n, axis=axis, norm=norm))
ihfft = _mk("ihfft", lambda a, n=None, axis=-1, norm="backward": jnp.fft.ihfft(a, n=n, axis=axis, norm=norm))
fft2 = _mk2("fft2", lambda a, s=None, axes=(-2, -1), norm="backward": jnp.fft.fft2(a, s=s, axes=axes, norm=norm))
ifft2 = _mk2("ifft2", lambda a, s=None, axes=(-2, -1), norm="backward": jnp.fft.ifft2(a, s=s, axes=axes, norm=norm))
rfft2 = _mk2("rfft2", lambda a, s=None, axes=(-2, -1), norm="backward": jnp.fft.rfft2(a, s=s, axes=axes, norm=norm))
irfft2 = _mk2("irfft2", lambda a, s=None, axes=(-2, -1), norm="backward": jnp.fft.irfft2(a, s=s, axes=axes, norm=norm))
fftn = _mk2("fftn", lambda a, s=None, axes=None, norm="backward": jnp.fft.fftn(a, s=s, axes=axes, norm=norm))
ifftn = _mk2("ifftn", lambda a, s=None, axes=None, norm="backward": jnp.fft.ifftn(a, s=s, axes=axes, norm=norm))
rfftn = _mk2("rfftn", lambda a, s=None, axes=None, norm="backward": jnp.fft.rfftn(a, s=s, axes=axes, norm=norm))
irfftn = _mk2("irfftn", lambda a, s=None, axes=None, norm="backward": jnp.fft.irfftn(a, s=s, axes=axes, norm=norm))


def fftshift(x, axes=None, name=None):
    t = as_tensor(x)
    return eager_call(
        "fft.fftshift",
        lambda a, axes=None: jnp.fft.fftshift(a, axes=axes),
        [t], attrs={"axes": axes},
    )


def ifftshift(x, axes=None, name=None):
    t = as_tensor(x)
    return eager_call(
        "fft.ifftshift",
        lambda a, axes=None: jnp.fft.ifftshift(a, axes=axes),
        [t], attrs={"axes": axes},
    )


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor

    return Tensor(jnp.fft.fftfreq(int(n), d=float(d)), stop_gradient=True)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor

    return Tensor(jnp.fft.rfftfreq(int(n), d=float(d)), stop_gradient=True)


__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "fftn", "ifftn", "rfftn", "irfftn",
    "fftshift", "ifftshift", "fftfreq", "rfftfreq",
]
