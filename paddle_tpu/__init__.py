"""paddle_tpu — a TPU-native deep-learning framework.

Brand-new framework with the capability surface of the reference
(PaddlePaddle ~2.2/2.3-dev snapshot at /root/reference, see SURVEY.md),
re-designed TPU-first: eager tensors + tape autograd over JAX/XLA, a jitted
program path, Fleet-style hybrid parallelism compiled to GSPMD/shard_map over
a `jax.sharding.Mesh`, and native C++ runtime components where the reference
is native.

Top-level namespace mirrors `import paddle`.
"""
from __future__ import annotations

# Paddle semantics: int64 indices/labels, explicit float management. JAX's
# x64-off mode silently truncates to int32, so enable it; every float path in
# this package passes dtypes explicitly (default float32 / bf16 on MXU).
import jax as _jax

_jax.config.update("jax_enable_x64", True)
# Matmul/conv precision is left at JAX's default. The reference's own fp32
# default is TF32 tensor cores on Ampere (cuDNN/cuBLAS allow_tf32=true),
# which corresponds to the MXU's default bf16-pass mode — while forcing
# "highest" makes every fp32 conv a multi-pass emulation that the TPU
# compiler autotunes pathologically slowly (minutes-long compiles for
# conv grads) and that runs ~3-6x slower. fp64 stays exact; use
# `with jax.default_matmul_precision("highest")` for reference-exact fp32.

# Core types -----------------------------------------------------------------
from .core.dtype import (  # noqa: F401
    bool_ as bool,  # type: ignore[misc]
    uint8, int8, int16, int32, int64,
    float16, bfloat16, float32, float64, complex64, complex128,
    set_default_dtype, get_default_dtype,
)
from .core.tensor import Tensor, Parameter, to_tensor  # noqa: F401
from .core.place import (  # noqa: F401
    CPUPlace, TPUPlace, CUDAPlace, Place,
    is_compiled_with_cuda, is_compiled_with_tpu,
)
from .core.engine import no_grad, enable_grad, set_grad_enabled, grad_enabled  # noqa: F401
from .core.random import seed, get_rng_state, set_rng_state, program_rng  # noqa: F401

# Ops (also monkey-patches Tensor methods) -----------------------------------
from . import ops as _ops  # noqa: F401
from .ops.creation import (  # noqa: F401
    zeros, ones, full, empty, zeros_like, ones_like, full_like, empty_like,
    arange, linspace, logspace, eye, diag, diagflat, tril, triu, meshgrid,
    assign, clone, numel, rand, randn, randint, randint_like, randperm,
    uniform, normal, gaussian, standard_normal, bernoulli, multinomial,
    shard_index,
)
from .ops.math import (  # noqa: F401
    add, subtract, multiply, divide, floor_divide, remainder, mod, pow,
    maximum, minimum, fmax, fmin, atan2, exp, expm1, log, log2, log10, log1p,
    sqrt, rsqrt, abs, sign, floor, ceil, round, trunc, frac, sin, cos, tan,
    asin, acos, atan, sinh, cosh, tanh, asinh, acosh, atanh, erf, erfinv,
    reciprocal, square, digamma, lgamma, sigmoid, clip, lerp, nan_to_num,
    stanh, isnan, isinf, isfinite, equal, not_equal, greater_than,
    greater_equal, less_than, less_equal, logical_and, logical_or,
    logical_not, logical_xor, bitwise_and, bitwise_or, bitwise_xor,
    bitwise_not, equal_all, allclose, isclose, sum, mean, max, min, prod,
    amax, amin, all, any, std, var, median, quantile, nanmean, nansum,
    logsumexp, argmax, argmin, cumsum, cumprod, cummax, cummin, logcumsumexp,
    matmul, mm, dot, inner, outer, addmm, bmm, kron, trace, diagonal, mv,
    dist, cast, scale, increment, neg, heaviside, hypot, copysign, nextafter,
    gcd, lcm, ldexp,
)
from .ops.manipulation import (  # noqa: F401
    reshape, reshape_, transpose, t, concat, stack, split, chunk, unbind,
    unstack, squeeze, unsqueeze, flatten, expand, expand_as, broadcast_to,
    broadcast_shape, broadcast_tensors, tile, repeat_interleave, flip, roll,
    rot90, gather, gather_nd, take_along_axis, put_along_axis, scatter,
    scatter_nd, scatter_nd_add, index_select, index_sample, index_add,
    masked_select, masked_fill, where, nonzero, slice, strided_slice, crop,
    topk, sort, argsort, searchsorted, unique, unique_consecutive, bincount,
    histogram, atleast_1d, atleast_2d, atleast_3d, as_real, as_complex, real,
    imag, conj, moveaxis, swapaxes,
)

from .ops import generated as _generated  # noqa: F401
from .ops import inplace as _inplace  # noqa: F401 (attaches Tensor methods)
from .ops import control_flow as _control_flow  # noqa: F401
from .ops.extra import (  # noqa: F401
    einsum, segment_sum, segment_mean, segment_max, segment_min, histogramdd,
)

# generated ops join the top-level namespace without clobbering hand-written
for _n, _fn in _generated.GENERATED.items():
    if _n not in globals():
        globals()[_n] = _fn
del _n, _fn

from .ops.misc import (  # noqa: F401
    is_tensor, is_floating_point, is_integer, is_complex, is_empty, rank,
    shape, tolist, reverse, multiplex, mode, poisson, set_printoptions,
    create_parameter, disable_signal_handler, is_compiled_with_cinn,
    is_compiled_with_rocm, is_compiled_with_xpu, is_compiled_with_npu,
    is_compiled_with_mlu, is_compiled_with_ipu, get_cuda_rng_state,
    set_cuda_rng_state,
)
from .linalg import (  # noqa: F401
    cholesky, cholesky_solve, cond, cov, eig, eigvals, eigvalsh, lstsq, lu,
    multi_dot, qr, triangular_solve, norm, inverse,
)
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import linalg  # noqa: F401
from . import autograd  # noqa: F401
from .autograd import grad  # noqa: F401
from . import device  # noqa: F401
from .device import set_device, get_device  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import metric  # noqa: F401
from . import vision  # noqa: F401
from . import jit  # noqa: F401
from . import static  # noqa: F401
from .framework.io import save, load  # noqa: F401
from .framework.flags import set_flags, get_flags  # noqa: F401
from . import distributed  # noqa: F401
from . import fault  # noqa: F401
from . import incubate  # noqa: F401
from . import inference  # noqa: F401
from . import text  # noqa: F401
from . import sparse  # noqa: F401
from . import quantization  # noqa: F401
from . import utils  # noqa: F401
from . import profiler  # noqa: F401
from .hapi.model import Model, summary  # noqa: F401
from .hapi.flops import flops  # noqa: F401
from . import onnx  # noqa: F401
from . import hub  # noqa: F401
from . import reader  # noqa: F401  (v1 reader decorators)
from . import dataset  # noqa: F401  (v1 generator datasets)
from . import tensor  # noqa: F401  (paddle.tensor namespace)
from . import cost_model  # noqa: F401
from . import callbacks  # noqa: F401
from .batch import batch  # noqa: F401
from .distributed.parallel import DataParallel  # noqa: F401
from .nn import ParamAttr  # noqa: F401
from .core.place import (  # noqa: F401
    CUDAPinnedPlace, IPUPlace, MLUPlace, NPUPlace, XPUPlace, CustomPlace,
)
from .core.engine import grad_enabled as is_grad_enabled  # noqa: F401
from .ops.math import floor_mod  # noqa: F401
from .ops.inplace import INPLACE_OPS as _INPLACE_OPS

# v1 top-level in-place names (paddle.tanh_ etc.)
for _n in ("scatter_", "squeeze_", "tanh_", "unsqueeze_", "relu_", "clip_",
           "exp_", "sqrt_", "subtract_", "add_"):
    if _n in _INPLACE_OPS:
        globals()[_n] = _INPLACE_OPS[_n]
del _n

# paddle.dtype — the dtype TYPE for isinstance checks (all framework dtypes,
# including the ml_dtypes bfloat16, are numpy dtype instances)
import numpy as _np  # noqa: E402

dtype = _np.dtype


def get_cudnn_version():
    """No cuDNN in a TPU-native build (the reference returns a version int
    on CUDA installs; None means 'not compiled with cuDNN' there too)."""
    return None
from . import distribution  # noqa: F401

from .io import DataLoader  # noqa: F401
from .nn.layer.common import ParameterList  # noqa: F401

disable_static = lambda *a, **k: None  # eager is the default (reference: paddle.disable_static)
enable_static = lambda *a, **k: None
in_dynamic_mode = lambda: True

# Warm executable starts: the lazy-flush signatures (and per-op jit keys) are
# stable across processes, so XLA's persistent compilation cache turns the
# first step of a rerun into a disk hit instead of a compile. Off via
# FLAGS_xla_persistent_cache=0 (see framework/flags.py).
from .core.compat import enable_persistent_compilation_cache as _enable_pcc  # noqa: E402

_enable_pcc()

__version__ = "0.1.0"
