"""Weight initializers.

Parity: reference ``python/paddle/nn/initializer/`` + fluid initializers
(``python/paddle/fluid/initializer.py``). Functional: each initializer is a
callable returning a jax array for a given shape/dtype.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core import random as random_state


def _fan(shape):
    shape = tuple(shape)
    if len(shape) < 2:
        fan_in = fan_out = int(shape[0]) if shape else 1
    else:
        receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
        fan_in = shape[1] * receptive if len(shape) > 2 else shape[0]
        fan_out = shape[0] * receptive if len(shape) > 2 else shape[1]
    return fan_in, fan_out


class Initializer:
    def __call__(self, shape, dtype=None):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=None):
        return jnp.full(tuple(shape), self.value, dtype=dtypes.convert_dtype(dtype) or dtypes.get_default_dtype())


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=None):
        key = random_state.next_key()
        dt = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        return jax.random.uniform(key, tuple(shape), dtype=jnp.float32, minval=self.low, maxval=self.high).astype(dt)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        key = random_state.next_key()
        dt = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        return (jax.random.normal(key, tuple(shape), dtype=jnp.float32) * self.std + self.mean).astype(dt)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        key = random_state.next_key()
        dt = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        return (
            jax.random.truncated_normal(key, -2.0, 2.0, tuple(shape), dtype=jnp.float32) * self.std + self.mean
        ).astype(dt)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None):
        fi, fo = _fan(shape)
        fi = self._fan_in or fi
        fo = self._fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return Uniform(-limit, limit)(shape, dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None):
        fi, fo = _fan(shape)
        fi = self._fan_in or fi
        fo = self._fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return Normal(0.0, std)(shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="leaky_relu"):
        self._fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype=None):
        fi, _ = _fan(shape)
        fi = self._fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2))
        limit = gain * math.sqrt(3.0 / fi)
        return Uniform(-limit, limit)(shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="leaky_relu"):
        self._fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype=None):
        fi, _ = _fan(shape)
        fi = self._fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2))
        return Normal(0.0, gain / math.sqrt(fi))(shape, dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=None):
        from ..core.tensor import Tensor

        v = self.value
        if isinstance(v, Tensor):
            v = v.numpy()
        arr = jnp.asarray(np.asarray(v), dtype=dtypes.convert_dtype(dtype) or None)
        assert tuple(arr.shape) == tuple(shape), f"Assign shape {arr.shape} != {shape}"
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=None):
        key = random_state.next_key()
        dt = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        return (jax.nn.initializers.orthogonal(scale=self.gain)(key, tuple(shape), jnp.float32)).astype(dt)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype=None):
        dt = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        arr = np.zeros(shape, dtype=np.float32)
        o, i = shape[0], shape[1]
        mins = min(o // self.groups, i)
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for d in range(mins):
                arr[(g * (o // self.groups) + d, d) + tuple(centers)] = 1.0
        return jnp.asarray(arr, dtype=dt)


# default global initializer (reference: fluid.initializer._global_weight_initializer)
_default_weight_init = XavierUniform()
_default_bias_init = Constant(0.0)


def set_global_initializer(weight_init, bias_init=None):
    global _default_weight_init, _default_bias_init
    _default_weight_init = weight_init
    if bias_init is not None:
        _default_bias_init = bias_init


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0, "conv3d": 1.0,
        "tanh": 5.0 / 3, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    return gains[nonlinearity]
