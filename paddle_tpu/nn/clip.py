"""Gradient clipping (reference python/paddle/fluid/clip.py).

ClipGradByGlobalNorm matches the reference semantics: one global norm across
all grads, scale applied uniformly. Each clip is routed through eager_call as
a single variadic op, so in lazy mode it fuses into the same flushed XLA
computation as backward + optimizer update, and under per-op dispatch it is
one jitted executable.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import eager_call
from ..core.tensor import Tensor


def _as_list(out):
    return out if isinstance(out, (list, tuple)) else [out]


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            clipped = eager_call(
                "clip_by_value",
                lambda a, lo=0.0, hi=0.0: jnp.clip(a, lo, hi),
                [g],
                attrs={"lo": self.min, "hi": self.max},
                differentiable=False,
            )
            out.append((p, clipped))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue

            def fn(a, clip_norm=self.clip_norm):
                af = a.astype(jnp.float32)
                norm = jnp.sqrt(jnp.sum(jnp.square(af)))
                scale = jnp.minimum(clip_norm / jnp.maximum(norm, 1e-12), 1.0)
                return (af * scale).astype(a.dtype)

            out.append((p, eager_call("clip_by_norm", fn, [g], differentiable=False)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        idx = [
            i
            for i, (p, g) in enumerate(params_grads)
            if g is not None and getattr(p, "need_clip", True)
        ]
        if not idx:
            return params_grads

        def fn(*gs, clip_norm=self.clip_norm):
            sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in gs)
            gn = jnp.sqrt(sq)
            scale = clip_norm / jnp.maximum(gn, clip_norm)
            return tuple((g.astype(jnp.float32) * scale).astype(g.dtype) for g in gs)

        clipped = _as_list(
            eager_call(
                "global_norm_clip",
                fn,
                [params_grads[i][1] for i in idx],
                differentiable=False,
            )
        )
        out = list(params_grads)
        for j, i in enumerate(idx):
            out[i] = (params_grads[i][0], clipped[j])
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    params = [p for p in parameters if p.grad is not None]
    if not params:
        return Tensor(jnp.zeros(()))

    def fn(*gs, max_norm=float(max_norm), norm_type=float(norm_type)):
        if norm_type == float("inf"):
            total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in gs]))
        else:
            total = jnp.power(
                sum(
                    jnp.sum(jnp.power(jnp.abs(g.astype(jnp.float32)), norm_type))
                    for g in gs
                ),
                1.0 / norm_type,
            )
        scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
        return (total,) + tuple(
            (g.astype(jnp.float32) * scale).astype(g.dtype) for g in gs
        )

    outs = _as_list(
        eager_call("clip_grad_norm_", fn, [p.grad for p in params], differentiable=False)
    )
    for p, t in zip(params, outs[1:]):
        p.grad._set_data(t._data)
    return outs[0]
