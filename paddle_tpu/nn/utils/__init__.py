"""paddle.nn.utils parity (weight_norm, spectral_norm wrappers, vector ops)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...core.lazy import concrete as _concrete

from ...core.tensor import Tensor


def parameters_to_vector(parameters, name=None):
    arrs = [p._data.reshape(-1) for p in parameters]
    return Tensor(jnp.concatenate(arrs))


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        p.set_value(np.asarray(vec._data[offset : offset + n]).reshape(p.shape))
        offset += n


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize weight = g * v/||v|| (reference nn/utils/weight_norm_hook.py)."""
    import jax

    w = getattr(layer, name)
    axes = tuple(i for i in range(w.ndim) if i != dim)
    norm = jnp.sqrt(jnp.sum(jnp.square(_concrete(w._data)), axis=axes, keepdims=True))
    g = layer.create_parameter(list(norm.shape), default_initializer=lambda s, d: norm)
    v = layer.create_parameter(list(w.shape), default_initializer=lambda s, d: w._data)
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    layer._parameters.pop(name, None)

    def hook(l, inputs):
        from ...core.dispatch import eager_call

        def fn(gv, vv):
            n = jnp.sqrt(jnp.sum(jnp.square(vv), axis=axes, keepdims=True))
            return gv * vv / jnp.maximum(n, 1e-12)

        new_w = eager_call("weight_norm", fn, [l._parameters[name + "_g"], l._parameters[name + "_v"]])
        object.__setattr__(l, name, new_w)

    layer.register_forward_pre_hook(hook)
    hook(layer, None)
    return layer


def remove_weight_norm(layer, name="weight"):
    g = layer._parameters.pop(name + "_g", None)
    v = layer._parameters.pop(name + "_v", None)
    if g is not None and v is not None:
        axes = tuple(i for i in range(v.ndim) if i != 0)
        n = jnp.sqrt(jnp.sum(jnp.square(_concrete(v._data)), axis=axes, keepdims=True))
        w = layer.create_parameter(list(v.shape), default_initializer=lambda s, d: g._data * v._data / n)
        layer.add_parameter(name, w)
        object.__setattr__(layer, name, w)
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12, dim=None):
    from .. import functional as F

    if dim is None:
        dim = 0

    def hook(l, inputs):
        w = l._parameters.get(name + "_orig", l._parameters.get(name))
        object.__setattr__(l, name, F.spectral_norm(w, dim, n_power_iterations, eps))

    if name in layer._parameters:
        layer.add_parameter(name + "_orig", layer._parameters.pop(name))
    layer.register_forward_pre_hook(hook)
    hook(layer, None)
    return layer
