"""Vision & misc functional ops.

Parity: reference ``python/paddle/nn/functional/vision.py`` (grid_sample,
affine_grid, pixel ops), ``input.py`` (one_hot/embedding), sequence ops
(``sequence_mask`` — paddle/fluid/layers/sequence_lod.py), temporal_shift
(``operators/temporal_shift_op.cu``), distance ops. All jnp builders through
eager_call (autograd/jit/AMP for free).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import as_tensor, eager_call


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """2D affine grid (N,2,3) -> (N,H,W,2). Reference vision.py affine_grid."""
    t = as_tensor(theta)
    if hasattr(out_shape, "numpy"):
        out_shape = [int(v) for v in out_shape.numpy()]
    N, C, H, W = [int(v) for v in out_shape]

    def fn(th, H=0, W=0, align=True):
        if align:
            xs = jnp.linspace(-1.0, 1.0, W)
            ys = jnp.linspace(-1.0, 1.0, H)
        else:
            xs = (jnp.arange(W) * 2 + 1) / W - 1
            ys = (jnp.arange(H) * 2 + 1) / H - 1
        gx, gy = jnp.meshgrid(xs, ys)  # (H, W)
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)  # (H, W, 3)
        return jnp.einsum("hwk,nck->nhwc", base.astype(th.dtype), th)

    return eager_call(
        "affine_grid", fn, [t], attrs={"H": H, "W": W, "align": bool(align_corners)}
    )


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True, name=None):
    """Bilinear/nearest sampling of x (N,C,H,W) at grid (N,Hg,Wg,2) in [-1,1].
    Reference vision.py grid_sample / grid_sampler_op.cu."""
    xt, gt = as_tensor(x), as_tensor(grid)

    def fn(feat, g, mode="bilinear", padding_mode="zeros", align=True):
        N, C, H, W = feat.shape
        gx, gy = g[..., 0], g[..., 1]
        if align:
            fx = (gx + 1) * (W - 1) / 2
            fy = (gy + 1) * (H - 1) / 2
        else:
            fx = ((gx + 1) * W - 1) / 2
            fy = ((gy + 1) * H - 1) / 2

        if padding_mode == "reflection":
            # reflect sample coordinates back into the image. Reference
            # grid_sampler reflects about [0, n-1] when align_corners=True
            # but about the pixel-edge extent [-0.5, n-0.5] when False.
            def refl(c, n):
                lo = 0.0 if align else -0.5
                hi = (n - 1.0) if align else (n - 0.5)
                span = max(hi - lo, 1e-3)
                c = jnp.abs(c - lo) % (2 * span)
                return lo + jnp.where(c > span, 2 * span - c, c)

            fx = refl(fx, W)
            fy = refl(fy, H)

        def gather(feat_n, yy, xx):
            # feat_n: (C,H,W); yy/xx int arrays (Hg,Wg)
            inb = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
            yc = jnp.clip(yy, 0, H - 1)
            xc = jnp.clip(xx, 0, W - 1)
            v = feat_n[:, yc, xc]  # (C,Hg,Wg)
            if padding_mode == "zeros":
                v = jnp.where(inb[None], v, 0.0)
            return v  # border/reflection: clamped

        def sample_n(feat_n, fx_n, fy_n):
            if mode == "nearest":
                return gather(feat_n, jnp.round(fy_n).astype(jnp.int32), jnp.round(fx_n).astype(jnp.int32))
            x0 = jnp.floor(fx_n)
            y0 = jnp.floor(fy_n)
            wx = fx_n - x0
            wy = fy_n - y0
            x0i, y0i = x0.astype(jnp.int32), y0.astype(jnp.int32)
            v00 = gather(feat_n, y0i, x0i)
            v01 = gather(feat_n, y0i, x0i + 1)
            v10 = gather(feat_n, y0i + 1, x0i)
            v11 = gather(feat_n, y0i + 1, x0i + 1)
            return (
                v00 * (1 - wx) * (1 - wy) + v01 * wx * (1 - wy)
                + v10 * (1 - wx) * wy + v11 * wx * wy
            )

        return jax.vmap(sample_n)(feat, fx, fy)

    return eager_call(
        "grid_sample", fn, [xt, gt],
        attrs={"mode": mode, "padding_mode": padding_mode, "align": bool(align_corners)},
    )


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    """mask[i, j] = j < lengths[i]. Reference sequence_lod.py sequence_mask."""
    lt = as_tensor(lengths)
    import numpy as np

    if maxlen is None:
        maxlen = int(np.asarray(lt._data).max())

    def fn(l, maxlen=0, dtype="int64"):
        return (jnp.arange(maxlen) < l[..., None]).astype(dtype)

    return eager_call(
        "sequence_mask", fn, [lt], attrs={"maxlen": int(maxlen), "dtype": dtype},
        differentiable=False,
    )


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None, data_format="NCHW"):
    """TSM shift (reference temporal_shift_op.cu)."""
    t = as_tensor(x)

    def fn(a, seg_num=1, shift_ratio=0.25):
        NT, C, H, W = a.shape
        N = NT // seg_num
        v = a.reshape(N, seg_num, C, H, W)
        c1 = int(C * shift_ratio)
        c2 = int(C * 2 * shift_ratio)
        back = jnp.concatenate([v[:, 1:, :c1], jnp.zeros_like(v[:, :1, :c1])], axis=1)
        fwd = jnp.concatenate([jnp.zeros_like(v[:, :1, c1:c2]), v[:, :-1, c1:c2]], axis=1)
        keep = v[:, :, c2:]
        return jnp.concatenate([back, fwd, keep], axis=2).reshape(NT, C, H, W)

    return eager_call(
        "temporal_shift", fn, [t],
        attrs={"seg_num": int(seg_num), "shift_ratio": float(shift_ratio)},
    )


def zeropad2d(x, padding, data_format="NCHW", name=None):
    t = as_tensor(x)
    if isinstance(padding, int):
        padding = [padding] * 4
    l, r, top, bot = [int(p) for p in padding]

    def fn(a, l=0, r=0, top=0, bot=0):
        return jnp.pad(a, ((0, 0), (0, 0), (top, bot), (l, r)))

    return eager_call("zeropad2d", fn, [t], attrs={"l": l, "r": r, "top": top, "bot": bot})


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    xt, yt = as_tensor(x), as_tensor(y)

    def fn(a, b, p=2.0, eps=1e-6, keepdim=False):
        d = a - b + eps
        return jnp.sum(jnp.abs(d) ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)

    return eager_call(
        "pairwise_distance", fn, [xt, yt],
        attrs={"p": float(p), "eps": float(epsilon), "keepdim": bool(keepdim)},
    )


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """Reference loss.py npair_loss."""
    a, pos, lab = as_tensor(anchor), as_tensor(positive), as_tensor(labels)

    def fn(an, po, lb, l2_reg=0.002):
        B = an.shape[0]
        lb = lb.reshape(-1)
        same = (lb[:, None] == lb[None, :]).astype(an.dtype)
        tgt = same / jnp.maximum(same.sum(-1, keepdims=True), 1.0)
        logits = an @ po.T
        logp = jax.nn.log_softmax(logits, axis=-1)
        xent = -(tgt * logp).sum(-1).mean()
        reg = (jnp.sum(an * an) + jnp.sum(po * po)) / B * l2_reg * 0.25
        return xent + reg

    return eager_call("npair_loss", fn, [a, pos, lab], attrs={"l2_reg": float(l2_reg)})


def dice_loss(input, label, epsilon=1e-5, name=None):
    """Reference loss.py dice_loss."""
    x, y = as_tensor(input), as_tensor(label)

    def fn(p, t, eps=1e-5):
        t1 = jax.nn.one_hot(t.squeeze(-1), p.shape[-1], dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = jnp.sum(p * t1, axis=reduce_dims)
        union = jnp.sum(p, axis=reduce_dims) + jnp.sum(t1, axis=reduce_dims)
        return jnp.mean(1 - (2 * inter + eps) / (union + eps))

    return eager_call("dice_loss", fn, [x, y], attrs={"eps": float(epsilon)})


def gather_tree(ids, parents):
    """Beam-search backtrace (reference gather_tree_op)."""
    it, pt = as_tensor(ids), as_tensor(parents)

    def fn(idv, par):
        T, B, W = idv.shape

        def step(carry, t):
            beams = carry  # (B, W) beam index being traced
            out = jnp.take_along_axis(idv[t], beams, axis=1)
            nxt = jnp.take_along_axis(par[t], beams, axis=1)
            return nxt, out

        init = jnp.tile(jnp.arange(W)[None], (B, 1))
        _, outs = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return outs[::-1]

    return eager_call("gather_tree", fn, [it, pt], differentiable=False)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0, data_format="NCHW", output_size=None, name=None):
    """Reference unpool_op: scatter pooled values back to indices.
    Default output size = (H-1)*stride + kernel - 2*padding (reference
    formula)."""
    xt, it = as_tensor(x), as_tensor(indices)
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size, kernel_size)
    stride = stride or kernel_size
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = (padding, padding)

    def fn(v, idx, kh=2, kw=2, sh=2, sw=2, ph=0, pw=0, oh=0, ow=0):
        N, C, H, W = v.shape
        OH = oh or (H - 1) * sh + kh - 2 * ph
        OW = ow or (W - 1) * sw + kw - 2 * pw
        flat = jnp.zeros((N, C, OH * OW), v.dtype)
        out = flat.at[
            jnp.arange(N)[:, None, None], jnp.arange(C)[None, :, None],
            idx.reshape(N, C, -1),
        ].set(v.reshape(N, C, -1))
        return out.reshape(N, C, OH, OW)

    oh, ow = (0, 0)
    if output_size is not None:
        oh, ow = int(output_size[-2]), int(output_size[-1])
    return eager_call(
        "max_unpool2d", fn, [xt, it],
        attrs={"kh": kernel_size[0], "kw": kernel_size[1],
               "sh": stride[0], "sw": stride[1],
               "ph": padding[0], "pw": padding[1], "oh": oh, "ow": ow},
    )


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0, data_format="NCL", output_size=None, name=None):
    xt = as_tensor(x)
    x4 = xt.unsqueeze(-2)
    i4 = as_tensor(indices).unsqueeze(-2)
    ks = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    st = stride if stride is not None else ks
    st = st if isinstance(st, int) else st[0]
    pd = padding if isinstance(padding, int) else padding[0]
    osz = None if output_size is None else [1, int(output_size[-1])]
    out = max_unpool2d(x4, i4, (1, ks), (1, st), padding=(0, pd), output_size=osz)
    return out.squeeze(-2)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0, data_format="NCDHW", output_size=None, name=None):
    xt, it = as_tensor(x), as_tensor(indices)
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size,) * 3
    stride = stride or kernel_size
    if isinstance(stride, int):
        stride = (stride,) * 3

    if isinstance(padding, int):
        padding = (padding,) * 3

    def fn(v, idx, kd=2, kh=2, kw=2, sd=2, sh=2, sw=2, pd=0, ph=0, pw=0, od=0, oh=0, ow=0):
        N, C, D, H, W = v.shape
        OD = od or (D - 1) * sd + kd - 2 * pd
        OH = oh or (H - 1) * sh + kh - 2 * ph
        OW = ow or (W - 1) * sw + kw - 2 * pw
        flat = jnp.zeros((N, C, OD * OH * OW), v.dtype)
        out = flat.at[
            jnp.arange(N)[:, None, None], jnp.arange(C)[None, :, None],
            idx.reshape(N, C, -1),
        ].set(v.reshape(N, C, -1))
        return out.reshape(N, C, OD, OH, OW)

    od = oh = ow = 0
    if output_size is not None:
        od, oh, ow = [int(v) for v in output_size[-3:]]
    return eager_call(
        "max_unpool3d", fn, [xt, it],
        attrs={"kd": kernel_size[0], "kh": kernel_size[1], "kw": kernel_size[2],
               "sd": stride[0], "sh": stride[1], "sw": stride[2],
               "pd": padding[0], "ph": padding[1], "pw": padding[2],
               "od": od, "oh": oh, "ow": ow},
    )


__all__ = [
    "affine_grid", "grid_sample", "sequence_mask", "temporal_shift",
    "zeropad2d", "pairwise_distance", "npair_loss", "dice_loss",
    "gather_tree", "max_unpool1d", "max_unpool2d", "max_unpool3d",
]
