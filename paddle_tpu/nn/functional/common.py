"""Common functionals: linear, dropout, embedding, one_hot, interpolate, etc.

Parity: reference ``python/paddle/nn/functional/common.py`` (linear at
:1472 → matmul_v2 + elementwise_add), ``input.py`` (one_hot/embedding →
lookup_table_v2), dropout kernels (``paddle/fluid/operators/dropout_op.*``).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core import random as random_state
from ...core.tensor import Tensor
from ...core.dispatch import as_tensor, eager_call
from ...ops.manipulation import pad as _pad_op


def linear(x, weight, bias=None, name=None):
    """y = x @ W (+ b). Reference functional/common.py:1472 — one MXU matmul."""
    x, weight = as_tensor(x), as_tensor(weight)
    if bias is not None:
        return eager_call("linear", lambda a, w, b: jnp.matmul(a, w) + b, [x, weight, as_tensor(bias)])
    return eager_call("linear", jnp.matmul, [x, weight])


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    x = as_tensor(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return eager_call("dropout_scale", lambda a, p: a * (1 - p), [x], {"p": p})
        return x
    key = random_state.next_key()
    shape = list(x.shape)
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)
        shape = [s if i in axes else 1 for i, s in enumerate(shape)]
    mask = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
    mask_t = Tensor(mask)

    def fn(a, m, p, mode):
        m = m.astype(a.dtype)
        if mode == "upscale_in_train":
            return a * m / (1.0 - p)
        return a * m

    return eager_call("dropout", fn, [x, mask_t], {"p": p, "mode": mode})


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = as_tensor(x)
    if not training or p == 0.0:
        return x
    key = random_state.next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    mask = jax.random.bernoulli(key, 1.0 - p, tuple(x.shape))
    mask_t = Tensor(mask)

    def fn(a, m, p, alpha_p):
        q = 1.0 - p
        a_coef = (q + alpha_p**2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        m = m.astype(a.dtype)
        return a_coef * (a * m + alpha_p * (1 - m)) + b_coef

    return eager_call("alpha_dropout", fn, [x, mask_t], {"p": p, "alpha_p": alpha_p})


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    return _pad_op(x, pad, mode=mode, value=value, data_format=data_format)


def one_hot(x, num_classes, name=None):
    x = as_tensor(x)
    return eager_call(
        "one_hot",
        lambda a, n: jax.nn.one_hot(a, n, dtype=jnp.float32),
        [x],
        {"n": int(num_classes)},
        differentiable=False,
    )


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Reference: lookup_table_v2 (paddle/fluid/operators/lookup_table_v2_op.*).

    On TPU this is a gather; padding_idx rows produce zero vectors and get no
    gradient (handled by zeroing the row before lookup).
    """
    x, weight = as_tensor(x), as_tensor(weight)

    def fn(ids, w, padding_idx):
        if padding_idx is not None:
            w = w.at[padding_idx].set(0.0)
        return jnp.take(w, ids, axis=0)

    return eager_call(
        "embedding", fn, [x, weight],
        {"padding_idx": None if padding_idx is None else int(padding_idx)},
    )


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = as_tensor(label)

    def fn(l, epsilon):
        k = l.shape[-1]
        return (1 - epsilon) * l + epsilon / k

    if prior_dist is not None:
        return eager_call(
            "label_smooth_prior",
            lambda l, p, epsilon: (1 - epsilon) * l + epsilon * p,
            [label, as_tensor(prior_dist)],
            {"epsilon": epsilon},
        )
    return eager_call("label_smooth", fn, [label], {"epsilon": epsilon})


def interpolate(
    x,
    size=None,
    scale_factor=None,
    mode="nearest",
    align_corners=False,
    align_mode=0,
    data_format="NCHW",
    name=None,
):
    x = as_tensor(x)
    if isinstance(size, Tensor):
        size = size.tolist()
    nd = x.ndim - 2
    ch_last = data_format[-1] == "C"
    spatial = x.shape[2:] if not ch_last else x.shape[1:-1]
    if size is None:
        if scale_factor is None:
            raise ValueError("either size or scale_factor required")
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * nd
        size = [int(s * f) for s, f in zip(spatial, sf)]
    size = [int(s) for s in (size if isinstance(size, (list, tuple)) else [size] * nd)]

    jmode = {
        "nearest": "nearest",
        "bilinear": "linear",
        "trilinear": "linear",
        "linear": "linear",
        "bicubic": "cubic",
        "area": "linear",
    }[mode]

    def fn(a, size, ch_last, jmode, align_corners):
        if ch_last:
            out_shape = (a.shape[0],) + tuple(size) + (a.shape[-1],)
            axes = tuple(range(1, a.ndim - 1))
        else:
            out_shape = a.shape[:2] + tuple(size)
            axes = tuple(range(2, a.ndim))
        if jmode == "nearest":
            # paddle nearest uses floor indexing (align_corners=False)
            idx = []
            for ax, s_out in zip(axes, size):
                s_in = a.shape[ax]
                ratio = s_in / s_out
                ix = jnp.floor(jnp.arange(s_out) * ratio).astype(jnp.int32)
                idx.append((ax, jnp.clip(ix, 0, s_in - 1)))
            out = a
            for ax, ix in idx:
                out = jnp.take(out, ix, axis=ax)
            return out
        method = {"linear": "bilinear" if len(axes) == 2 else "linear", "cubic": "bicubic"}[jmode]
        if len(axes) == 3:
            method = "trilinear"
        return jax.image.resize(a, out_shape, method=method)

    return eager_call(
        "interpolate", fn, [x],
        {"size": tuple(size), "ch_last": ch_last, "jmode": jmode, "align_corners": align_corners},
    )


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference paddle/fluid/operators/unfold_op.cc)."""
    x = as_tensor(x)

    def _pair(v):
        return tuple(v) if isinstance(v, (list, tuple)) else (v, v)

    k = _pair(kernel_sizes)
    s = _pair(strides)
    p = _pair(paddings) if not (isinstance(paddings, (list, tuple)) and len(paddings) == 4) else tuple(paddings)
    d = _pair(dilations)

    def fn(a, k, s, p, d):
        n, c, h, w = a.shape
        if len(p) == 2:
            pads = ((p[0], p[0]), (p[1], p[1]))
        else:
            pads = ((p[0], p[2]), (p[1], p[3]))
        a = jnp.pad(a, ((0, 0), (0, 0), pads[0], pads[1]))
        patches = jax.lax.conv_general_dilated_patches(
            a, filter_shape=k, window_strides=s, padding="VALID", rhs_dilation=d,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        # patches: (N, C*kh*kw, oh, ow) → (N, C*kh*kw, L)
        return patches.reshape(n, patches.shape[1], -1)

    return eager_call("unfold", fn, [x], {"k": k, "s": s, "p": p, "d": d})


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    x = as_tensor(x)

    def _pair(v):
        return tuple(v) if isinstance(v, (list, tuple)) else (v, v)

    out_hw = _pair(output_sizes)
    k = _pair(kernel_sizes)
    s = _pair(strides)
    p = _pair(paddings)
    d = _pair(dilations)

    def fn(a, out_hw, k, s, p, d):
        n, ckk, l = a.shape
        c = ckk // (k[0] * k[1])
        oh = (out_hw[0] + 2 * p[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
        ow = (out_hw[1] + 2 * p[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
        cols = a.reshape(n, c, k[0], k[1], oh, ow)
        H = out_hw[0] + 2 * p[0]
        W = out_hw[1] + 2 * p[1]
        out = jnp.zeros((n, c, H, W), a.dtype)
        for i in range(k[0]):
            for j in range(k[1]):
                hi = i * d[0]
                wj = j * d[1]
                out = out.at[:, :, hi : hi + oh * s[0] : s[0], wj : wj + ow * s[1] : s[1]].add(
                    cols[:, :, i, j]
                )
        return out[:, :, p[0] : H - p[0], p[1] : W - p[1]]

    return eager_call("fold", fn, [x], {"out_hw": out_hw, "k": k, "s": s, "p": p, "d": d})


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    return eager_call(
        "cosine_similarity",
        lambda a, b, axis, eps: jnp.sum(a * b, axis=axis)
        / jnp.maximum(jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis), eps),
        [as_tensor(x1), as_tensor(x2)],
        {"axis": axis, "eps": eps},
    )


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    def fn(a, r, data_format):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c // (r * r), r, r, h, w)
            a = a.transpose(0, 1, 4, 2, 5, 3)
            return a.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, r, r, c // (r * r))
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h * r, w * r, c // (r * r))

    return eager_call("pixel_shuffle", fn, [as_tensor(x)], {"r": int(upscale_factor), "data_format": data_format})


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    def fn(a, r, data_format):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            a = a.transpose(0, 1, 3, 5, 2, 4)
            return a.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        a = a.reshape(n, h // r, r, w // r, r, c)
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h // r, w // r, c * r * r)

    return eager_call("pixel_unshuffle", fn, [as_tensor(x)], {"r": int(downscale_factor), "data_format": data_format})


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def fn(a, g, data_format):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            return a.reshape(n, g, c // g, h, w).transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
        n, h, w, c = a.shape
        return a.reshape(n, h, w, g, c // g).transpose(0, 1, 2, 4, 3).reshape(n, h, w, c)

    return eager_call("channel_shuffle", fn, [as_tensor(x)], {"g": int(groups), "data_format": data_format})


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return eager_call(
        "normalize",
        lambda a, p, axis, eps: a
        / jnp.maximum(jnp.linalg.norm(a, ord=p, axis=axis, keepdims=True), eps),
        [as_tensor(x)],
        {"p": p, "axis": axis, "eps": epsilon},
    )


def bilinear(x1, x2, weight, bias=None, name=None):
    x1, x2, weight = as_tensor(x1), as_tensor(x2), as_tensor(weight)

    def fn(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out

    args = [x1, x2, weight] + ([as_tensor(bias)] if bias is not None else [])

    def fn2(a, b, w, *rest):
        return fn(a, b, w, *rest)

    return eager_call("bilinear", fn2, args)


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample negative class centers (reference common.py class_center_sample).
    Host-side sampling like the reference's CPU path."""
    import numpy as np
    import jax.numpy as jnp
    from ...core.dispatch import as_tensor
    from ...core.tensor import Tensor
    from ...core import random as random_state

    lt = as_tensor(label)
    lab = np.asarray(lt._data).reshape(-1)
    pos = np.unique(lab)
    rest = np.setdiff1d(np.arange(num_classes), pos)
    import jax

    key = random_state.next_key()
    n_extra = max(0, min(num_samples, num_classes) - pos.size)
    if n_extra > 0 and rest.size:
        perm = np.asarray(jax.random.permutation(key, rest.size))[:n_extra]
        sampled = np.sort(np.concatenate([pos, rest[perm]]))
    else:
        sampled = pos
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(sampled.size)
    return (
        Tensor(jnp.asarray(remap[lab]), stop_gradient=True),
        Tensor(jnp.asarray(sampled), stop_gradient=True),
    )
