"""Activation functionals.

Parity: reference ``python/paddle/nn/functional/activation.py`` backed by
``paddle/fluid/operators/activation_op.*`` kernels — here jax.nn/XLA, fused
into surrounding matmuls by the compiler.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import as_tensor, eager_call


def _act(op_name, jfn):
    def op(x, name=None):
        return eager_call(op_name, jfn, [as_tensor(x)])

    op.__name__ = op_name
    return op


relu = _act("relu", jax.nn.relu)
relu6 = _act("relu6", jax.nn.relu6)
sigmoid = _act("sigmoid", jax.nn.sigmoid)
tanh = _act("tanh", jnp.tanh)
silu = _act("silu", jax.nn.silu)
swish = silu
mish = _act("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)))
tanhshrink = _act("tanhshrink", lambda a: a - jnp.tanh(a))
softsign = _act("softsign", jax.nn.soft_sign)
log_sigmoid = _act("log_sigmoid", jax.nn.log_sigmoid)


def gelu(x, approximate=False, name=None):
    return eager_call(
        "gelu", lambda a, approximate: jax.nn.gelu(a, approximate=approximate),
        [as_tensor(x)], {"approximate": approximate},
    )


def leaky_relu(x, negative_slope=0.01, name=None):
    return eager_call(
        "leaky_relu",
        lambda a, negative_slope: jax.nn.leaky_relu(a, negative_slope),
        [as_tensor(x)],
        {"negative_slope": negative_slope},
    )


def prelu(x, weight, data_format="NCHW", name=None):
    x, weight = as_tensor(x), as_tensor(weight)

    def fn(a, w, data_format):
        if w.size > 1:
            shape = [1] * a.ndim
            ch_axis = 1 if data_format[1] == "C" else a.ndim - 1
            shape[ch_axis] = w.size
            w = w.reshape(shape)
        return jnp.where(a > 0, a, w * a)

    return eager_call("prelu", fn, [x, weight], {"data_format": data_format})


def elu(x, alpha=1.0, name=None):
    return eager_call("elu", lambda a, alpha: jax.nn.elu(a, alpha), [as_tensor(x)], {"alpha": alpha})


def celu(x, alpha=1.0, name=None):
    return eager_call("celu", lambda a, alpha: jax.nn.celu(a, alpha), [as_tensor(x)], {"alpha": alpha})


def selu(
    x,
    scale=1.0507009873554804934193349852946,
    alpha=1.6732632423543772848170429916717,
    name=None,
):
    return eager_call(
        "selu",
        lambda a, scale, alpha: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)),
        [as_tensor(x)],
        {"scale": scale, "alpha": alpha},
    )


def hardshrink(x, threshold=0.5, name=None):
    return eager_call(
        "hardshrink",
        lambda a, threshold: jnp.where(jnp.abs(a) > threshold, a, 0.0).astype(a.dtype),
        [as_tensor(x)],
        {"threshold": threshold},
    )


def softshrink(x, threshold=0.5, name=None):
    return eager_call(
        "softshrink",
        lambda a, t: jnp.where(a > t, a - t, jnp.where(a < -t, a + t, 0.0)).astype(a.dtype),
        [as_tensor(x)],
        {"t": threshold},
    )


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return eager_call(
        "hardtanh", lambda a, mn, mx: jnp.clip(a, mn, mx), [as_tensor(x)], {"mn": min, "mx": max}
    )


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return eager_call(
        "hardsigmoid",
        lambda a, slope, offset: jnp.clip(slope * a + offset, 0.0, 1.0),
        [as_tensor(x)],
        {"slope": slope, "offset": offset},
    )


def hardswish(x, name=None):
    return eager_call("hardswish", lambda a: a * jnp.clip(a / 6.0 + 0.5, 0.0, 1.0), [as_tensor(x)])


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return eager_call(
        "softplus",
        lambda a, beta, threshold: jnp.where(
            beta * a > threshold, a, jax.nn.softplus(beta * a) / beta
        ),
        [as_tensor(x)],
        {"beta": beta, "threshold": threshold},
    )


def softmax(x, axis=-1, dtype=None, name=None):
    x = as_tensor(x)
    if dtype is not None:
        from ...ops.math import cast

        x = cast(x, dtype)
    return eager_call("softmax", lambda a, axis: jax.nn.softmax(a, axis=axis), [x], {"axis": int(axis)})


def log_softmax(x, axis=-1, dtype=None, name=None):
    x = as_tensor(x)
    if dtype is not None:
        from ...ops.math import cast

        x = cast(x, dtype)
    return eager_call(
        "log_softmax", lambda a, axis: jax.nn.log_softmax(a, axis=axis), [x], {"axis": int(axis)}
    )


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core import random as random_state
    from ...core.tensor import Tensor

    x = as_tensor(x)
    key = random_state.next_key()
    g = jax.random.gumbel(key, x._data.shape, dtype=x._data.dtype)
    gt = Tensor(g)

    def fn(a, gumbel, temperature, hard, axis):
        y = jax.nn.softmax((a + gumbel) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            hard_y = jnp.zeros_like(y)
            hard_y = jnp.put_along_axis(hard_y, idx, 1.0, axis=axis, inplace=False)
            y = jax.lax.stop_gradient(hard_y - y) + y
        return y

    return eager_call(
        "gumbel_softmax", fn, [x, gt], {"temperature": temperature, "hard": hard, "axis": axis}
    )


def maxout(x, groups, axis=1, name=None):
    def fn(a, groups, axis):
        shape = list(a.shape)
        c = shape[axis]
        shape[axis : axis + 1] = [c // groups, groups]
        return jnp.max(a.reshape(shape), axis=axis + 1)

    return eager_call("maxout", fn, [as_tensor(x)], {"groups": groups, "axis": axis})


def glu(x, axis=-1, name=None):
    return eager_call("glu", lambda a, axis: jax.nn.glu(a, axis=axis), [as_tensor(x)], {"axis": axis})


def thresholded_relu(x, threshold=1.0, name=None):
    return eager_call(
        "thresholded_relu",
        lambda a, threshold: jnp.where(a > threshold, a, 0.0).astype(a.dtype),
        [as_tensor(x)],
        {"threshold": threshold},
    )


def rrelu(x, lower=0.125, upper=0.3333333333333333, training=False, name=None):
    from ...core import random as random_state
    from ...core.tensor import Tensor

    x = as_tensor(x)
    if training:
        key = random_state.next_key()
        slope = jax.random.uniform(key, x._data.shape, minval=lower, maxval=upper, dtype=jnp.float32).astype(x._data.dtype)
    else:
        slope = jnp.asarray((lower + upper) / 2.0, dtype=x._data.dtype)
        slope = jnp.broadcast_to(slope, x._data.shape)
    st = Tensor(slope)
    return eager_call("rrelu", lambda a, s: jnp.where(a >= 0, a, s * a), [x, st])


def relu_(x, name=None):
    """In-place relu (reference activation.py relu_)."""
    from ...core.engine import grad_enabled

    t = x
    if not t.stop_gradient and grad_enabled():
        raise RuntimeError("relu_(): in-place on a tensor that requires grad")
    out = relu(t)
    t._set_data(out._data)
    return t


def elu_(x, alpha=1.0, name=None):
    from ...core.engine import grad_enabled

    if not x.stop_gradient and grad_enabled():
        raise RuntimeError("elu_(): in-place on a tensor that requires grad")
    x._set_data(elu(x, alpha)._data)
    return x


def softmax_(x, axis=-1, dtype=None, name=None):
    from ...core.engine import grad_enabled

    if not x.stop_gradient and grad_enabled():
        raise RuntimeError("softmax_(): in-place on a tensor that requires grad")
    x._set_data(softmax(x, axis)._data)
    return x
