"""Normalization functionals.

Parity: reference ``python/paddle/nn/functional/norm.py`` backed by
``paddle/fluid/operators/batch_norm_op.*``, ``layer_norm_op.*``,
``group_norm_op.*`` (cuDNN); here plain jnp — XLA fuses the reductions.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...core.dispatch import as_tensor, eager_call


def batch_norm(
    x,
    running_mean,
    running_var,
    weight=None,
    bias=None,
    training=False,
    momentum=0.9,
    epsilon=1e-05,
    data_format="NCHW",
    use_global_stats=None,
    name=None,
):
    """Training mode computes batch stats and updates running stats in place
    (reference: batch_norm op's MeanOut/VarianceOut aliasing)."""
    x = as_tensor(x)
    rm, rv = as_tensor(running_mean), as_tensor(running_var)
    ch_axis = 1 if (data_format.startswith("NC") or data_format == "NCHW") else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    use_batch_stats = training and not (use_global_stats or False)

    if use_batch_stats:
        # compute batch stats eagerly (needed for the running-stat update)
        mean = eager_call("bn_mean", lambda a, axes: jnp.mean(a, axis=axes), [x], {"axes": axes})
        var = eager_call(
            "bn_var", lambda a, axes: jnp.var(a, axis=axes), [x], {"axes": axes}
        )
        # update running stats (no grad; in-place buffer update)
        n = x.size // x.shape[ch_axis]
        unbiased = var._data * (n / max(n - 1, 1))
        rm._set_data(rm._data * momentum + mean._data * (1 - momentum))
        rv._set_data(rv._data * momentum + unbiased * (1 - momentum))
        stats_m, stats_v = mean, var
    else:
        stats_m, stats_v = rm, rv

    inputs = [x, stats_m, stats_v]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        inputs.append(as_tensor(weight))
    if has_b:
        inputs.append(as_tensor(bias))

    def fn(a, m, v, *wb, epsilon=1e-5, ch_axis=1, has_w=False, has_b=False):
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        m = m.reshape(shape)
        v = v.reshape(shape)
        out = (a - m) / jnp.sqrt(v + epsilon)
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        return out

    return eager_call(
        "batch_norm", fn, inputs,
        {"epsilon": epsilon, "ch_axis": ch_axis, "has_w": has_w, "has_b": has_b},
    )


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    x = as_tensor(x)
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(list(normalized_shape))
    inputs = [x]
    has_w, has_b = weight is not None, bias is not None
    if has_w:
        inputs.append(as_tensor(weight))
    if has_b:
        inputs.append(as_tensor(bias))

    def fn(a, *wb, n_axes=1, epsilon=1e-5, has_w=False, has_b=False):
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) / jnp.sqrt(v + epsilon)
        i = 0
        if has_w:
            out = out * wb[i]
            i += 1
        if has_b:
            out = out + wb[i]
        return out

    return eager_call(
        "layer_norm", fn, inputs,
        {"n_axes": n_axes, "epsilon": epsilon, "has_w": has_w, "has_b": has_b},
    )


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None, data_format="NCHW", name=None):
    x = as_tensor(x)
    ch_last = data_format[-1] == "C"
    inputs = [x]
    has_w, has_b = weight is not None, bias is not None
    if has_w:
        inputs.append(as_tensor(weight))
    if has_b:
        inputs.append(as_tensor(bias))

    def fn(a, *wb, g=1, epsilon=1e-5, ch_last=False, has_w=False, has_b=False):
        if ch_last:
            a_t = jnp.moveaxis(a, -1, 1)
        else:
            a_t = a
        n, c = a_t.shape[:2]
        grouped = a_t.reshape((n, g, c // g) + a_t.shape[2:])
        axes = tuple(range(2, grouped.ndim))
        m = jnp.mean(grouped, axis=axes, keepdims=True)
        v = jnp.var(grouped, axis=axes, keepdims=True)
        out = ((grouped - m) / jnp.sqrt(v + epsilon)).reshape(a_t.shape)
        shape = (1, c) + (1,) * (a_t.ndim - 2)
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        if ch_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    return eager_call(
        "group_norm", fn, inputs,
        {"g": int(num_groups), "epsilon": epsilon, "ch_last": ch_last, "has_w": has_w, "has_b": has_b},
    )


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None, use_input_stats=True, momentum=0.9, eps=1e-05, data_format="NCHW", name=None):
    x = as_tensor(x)
    inputs = [x]
    has_w, has_b = weight is not None, bias is not None
    if has_w:
        inputs.append(as_tensor(weight))
    if has_b:
        inputs.append(as_tensor(bias))

    def fn(a, *wb, eps=1e-5, has_w=False, has_b=False):
        axes = tuple(range(2, a.ndim))
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) / jnp.sqrt(v + eps)
        shape = (1, a.shape[1]) + (1,) * (a.ndim - 2)
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        return out

    return eager_call("instance_norm", fn, inputs, {"eps": eps, "has_w": has_w, "has_b": has_b})


def local_response_norm(x, size, alpha=0.0001, beta=0.75, k=1.0, data_format="NCHW", name=None):
    x = as_tensor(x)

    def fn(a, size, alpha, beta, k):
        sq = jnp.square(a)
        half = size // 2
        c = a.shape[1]
        pad = jnp.pad(sq, ((0, 0), (half, size - 1 - half)) + ((0, 0),) * (a.ndim - 2))
        acc = sum(pad[:, i : i + c] for i in range(size))
        return a / jnp.power(k + alpha * acc / size, beta) * 1.0

    return eager_call(
        "local_response_norm", fn, [x], {"size": size, "alpha": alpha, "beta": beta, "k": k}
    )


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    import jax

    w = as_tensor(weight)

    def fn(W, dim, power_iters, eps):
        Wm = jnp.moveaxis(W, dim, 0).reshape(W.shape[dim], -1)
        u = jnp.ones((Wm.shape[0],), W.dtype)
        v = jnp.ones((Wm.shape[1],), W.dtype)
        for _ in range(power_iters):
            v = Wm.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = Wm @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ Wm @ v
        return W / sigma

    return eager_call("spectral_norm", fn, [w], {"dim": dim, "power_iters": power_iters, "eps": eps})
