"""Convolution functionals.

Parity: reference ``python/paddle/nn/functional/conv.py`` backed by cuDNN
(``paddle/fluid/operators/conv_op.*``, ``conv_transpose_op.*``). Here each
conv is one ``lax.conv_general_dilated`` — XLA tiles it onto the MXU; no
algorithm search / workspace management is needed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ...core.dispatch import as_tensor, eager_call


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(x) for x in v)
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(n))
        return tuple(int(x) for x in v)
    return tuple(int(v) for _ in range(n))


def _padding(padding, n):
    """Normalize paddle padding spec → lax padding list of (lo, hi)."""
    if isinstance(padding, str):
        return padding.upper()  # 'SAME' | 'VALID'
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:  # [before0, after0, before1, after1...] paddle style
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    if all(isinstance(p, (list, tuple)) for p in padding):
        # NCHW-style full spec: take spatial entries
        spatial = [p for p in padding if tuple(p) != (0, 0)]
        out = [tuple(p) for p in padding[-n:]]
        return out
    return [(int(p), int(p)) for p in padding]


def _dim_numbers(nd, channel_last):
    if nd == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if nd == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _conv(x, weight, bias, stride, padding, dilation, groups, nd, data_format, name):
    x, weight = as_tensor(x), as_tensor(weight)
    channel_last = data_format[-1] == "C"
    stride = _tuple(stride, nd)
    dilation = _tuple(dilation, nd)
    pad = _padding(padding, nd)
    dn = _dim_numbers(nd, channel_last)

    def fn(a, w, *rest, stride=None, pad=None, dilation=None, groups=None, dn=None, channel_last=False):
        # weight layout is paddle OIHW; convert for channel-last dn
        if dn[1] in ("WIO", "HWIO", "DHWIO"):
            w = jnp.moveaxis(w, (0, 1), (-1, -2))
        out = lax.conv_general_dilated(
            a, w, window_strides=stride, padding=pad,
            rhs_dilation=dilation, feature_group_count=groups,
            dimension_numbers=dn,
        )
        if rest:
            b = rest[0]
            if channel_last:
                out = out + b.reshape((1,) * (out.ndim - 1) + (-1,))
            else:
                out = out + b.reshape((1, -1) + (1,) * (out.ndim - 2))
        return out

    args = [x, weight] + ([as_tensor(bias)] if bias is not None else [])
    return eager_call(
        f"conv{nd}d", fn, args,
        {
            "stride": stride,
            "pad": pad if isinstance(pad, str) else tuple(pad),
            "dilation": dilation,
            "groups": int(groups),
            "dn": dn,
            "channel_last": channel_last,
        },
    )


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCL", name=None):
    df = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, df, name)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format, name)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format, name)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, groups, dilation, nd, data_format, output_size, name):
    x, weight = as_tensor(x), as_tensor(weight)
    channel_last = data_format[-1] == "C"
    stride = _tuple(stride, nd)
    dilation = _tuple(dilation, nd)
    out_pad = _tuple(output_padding, nd) if output_padding is not None else (0,) * nd
    pad = _padding(padding, nd)
    dn = _dim_numbers(nd, channel_last)

    def fn(a, w, *rest, stride=None, pad=None, dilation=None, groups=None, dn=None, channel_last=False, out_pad=None):
        # paddle conv_transpose weight layout: (in, out/groups, *k)
        # grad-of-conv formulation: lax.conv_transpose with IO swap
        if isinstance(pad, str):
            pads = pad
        else:
            # convert forward-conv padding p to transpose padding:
            # lo = k_eff - 1 - p_lo ; hi = k_eff - 1 - p_hi + out_pad
            k = w.shape[2:]
            pads = [
                (
                    dilation[i] * (k[i] - 1) - pad[i][0],
                    dilation[i] * (k[i] - 1) - pad[i][1] + out_pad[i],
                )
                for i in range(len(k))
            ]
        # weight (I, O/g, *k) → flip spatial, to (O, I/g...) conv on dilated input
        w_flip = jnp.flip(w, axis=tuple(range(2, w.ndim)))
        if groups > 1:
            # split groups: w (I, O/g, *k) with I = g * (I/g)
            i_per_g = w.shape[0] // groups
            w_g = w_flip.reshape((groups, i_per_g) + w.shape[1:])
            w_g = jnp.swapaxes(w_g, 1, 2)  # (g, O/g, I/g, *k)
            w_oihw = w_g.reshape((w.shape[1] * groups, i_per_g) + w.shape[2:])
        else:
            w_oihw = jnp.swapaxes(w_flip, 0, 1)
        if dn[1] in ("WIO", "HWIO", "DHWIO"):
            w_oihw = jnp.moveaxis(w_oihw, (0, 1), (-1, -2))
        out = lax.conv_general_dilated(
            a, w_oihw, window_strides=(1,) * len(stride), padding=pads,
            lhs_dilation=stride, rhs_dilation=dilation,
            feature_group_count=groups, dimension_numbers=dn,
        )
        if rest:
            b = rest[0]
            if channel_last:
                out = out + b.reshape((1,) * (out.ndim - 1) + (-1,))
            else:
                out = out + b.reshape((1, -1) + (1,) * (out.ndim - 2))
        return out

    args = [x, weight] + ([as_tensor(bias)] if bias is not None else [])
    out = eager_call(
        f"conv{nd}d_transpose", fn, args,
        {
            "stride": stride,
            "pad": pad if isinstance(pad, str) else tuple(pad),
            "dilation": dilation,
            "groups": int(groups),
            "dn": dn,
            "channel_last": channel_last,
            "out_pad": out_pad,
        },
    )
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    df = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, groups, dilation, 1, df, output_size, name)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, data_format="NCHW", output_size=None, name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, groups, dilation, 2, data_format, output_size, name)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, data_format="NCDHW", output_size=None, name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, groups, dilation, 3, data_format, output_size, name)
