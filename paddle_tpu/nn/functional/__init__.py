"""paddle.nn.functional parity namespace."""
from .activation import (  # noqa: F401
    relu, relu6, sigmoid, tanh, silu, swish, mish, tanhshrink, softsign,
    log_sigmoid, gelu, leaky_relu, prelu, elu, celu, selu, hardshrink,
    softshrink, hardtanh, hardsigmoid, hardswish, softplus, softmax,
    log_softmax, gumbel_softmax, maxout, glu, thresholded_relu, rrelu,
)
from .common import (  # noqa: F401
    linear, dropout, dropout2d, dropout3d, alpha_dropout, pad, one_hot,
    embedding, label_smooth, interpolate, upsample, unfold, fold,
    cosine_similarity, pixel_shuffle, pixel_unshuffle, channel_shuffle,
    normalize, bilinear,
)
from .conv import (  # noqa: F401
    conv1d, conv2d, conv3d, conv1d_transpose, conv2d_transpose,
    conv3d_transpose,
)
from .pooling import (  # noqa: F401
    max_pool1d, max_pool2d, max_pool3d, avg_pool1d, avg_pool2d, avg_pool3d,
    adaptive_avg_pool1d, adaptive_avg_pool2d, adaptive_avg_pool3d,
    adaptive_max_pool1d, adaptive_max_pool2d, adaptive_max_pool3d,
)
from .norm import (  # noqa: F401
    batch_norm, layer_norm, group_norm, instance_norm, local_response_norm,
    spectral_norm,
)
from .loss import (  # noqa: F401
    cross_entropy, softmax_with_cross_entropy, nll_loss, mse_loss, l1_loss,
    smooth_l1_loss, binary_cross_entropy, binary_cross_entropy_with_logits,
    kl_div, margin_ranking_loss, hinge_embedding_loss, cosine_embedding_loss,
    triplet_margin_loss, log_loss, square_error_cost, ctc_loss,
    sigmoid_focal_loss,
)
from .attention import scaled_dot_product_attention, flash_attention  # noqa: F401
from .sparse_attention import sparse_attention  # noqa: F401
from .vision import (  # noqa: F401
    affine_grid, grid_sample, sequence_mask, temporal_shift, zeropad2d,
    pairwise_distance, npair_loss, dice_loss, gather_tree,
    max_unpool1d, max_unpool2d, max_unpool3d,
)
from .activation import relu_, elu_, softmax_  # noqa: F401
from .loss import hsigmoid_loss, margin_cross_entropy  # noqa: F401
from .loss import fused_linear_cross_entropy  # noqa: F401
from .common import class_center_sample  # noqa: F401
