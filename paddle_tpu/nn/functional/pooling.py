"""Pooling functionals.

Parity: reference ``python/paddle/nn/functional/pooling.py`` backed by
``paddle/fluid/operators/pool_op.*`` — here ``lax.reduce_window``.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...core.dispatch import as_tensor, eager_call


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in (v if len(v) == n else list(v) * n)[:n])
    return tuple(int(v) for _ in range(n))


def _pads(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    return [(int(p), int(p)) for p in padding]


def _pool(x, kernel, stride, padding, nd, op, data_format, ceil_mode=False, exclusive=True, count_include_pad=False, name="pool"):
    x = as_tensor(x)
    channel_last = data_format[-1] == "C"
    kernel = _tuple(kernel, nd)
    stride = _tuple(stride if stride is not None else kernel, nd)
    pads = _pads(padding, nd)

    def fn(a, kernel, stride, pads, op, channel_last, ceil_mode, exclusive):
        nd_ = len(kernel)
        if channel_last:
            window = (1,) + kernel + (1,)
            strides = (1,) + stride + (1,)
            full_pads = pads if isinstance(pads, str) else [(0, 0)] + list(pads) + [(0, 0)]
        else:
            window = (1, 1) + kernel
            strides = (1, 1) + stride
            full_pads = pads if isinstance(pads, str) else [(0, 0), (0, 0)] + list(pads)
        if isinstance(full_pads, str):
            spatial = a.shape[1:-1] if channel_last else a.shape[2:]
            if full_pads == "SAME":
                fp = []
                for s_in, k, s in zip(spatial, kernel, stride):
                    out = -(-s_in // s)
                    total = max(0, (out - 1) * s + k - s_in)
                    fp.append((total // 2, total - total // 2))
                full_pads = ([(0, 0)] + fp + [(0, 0)]) if channel_last else ([(0, 0), (0, 0)] + fp)
            else:
                full_pads = [(0, 0)] * a.ndim
        if ceil_mode:
            spatial_ax = range(1, a.ndim - 1) if channel_last else range(2, a.ndim)
            fp = list(full_pads)
            for i, ax in enumerate(spatial_ax):
                s_in = a.shape[ax] + fp[ax][0] + fp[ax][1]
                k, s = kernel[i], stride[i]
                rem = (s_in - k) % s
                if rem:
                    fp[ax] = (fp[ax][0], fp[ax][1] + (s - rem))
            full_pads = fp
        if op == "max":
            init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
            return lax.reduce_window(a, init, lax.max, window, strides, full_pads)
        # avg
        summed = lax.reduce_window(a, 0.0, lax.add, window, strides, full_pads)
        if exclusive:
            ones = jnp.ones(a.shape, a.dtype)
            counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, full_pads)
            return summed / counts
        return summed / np.prod(kernel)

    return eager_call(
        name, fn, [x],
        {
            "kernel": kernel, "stride": stride,
            "pads": pads if isinstance(pads, str) else tuple(pads),
            "op": op, "channel_last": channel_last,
            "ceil_mode": ceil_mode, "exclusive": exclusive,
        },
    )


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, name=None):
    out = _pool(x, kernel_size, stride, padding, 1, "max", "NCW", ceil_mode, name="max_pool1d")
    return (out, None) if return_mask else out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 2, "max", data_format, ceil_mode, name="max_pool2d")
    if return_mask:
        idx = _max_pool_indices(x, kernel_size, stride, padding, data_format)
        return out, idx
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 3, "max", data_format, ceil_mode, name="max_pool3d")
    return (out, None) if return_mask else out


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, "avg", "NCW", ceil_mode, exclusive, name="avg_pool1d")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, "avg", data_format, ceil_mode, exclusive, name="avg_pool2d")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, "avg", data_format, ceil_mode, exclusive, name="avg_pool3d")


def _max_pool_indices(x, kernel_size, stride, padding, data_format):
    from ...core.tensor import Tensor

    x = as_tensor(x)
    k = _tuple(kernel_size, 2)
    s = _tuple(stride if stride is not None else kernel_size, 2)
    a = np.asarray(x._data)
    if data_format != "NCHW":
        a = np.moveaxis(a, -1, 1)
    n, c, h, w = a.shape
    oh = (h - k[0]) // s[0] + 1
    ow = (w - k[1]) // s[1] + 1
    idx = np.zeros((n, c, oh, ow), dtype=np.int64)
    for i in range(oh):
        for j in range(ow):
            win = a[:, :, i * s[0] : i * s[0] + k[0], j * s[1] : j * s[1] + k[1]].reshape(n, c, -1)
            am = win.argmax(-1)
            r, cc = np.unravel_index(am, k)
            idx[:, :, i, j] = (i * s[0] + r) * w + (j * s[1] + cc)
    return Tensor(idx)


def _adaptive_windows(in_size, out_size):
    # paddle adaptive pooling: start = floor(i*in/out), end = ceil((i+1)*in/out)
    starts = [int(np.floor(i * in_size / out_size)) for i in range(out_size)]
    ends = [int(np.ceil((i + 1) * in_size / out_size)) for i in range(out_size)]
    return starts, ends


def _adaptive_pool(x, output_size, nd, op, data_format, name):
    x = as_tensor(x)
    channel_last = data_format[-1] == "C"
    spatial = x.shape[1:-1] if channel_last else x.shape[2:]
    out_size = _tuple(output_size, nd)
    out_size = tuple(o if o is not None else s for o, s in zip(out_size, spatial))

    if all(s % o == 0 for s, o in zip(spatial, out_size)):
        k = tuple(s // o for s, o in zip(spatial, out_size))
        return _pool(x, k, k, 0, nd, op, data_format, name=name)

    def fn(a, out_size, op, channel_last):
        axes = list(range(1, a.ndim - 1)) if channel_last else list(range(2, a.ndim))
        out = a
        for dim_i, ax in enumerate(axes):
            in_size = out.shape[ax]
            starts, ends = _adaptive_windows(in_size, out_size[dim_i])
            slices = []
            for st, en in zip(starts, ends):
                window = lax.slice_in_dim(out, st, en, axis=ax)
                red = jnp.max(window, axis=ax, keepdims=True) if op == "max" else jnp.mean(window, axis=ax, keepdims=True)
                slices.append(red)
            out = jnp.concatenate(slices, axis=ax)
        return out

    return eager_call(name, fn, [x], {"out_size": out_size, "op": op, "channel_last": channel_last})


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "avg", "NCW", "adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, "avg", data_format, "adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, "avg", data_format, "adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(x, output_size, 1, "max", "NCW", "adaptive_max_pool1d")
    return (out, None) if return_mask else out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(x, output_size, 2, "max", "NCHW", "adaptive_max_pool2d")
    return (out, None) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(x, output_size, 3, "max", "NCDHW", "adaptive_max_pool3d")
    return (out, None) if return_mask else out
