"""Sparse (block-masked) attention.

Parity: reference ``python/paddle/nn/functional/sparse_attention.py``
(CSR-masked attention CUDA op). TPU-native: block-sparse masking inside a
dense softmax-attention — XLA removes masked blocks' contribution; a Pallas
block-sparse kernel is the perf path for long sequences (see ring attention
in paddle_tpu/distributed for the scaled path).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.dispatch import as_tensor, eager_call


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns, name=None):
    """q,k,v: (B, H, T, D); offset/columns describe a per-row CSR mask."""
    q, k, v = as_tensor(query), as_tensor(key), as_tensor(value)
    off, cols = as_tensor(sparse_csr_offset), as_tensor(sparse_csr_columns)

    def fn(q, k, v, off, cols):
        B, H, T, D = q.shape
        scale = 1.0 / math.sqrt(D)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        # CSR → dense boolean mask
        off_i = off.astype(jnp.int32)
        cols_i = cols.astype(jnp.int32)
        nnz = cols_i.shape[-1]
        row_of = jnp.searchsorted(off_i[0, 0], jnp.arange(nnz), side="right") - 1

        def build_mask(off_row, cols_row):
            counts = off_row[1:] - off_row[:-1]
            rows = jnp.repeat(jnp.arange(T), counts, total_repeat_length=cols_row.shape[0])
            m = jnp.zeros((T, T), bool).at[rows, cols_row].set(True)
            return m

        mask = jax.vmap(jax.vmap(build_mask))(off_i, cols_i)
        scores = jnp.where(mask, scores, jnp.asarray(-1e30, scores.dtype))
        probs = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", probs, v)

    return eager_call("sparse_attention", fn, [q, k, v, off, cols])
