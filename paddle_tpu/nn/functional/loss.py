"""Loss functionals.

Parity: reference ``python/paddle/nn/functional/loss.py`` backed by
``paddle/fluid/operators/{softmax_with_cross_entropy,bce_loss,...}_op.*``.
Softmax+CE is computed fused-in-log-space (the reference's
softmax_with_cross_entropy kernel) — one pass, numerically stable, XLA fuses.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...core.dispatch import as_tensor, eager_call


def _reduce(val, reduction):
    if reduction == "mean":
        return jnp.mean(val)
    if reduction == "sum":
        return jnp.sum(val)
    return val


def cross_entropy(
    input,
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
    name=None,
):
    input, label = as_tensor(input), as_tensor(label)
    inputs = [input, label]
    has_w = weight is not None
    if has_w:
        inputs.append(as_tensor(weight))

    def fn(logits, lab, *w, ignore_index=-100, reduction="mean", soft_label=False,
           axis=-1, use_softmax=True, label_smoothing=0.0, has_w=False):
        if use_softmax:
            logp = jax.nn.log_softmax(logits, axis=axis)
        else:
            logp = jnp.log(jnp.maximum(logits, 1e-30))
        n_classes = logits.shape[axis]
        if soft_label:
            soft = lab
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -jnp.sum(soft * logp, axis=axis)
        else:
            lab_i = lab.astype(jnp.int32)
            if lab_i.ndim == logp.ndim:
                lab_i = jnp.squeeze(lab_i, axis=axis)
            valid = lab_i != ignore_index
            safe_lab = jnp.where(valid, lab_i, 0)
            picked = jnp.take_along_axis(
                logp, jnp.expand_dims(safe_lab, axis), axis=axis
            ).squeeze(axis)
            if label_smoothing > 0:
                smooth_loss = -jnp.mean(logp, axis=axis)
                loss = -(1 - label_smoothing) * picked + label_smoothing * smooth_loss
            else:
                loss = -picked
            if has_w:
                loss = loss * jnp.take(w[0], safe_lab)
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                if has_w:
                    denom = jnp.sum(jnp.where(valid, jnp.take(w[0], safe_lab), 0.0))
                else:
                    denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
                return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    return eager_call(
        "cross_entropy", fn, inputs,
        {
            "ignore_index": ignore_index, "reduction": reduction,
            "soft_label": soft_label, "axis": axis, "use_softmax": use_softmax,
            "label_smoothing": label_smoothing, "has_w": has_w,
        },
    )


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index, reduction="none", axis=axis)
    from .activation import softmax as _softmax

    loss = loss.unsqueeze(axis)
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    input, label = as_tensor(input), as_tensor(label)
    inputs = [input, label]
    has_w = weight is not None
    if has_w:
        inputs.append(as_tensor(weight))

    def fn(logp, lab, *w, ignore_index=-100, reduction="mean", has_w=False):
        lab_i = lab.astype(jnp.int32)
        valid = lab_i != ignore_index
        safe = jnp.where(valid, lab_i, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1).squeeze(1)
        loss = -picked
        wts = jnp.take(w[0], safe) if has_w else jnp.ones_like(loss)
        loss = jnp.where(valid, loss * wts, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(valid, wts, 0.0)), 1e-12)
        return _reduce(loss, reduction)

    return eager_call(
        "nll_loss", fn, inputs,
        {"ignore_index": ignore_index, "reduction": reduction, "has_w": has_w},
    )


def mse_loss(input, label, reduction="mean", name=None):
    return eager_call(
        "mse_loss",
        lambda a, b, reduction: _reduce(jnp.square(a - b), reduction),
        [as_tensor(input), as_tensor(label)],
        {"reduction": reduction},
    )


def l1_loss(input, label, reduction="mean", name=None):
    return eager_call(
        "l1_loss",
        lambda a, b, reduction: _reduce(jnp.abs(a - b), reduction),
        [as_tensor(input), as_tensor(label)],
        {"reduction": reduction},
    )


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b, reduction, delta):
        diff = jnp.abs(a - b)
        loss = jnp.where(diff < delta, 0.5 * diff * diff / delta, diff - 0.5 * delta)
        return _reduce(loss, reduction)

    return eager_call(
        "smooth_l1_loss", fn, [as_tensor(input), as_tensor(label)],
        {"reduction": reduction, "delta": delta},
    )


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    inputs = [as_tensor(input), as_tensor(label)]
    has_w = weight is not None
    if has_w:
        inputs.append(as_tensor(weight))

    def fn(p, y, *w, reduction="mean", has_w=False):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if has_w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    return eager_call("bce", fn, inputs, {"reduction": reduction, "has_w": has_w})


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    inputs = [as_tensor(logit), as_tensor(label)]
    has_w = weight is not None
    has_pw = pos_weight is not None
    if has_w:
        inputs.append(as_tensor(weight))
    if has_pw:
        inputs.append(as_tensor(pos_weight))

    def fn(x, y, *rest, reduction="mean", has_w=False, has_pw=False):
        i = 0
        w = rest[i] if has_w else None
        if has_w:
            i += 1
        pw = rest[i] if has_pw else None
        # stable: max(x,0) - x*y + log(1+exp(-|x|)), pos_weight folds into y term
        if pw is not None:
            log_weight = (pw - 1) * y + 1
            loss = (1 - y) * x + log_weight * (jnp.logaddexp(0.0, -jnp.abs(x)) + jnp.maximum(-x, 0.0))
        else:
            loss = jnp.maximum(x, 0) - x * y + jnp.logaddexp(0.0, -jnp.abs(x))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    return eager_call(
        "bce_with_logits", fn, inputs,
        {"reduction": reduction, "has_w": has_w, "has_pw": has_pw},
    )


def kl_div(input, label, reduction="mean", name=None):
    def fn(logp, y, reduction):
        loss = y * (jnp.log(jnp.maximum(y, 1e-12)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)

    return eager_call("kl_div", fn, [as_tensor(input), as_tensor(label)], {"reduction": reduction})


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    def fn(a, b, y, margin, reduction):
        return _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction)

    return eager_call(
        "margin_ranking_loss", fn,
        [as_tensor(input), as_tensor(other), as_tensor(label)],
        {"margin": margin, "reduction": reduction},
    )


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def fn(a, y, margin, reduction):
        loss = jnp.where(y == 1.0, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)

    return eager_call(
        "hinge_embedding_loss", fn, [as_tensor(input), as_tensor(label)],
        {"margin": margin, "reduction": reduction},
    )


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def fn(a, b, y, margin, reduction):
        cos = jnp.sum(a * b, axis=-1) / (
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1) + 1e-12
        )
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return eager_call(
        "cosine_embedding_loss", fn,
        [as_tensor(input1), as_tensor(input2), as_tensor(label)],
        {"margin": margin, "reduction": reduction},
    )


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-06, swap=False, reduction="mean", name=None):
    def fn(a, pos, neg, margin, p, epsilon, swap, reduction):
        d_pos = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        d_neg = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            d_swap = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            d_neg = jnp.minimum(d_neg, d_swap)
        return _reduce(jnp.maximum(0.0, d_pos - d_neg + margin), reduction)

    return eager_call(
        "triplet_margin_loss", fn,
        [as_tensor(input), as_tensor(positive), as_tensor(negative)],
        {"margin": margin, "p": p, "epsilon": epsilon, "swap": swap, "reduction": reduction},
    )


def log_loss(input, label, epsilon=0.0001, name=None):
    def fn(p, y, epsilon):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)

    return eager_call("log_loss", fn, [as_tensor(input), as_tensor(label)], {"epsilon": epsilon})


def square_error_cost(input, label):
    return eager_call(
        "square_error_cost", lambda a, b: jnp.square(a - b), [as_tensor(input), as_tensor(label)]
    )


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean", norm_by_times=False):
    """CTC via the standard alpha-recursion in log space (lax.scan over time).

    Reference: warpctc op (paddle/fluid/operators/warpctc_op.*).
    log_probs: (T, N, C) logits (softmax applied internally, paddle semantics).
    """
    lp, lab = as_tensor(log_probs), as_tensor(labels)
    il, ll = as_tensor(input_lengths), as_tensor(label_lengths)

    def fn(logits, labels, in_len, lab_len, blank, reduction):
        logp = jax.nn.log_softmax(logits, axis=-1)
        T, N, C = logp.shape
        S = labels.shape[1]
        ext_len = 2 * S + 1
        labels_i = labels.astype(jnp.int32)
        ext = jnp.full((N, ext_len), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(labels_i)
        neg_inf = jnp.asarray(-1e30, logp.dtype)

        def emit(t_logp, s_ext):
            return jnp.take_along_axis(t_logp, s_ext, axis=1)  # (N, ext_len)

        alpha0 = jnp.full((N, ext_len), neg_inf)
        alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
        first_lab = emit(logp[0], ext)[:, 1]
        alpha0 = alpha0.at[:, 1].set(first_lab)

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((N, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1
        )

        def step(alpha, t_logp):
            a_shift1 = jnp.concatenate([jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate([jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
            a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, a_shift1), a_shift2)
            new_alpha = merged + emit(t_logp, ext)
            return new_alpha, new_alpha

        _, alphas = jax.lax.scan(step, alpha0, logp[1:])
        alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # (T, N, ext)

        t_idx = (in_len.astype(jnp.int32) - 1).reshape(1, N, 1)
        final = jnp.take_along_axis(alphas, jnp.broadcast_to(t_idx, (1, N, ext_len)), axis=0)[0]
        last = (2 * lab_len.astype(jnp.int32)).reshape(N, 1)
        p_last = jnp.take_along_axis(final, last, axis=1)[:, 0]
        p_prev = jnp.take_along_axis(final, jnp.maximum(last - 1, 0), axis=1)[:, 0]
        ll_total = jnp.logaddexp(p_last, p_prev)
        loss = -ll_total
        return _reduce(loss, reduction)

    return eager_call(
        "ctc_loss", fn, [lp, lab, il, ll], {"blank": blank, "reduction": reduction}
    )


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    inputs = [as_tensor(logit), as_tensor(label)]
    has_norm = normalizer is not None
    if has_norm:
        inputs.append(as_tensor(normalizer))

    def fn(x, y, *n, alpha=0.25, gamma=2.0, reduction="sum", has_norm=False):
        p = jax.nn.sigmoid(x)
        ce = jnp.maximum(x, 0) - x * y + jnp.logaddexp(0.0, -jnp.abs(x))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if has_norm:
            loss = loss / n[0]
        return _reduce(loss, reduction)

    return eager_call(
        "sigmoid_focal_loss", fn, inputs,
        {"alpha": alpha, "gamma": gamma, "reduction": reduction, "has_norm": has_norm},
    )


def hsigmoid_loss(input, label, num_classes, weight, bias=None, path_table=None,
                  path_code=None, is_sparse=False, name=None):
    """Hierarchical sigmoid over a default complete binary tree
    (reference loss.py hsigmoid_loss / hierarchical_sigmoid_op)."""
    import numpy as np
    from ...core.dispatch import as_tensor, eager_call

    x, y, w = as_tensor(input), as_tensor(label), as_tensor(weight)
    if path_table is not None or path_code is not None:
        raise NotImplementedError("custom-tree hsigmoid: pass num_classes tree")
    depth = int(np.ceil(np.log2(max(num_classes, 2))))

    def fn(xv, yv, wv, *rest, depth=1, num_classes=2):
        bv = rest[0] if rest else None
        # complete-tree paths: node index = (label + num_classes) >> (k+1),
        # code bit = ((label + num_classes) >> k) & 1
        lab = yv.reshape(-1).astype(jnp.int32) + num_classes
        ks = jnp.arange(depth)
        nodes = (lab[:, None] >> (ks + 1)[None, :]) - 1          # (B, depth)
        codes = ((lab[:, None] >> ks[None, :]) & 1).astype(xv.dtype)
        valid = nodes >= 0
        nodes = jnp.clip(nodes, 0, wv.shape[0] - 1)
        logits = jnp.einsum("bd,bkd->bk", xv, wv[nodes])
        if bv is not None:
            logits = logits + bv.reshape(-1)[nodes]
        # bce with code as target; per-sample (N, 1) like the reference
        losses = jnp.maximum(logits, 0) - logits * codes + jnp.log1p(jnp.exp(-jnp.abs(logits)))
        return (losses * valid).sum(-1, keepdims=True)

    args = [x, y, w] + ([as_tensor(bias)] if bias is not None else [])
    return eager_call(
        "hsigmoid_loss", fn, args,
        attrs={"depth": depth, "num_classes": int(num_classes)},
    )


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False, reduction="mean"):
    """ArcFace/CosFace margin softmax (reference loss.py margin_cross_entropy;
    the mp-sharded variant rides GSPMD when logits carry an 'mp' sharding)."""
    from ...core.dispatch import as_tensor, eager_call

    lt, yt = as_tensor(logits), as_tensor(label)

    def fn(lg, yv, m1=1.0, m2=0.5, m3=0.0, s=64.0, reduction="mean"):
        yv = yv.reshape(-1)
        onehot = jax.nn.one_hot(yv, lg.shape[-1], dtype=lg.dtype)
        theta = jnp.arccos(jnp.clip(lg, -1 + 1e-7, 1 - 1e-7))
        target = jnp.cos(m1 * theta + m2) - m3
        adj = jnp.where(onehot > 0, target, lg) * s
        logp = jax.nn.log_softmax(adj, axis=-1)
        loss = -(onehot * logp).sum(-1)
        if reduction == "mean":
            loss = loss.mean()
        elif reduction == "sum":
            loss = loss.sum()
        return (loss, jax.nn.softmax(adj, -1))

    loss, sm = eager_call(
        "margin_cross_entropy", fn, [lt, yt],
        attrs={"m1": float(margin1), "m2": float(margin2), "m3": float(margin3),
               "s": float(scale), "reduction": reduction},
    )
    return (loss, sm) if return_softmax else loss


def fused_linear_cross_entropy(x, weight, label, block_rows=None, ignore_index=-100):
    """LM-head projection + softmax cross-entropy WITHOUT materializing the
    (N, vocab) logits tensor (see ops/fused_ce.py; role of the reference's
    c_softmax_with_cross_entropy fused op). x: (..., d); weight: (V, d);
    label: int (...,). Returns scalar mean loss over non-ignored rows.
    ``block_rows=None`` resolves the row-block size through the kernel
    registry (pinned 2048 default with autotune off)."""
    from ...ops.fused_ce import fused_linear_cross_entropy as _fce

    xt, wt, yt = as_tensor(x), as_tensor(weight), as_tensor(label)
    d = xt.shape[-1]

    def fn(xa, wa, ya, block_rows=0, ignore_index=-100):
        return _fce(
            xa.reshape(-1, d), wa, ya.reshape(-1).astype(jnp.int32),
            block_rows or None, ignore_index,
        )

    # attrs ride the eager-call cache key, so the registry sentinel is the
    # int 0 (= resolve at trace time), never a None
    return eager_call(
        "fused_linear_cross_entropy", fn, [xt, wt, yt],
        attrs={"block_rows": 0 if block_rows is None else int(block_rows),
               "ignore_index": int(ignore_index)},
    )
