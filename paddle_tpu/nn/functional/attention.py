"""Attention functionals.

Parity+: the reference only has fused_attention (C++
``paddle/fluid/operators/fused/fused_attention_op.cc`` / ``fmha_ref.h``); we
provide the same capability as a functional that XLA fuses, plus a
flash-attention entry point that routes to the Pallas TPU kernel when
available (paddle_tpu/ops/pallas/flash_attention.py).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.dispatch import as_tensor, eager_call


def _flash_eligible(q, k, is_causal, attn_mask, dropout_p, training):
    if not is_causal or attn_mask is not None:
        return False
    if dropout_p and training:
        return False
    d = q.shape[-1]
    if d % 8 != 0 or d > 256:
        return False
    if q.shape[1] < 512 or k.shape[1] % 128 != 0:
        return False  # short sequences: XLA's fused exact path measured faster
    # The backward kernels keep one full (T, D) operand pair resident in VMEM
    # (K/V for dq, Q/dO for dkv); bound it so jit-compile can't die on a
    # Mosaic allocation error with no fallback (~16 MB VMEM on v5e).
    esize = 2 if q.dtype in ("bfloat16", jnp.bfloat16) else 4
    if k.shape[1] * d * esize > 4 * 1024 * 1024:
        return False
    if jax.devices()[0].platform == "cpu":
        return False  # interpret-mode pallas is orders slower; XLA exact wins
    return True


def scaled_dot_product_attention(
    query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False, training=True,
    name=None, impl=None
):
    """q,k,v: (B, T, H, D) — paddle convention. Returns (B, T, H, D).

    Causal/no-mask/no-dropout calls route to the Pallas flash kernel
    (blockwise online softmax, no T×T materialization); everything else uses
    the XLA fused formulation. ``impl``: None (auto) | "exact" (never flash)
    | "flash" (force the Pallas kernel; raises if the call is ineligible).
    """
    q, k, v = as_tensor(query), as_tensor(key), as_tensor(value)
    if impl == "flash":
        from ...ops.pallas.flash_attention import flash_attention_tpu

        if not is_causal or attn_mask is not None or (dropout_p and training):
            raise ValueError(
                "impl='flash' requires is_causal=True, no attn_mask, no dropout"
            )
        return flash_attention_tpu(q, k, v, causal=True)
    if impl is None and _flash_eligible(q, k, is_causal, attn_mask, dropout_p, training):
        try:
            from ...ops.pallas.flash_attention import flash_attention_tpu

            return flash_attention_tpu(q, k, v, causal=True)
        except Exception:
            pass
    inputs = [q, k, v]
    has_mask = attn_mask is not None
    if has_mask:
        inputs.append(as_tensor(attn_mask))
    use_dropout = bool(dropout_p) and training
    if use_dropout:
        # keep-mask as a data input (same pattern as functional.dropout — a
        # closure-captured key would recompile the dispatch cache every step)
        from ...core import random as random_state
        from ...core.tensor import Tensor

        shape = (q.shape[0], q.shape[2], q.shape[1], k.shape[1])
        keep = jax.random.bernoulli(random_state.next_key(), 1.0 - float(dropout_p), shape)
        inputs.append(Tensor(keep))

    def fn(q, k, v, *rest, is_causal=False, has_mask=False, dropout_p=0.0):
        # (B, T, H, D) → (B, H, T, D)
        qh = jnp.swapaxes(q, 1, 2)
        kh = jnp.swapaxes(k, 1, 2)
        vh = jnp.swapaxes(v, 1, 2)
        scale = 1.0 / math.sqrt(qh.shape[-1])
        scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
        idx = 0
        if has_mask:
            scores = scores + rest[idx]
            idx += 1
        if is_causal:
            tq, tk = scores.shape[-2], scores.shape[-1]
            causal = jnp.tril(jnp.ones((tq, tk), bool))
            scores = jnp.where(causal, scores, jnp.asarray(-1e30, scores.dtype))
        probs = jax.nn.softmax(scores, axis=-1)
        if dropout_p:
            probs = probs * rest[idx].astype(probs.dtype) / (1.0 - dropout_p)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
        return jnp.swapaxes(out, 1, 2)

    return eager_call(
        "scaled_dot_product_attention", fn, inputs,
        {"is_causal": is_causal, "has_mask": has_mask,
         "dropout_p": float(dropout_p) if use_dropout else 0.0},
    )


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False, name=None):
    """Flash attention — same routing as scaled_dot_product_attention (one
    eligibility gate: Pallas kernel when it wins, XLA exact otherwise)."""
    return scaled_dot_product_attention(query, key, value, is_causal=causal, dropout_p=dropout), None
