"""Attention functionals.

Parity+: the reference only has fused_attention (C++
``paddle/fluid/operators/fused/fused_attention_op.cc`` / ``fmha_ref.h``); we
provide the same capability as a functional that XLA fuses, plus a
flash-attention entry point that routes to the Pallas TPU kernel when
available (paddle_tpu/ops/pallas/flash_attention.py).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.dispatch import as_tensor, eager_call


def _flash_eligible(q, k, is_causal, attn_mask, dropout_p, training):
    if not is_causal or attn_mask is not None:
        return False
    if dropout_p and training:
        return False
    d = q.shape[-1]
    if d % 8 != 0 or d > 256:
        return False
    if q.shape[1] < 128 or k.shape[1] % 128 != 0:
        return False  # tiny sequences: XLA fused path is already fine
    return True


def scaled_dot_product_attention(
    query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False, training=True, name=None
):
    """q,k,v: (B, T, H, D) — paddle convention. Returns (B, T, H, D).

    Causal/no-mask/no-dropout calls route to the Pallas flash kernel
    (blockwise online softmax, no T×T materialization); everything else uses
    the XLA fused formulation.
    """
    q, k, v = as_tensor(query), as_tensor(key), as_tensor(value)
    if _flash_eligible(q, k, is_causal, attn_mask, dropout_p, training):
        try:
            from ...ops.pallas.flash_attention import flash_attention_tpu

            return flash_attention_tpu(q, k, v, causal=True)
        except Exception:
            pass
    inputs = [q, k, v]
    has_mask = attn_mask is not None
    if has_mask:
        inputs.append(as_tensor(attn_mask))

    def fn(q, k, v, *m, is_causal=False, has_mask=False):
        # (B, T, H, D) → (B, H, T, D)
        qh = jnp.swapaxes(q, 1, 2)
        kh = jnp.swapaxes(k, 1, 2)
        vh = jnp.swapaxes(v, 1, 2)
        scale = 1.0 / math.sqrt(qh.shape[-1])
        scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
        if has_mask:
            scores = scores + m[0]
        if is_causal:
            tq, tk = scores.shape[-2], scores.shape[-1]
            causal = jnp.tril(jnp.ones((tq, tk), bool))
            scores = jnp.where(causal, scores, jnp.asarray(-1e30, scores.dtype))
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
        return jnp.swapaxes(out, 1, 2)

    return eager_call(
        "scaled_dot_product_attention", fn, inputs,
        {"is_causal": is_causal, "has_mask": has_mask},
    )


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False, name=None):
    """Flash attention — Pallas TPU kernel when on TPU, XLA fallback otherwise."""
    q = as_tensor(query)
    try:
        from ...ops.pallas.flash_attention import flash_attention_tpu

        out = flash_attention_tpu(q, as_tensor(key), as_tensor(value), causal=causal)
    except Exception:
        out = scaled_dot_product_attention(query, key, value, is_causal=causal)
    if return_softmax:
        return out, None
    return out, None
