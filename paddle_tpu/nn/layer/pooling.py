"""Pooling layers (reference python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer


class _Pool(Layer):
    def __init__(self, kernel_size=None, stride=None, padding=0, ceil_mode=False, return_mask=False, data_format=None, name=None, **kw):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.return_mask = return_mask
        self.data_format = data_format
        self.kw = kw


class MaxPool1D(_Pool):
    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding, self.return_mask, self.ceil_mode)


class MaxPool2D(_Pool):
    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding, self.return_mask, self.ceil_mode, self.data_format or "NCHW")


class MaxPool3D(_Pool):
    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding, self.return_mask, self.ceil_mode, self.data_format or "NCDHW")


class AvgPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode)
        self.exclusive = exclusive

    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding, self.exclusive, self.ceil_mode)


class AvgPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, data_format=data_format)
        self.exclusive = exclusive
        self.divisor_override = divisor_override

    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding, self.ceil_mode, self.exclusive, self.divisor_override, self.data_format)


class AvgPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, data_format=data_format)
        self.exclusive = exclusive
        self.divisor_override = divisor_override

    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding, self.ceil_mode, self.exclusive, self.divisor_override, self.data_format)


class _AdaptivePool(Layer):
    def __init__(self, output_size, return_mask=False, data_format=None, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask
        self.data_format = data_format


class AdaptiveAvgPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format or "NCHW")


class AdaptiveAvgPool3D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size, self.data_format or "NCDHW")


class AdaptiveMaxPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool3D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size, self.return_mask)
