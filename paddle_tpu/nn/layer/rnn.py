"""RNN layers.

Parity: reference ``python/paddle/nn/layer/rnn.py`` (+ C++ ``rnn_op`` /
cuDNN RNN kernels). TPU-native: the time loop is a ``lax.scan`` inside one
traced op so XLA compiles a single fused loop — no per-step dispatch.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import as_tensor, eager_call
from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from ..param_attr import ParamAttr
from .common import LayerList
from .layers import Layer


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None, init_value=0.0, batch_dim_idx=0):
        from ...ops.creation import full

        batch = batch_ref.shape[batch_dim_idx]
        state_shape = shape or getattr(self, "state_shape", None)

        def build(s):
            return full([batch] + list(s), init_value)

        if isinstance(state_shape, tuple) and state_shape and isinstance(state_shape[0], (list, tuple)):
            return tuple(build(s) for s in state_shape)
        return build(state_shape)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([hidden_size, input_size], attr=ParamAttr._to_attr(weight_ih_attr), default_initializer=u)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size], attr=ParamAttr._to_attr(weight_hh_attr), default_initializer=u)
        self.bias_ih = self.create_parameter([hidden_size], attr=ParamAttr._to_attr(bias_ih_attr), is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter([hidden_size], attr=ParamAttr._to_attr(bias_hh_attr), is_bias=True, default_initializer=u)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        self.state_shape = (hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def fn(x, h, w_ih, w_hh, b_ih, b_hh):
            return act(x @ w_ih.T + b_ih + h @ w_hh.T + b_hh)

        out = eager_call(
            "simple_rnn_cell", fn,
            [as_tensor(inputs), as_tensor(states), self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh],
        )
        return out, out


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size], attr=ParamAttr._to_attr(weight_ih_attr), default_initializer=u)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size], attr=ParamAttr._to_attr(weight_hh_attr), default_initializer=u)
        self.bias_ih = self.create_parameter([4 * hidden_size], attr=ParamAttr._to_attr(bias_ih_attr), is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter([4 * hidden_size], attr=ParamAttr._to_attr(bias_hh_attr), is_bias=True, default_initializer=u)
        self.hidden_size = hidden_size
        self.input_size = input_size
        self.state_shape = ((hidden_size,), (hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states

        def fn(x, h, c, w_ih, w_hh, b_ih, b_hh):
            gates = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            new_c = f * c + i * g
            new_h = o * jnp.tanh(new_c)
            return new_h, new_c

        out = eager_call(
            "lstm_cell", fn,
            [as_tensor(inputs), as_tensor(h), as_tensor(c), self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh],
        )
        return out[0], (out[0], out[1])


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size], attr=ParamAttr._to_attr(weight_ih_attr), default_initializer=u)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size], attr=ParamAttr._to_attr(weight_hh_attr), default_initializer=u)
        self.bias_ih = self.create_parameter([3 * hidden_size], attr=ParamAttr._to_attr(bias_ih_attr), is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter([3 * hidden_size], attr=ParamAttr._to_attr(bias_hh_attr), is_bias=True, default_initializer=u)
        self.hidden_size = hidden_size
        self.input_size = input_size
        self.state_shape = (hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def fn(x, h, w_ih, w_hh, b_ih, b_hh):
            gx = x @ w_ih.T + b_ih
            gh = h @ w_hh.T + b_hh
            rx, zx, cx = jnp.split(gx, 3, axis=-1)
            rh, zh, ch = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(rx + rh)
            z = jax.nn.sigmoid(zx + zh)
            c = jnp.tanh(cx + r * ch)
            return (1 - z) * c + z * h

        out = eager_call(
            "gru_cell", fn,
            [as_tensor(inputs), as_tensor(states), self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh],
        )
        return out, out


def _scan_layer(cell_kind, x, h0, c0, params, reverse=False):
    """One direction of one RNN layer as a lax.scan (x: (B, T, I))."""
    w_ih, w_hh, b_ih, b_hh = params

    def lstm_step(carry, xt):
        h, c = carry
        gates = xt @ w_ih.T + b_ih + h @ w_hh.T + b_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c2 = f * c + i * g
        h2 = o * jnp.tanh(c2)
        return (h2, c2), h2

    def gru_step(carry, xt):
        h = carry
        gx = xt @ w_ih.T + b_ih
        gh = h @ w_hh.T + b_hh
        rx, zx, cx = jnp.split(gx, 3, axis=-1)
        rh, zh, ch = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(rx + rh)
        z = jax.nn.sigmoid(zx + zh)
        c = jnp.tanh(cx + r * ch)
        h2 = (1 - z) * c + z * h
        return h2, h2

    def rnn_step(carry, xt):
        h = carry
        h2 = jnp.tanh(xt @ w_ih.T + b_ih + h @ w_hh.T + b_hh)
        return h2, h2

    xs = jnp.swapaxes(x, 0, 1)  # (T, B, I)
    if reverse:
        xs = jnp.flip(xs, 0)
    if cell_kind == "lstm":
        (hT, cT), ys = jax.lax.scan(lstm_step, (h0, c0), xs)
    elif cell_kind == "gru":
        hT, ys = jax.lax.scan(gru_step, h0, xs)
        cT = None
    else:
        hT, ys = jax.lax.scan(rnn_step, h0, xs)
        cT = None
    if reverse:
        ys = jnp.flip(ys, 0)
    return jnp.swapaxes(ys, 0, 1), hT, cT


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1, direction="forward", time_major=False, dropout=0.0, weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = 2 if direction in ("bidirect", "bidirectional") else 1
        gate_mult = {"lstm": 4, "gru": 3, "rnn": 1}[mode]
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self._all_weights = []
        for layer in range(num_layers):
            for direction_i in range(self.bidirect):
                in_sz = input_size if layer == 0 else hidden_size * self.bidirect
                suffix = f"_l{layer}" + ("_rev" if direction_i else "")
                w_ih = self.create_parameter([gate_mult * hidden_size, in_sz], default_initializer=u)
                w_hh = self.create_parameter([gate_mult * hidden_size, hidden_size], default_initializer=u)
                b_ih = self.create_parameter([gate_mult * hidden_size], is_bias=True, default_initializer=u)
                b_hh = self.create_parameter([gate_mult * hidden_size], is_bias=True, default_initializer=u)
                for n, p in (("weight_ih", w_ih), ("weight_hh", w_hh), ("bias_ih", b_ih), ("bias_hh", b_hh)):
                    self.add_parameter(n + suffix, p)
                self._all_weights.append((w_ih, w_hh, b_ih, b_hh))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = as_tensor(inputs)
        if self.time_major:
            x = x.transpose([1, 0, 2])
        B = x.shape[0]
        n_states = self.num_layers * self.bidirect
        if initial_states is None:
            from ...ops.creation import zeros

            h0 = zeros([n_states, B, self.hidden_size])
            c0 = zeros([n_states, B, self.hidden_size]) if self.mode == "lstm" else None
        else:
            if self.mode == "lstm":
                h0, c0 = initial_states
            else:
                h0, c0 = initial_states, None

        flat_params = [p for group in self._all_weights for p in group]
        tensor_args = [x, h0] + ([c0] if c0 is not None else []) + flat_params

        mode = self.mode
        num_layers = self.num_layers
        bidirect = self.bidirect
        has_c = c0 is not None
        dropout = self.dropout
        training = self.training

        def fn(xa, h0a, *rest, mode=mode, num_layers=num_layers, bidirect=bidirect, has_c=has_c):
            if has_c:
                c0a, params = rest[0], rest[1:]
            else:
                c0a, params = None, rest
            groups = [params[i * 4 : (i + 1) * 4] for i in range(num_layers * bidirect)]
            out = xa
            h_finals, c_finals = [], []
            gi = 0
            for layer in range(num_layers):
                outs_dir = []
                for d in range(bidirect):
                    g = groups[gi]
                    h_init = h0a[gi]
                    c_init = c0a[gi] if has_c else None
                    ys, hT, cT = _scan_layer(mode, out, h_init, c_init, g, reverse=(d == 1))
                    outs_dir.append(ys)
                    h_finals.append(hT)
                    if has_c:
                        c_finals.append(cT)
                    gi += 1
                out = outs_dir[0] if bidirect == 1 else jnp.concatenate(outs_dir, axis=-1)
            h_final = jnp.stack(h_finals)
            if has_c:
                return out, h_final, jnp.stack(c_finals)
            return out, h_final

        outs = eager_call(f"{mode}_rnn", fn, tensor_args)
        y = outs[0]
        if self.time_major:
            y = y.transpose([1, 0, 2])
        if self.mode == "lstm":
            return y, (outs[1], outs[2])
        return y, outs[1]


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward", time_major=False, dropout=0.0, activation="tanh", **kwargs):
        super().__init__("rnn", input_size, hidden_size, num_layers, direction, time_major, dropout, **kwargs)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("lstm", input_size, hidden_size, num_layers, direction, time_major, dropout, **kwargs)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("gru", input_size, hidden_size, num_layers, direction, time_major, dropout, **kwargs)


class RNN(Layer):
    """Wrap a cell into a scan over time (reference nn/layer/rnn.py RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None, **kwargs):
        x = as_tensor(inputs)
        if self.time_major:
            x = x.transpose([1, 0, 2])
        T = x.shape[1]
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        states = initial_states
        outs = []
        for t in steps:
            out, states = self.cell(x[:, t], states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        from ...ops.manipulation import stack

        y = stack(outs, axis=1)
        if self.time_major:
            y = y.transpose([1, 0, 2])
        return y, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        states_fw, states_bw = (initial_states or (None, None))
        y_fw, s_fw = self.rnn_fw(inputs, states_fw, sequence_length)
        y_bw, s_bw = self.rnn_bw(inputs, states_bw, sequence_length)
        from ...ops.manipulation import concat

        return concat([y_fw, y_bw], axis=-1), (s_fw, s_bw)
