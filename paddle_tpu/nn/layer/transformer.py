"""Transformer layers.

Parity: reference ``python/paddle/nn/layer/transformer.py`` (MultiHeadAttention
with cache, Encoder/Decoder stacks, Transformer) — attention core routes
through the fused scaled_dot_product_attention functional (XLA/Pallas).
"""
from __future__ import annotations

import collections

from ...core.tensor import Tensor
from .. import functional as F
from ..param_attr import ParamAttr
from .common import Dropout, Linear
from .layers import Layer
from .norm import LayerNorm


def _convert_attention_mask(attn_mask, dtype):
    import numpy as np

    if attn_mask is None:
        return None
    if attn_mask.dtype == np.dtype("bool"):
        from ...ops import math as m

        return (1.0 - attn_mask.cast("float32")) * -1e9
    return attn_mask


class MultiHeadAttention(Layer):
    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None, need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _prepare_qkv(self, query, key, value, cache=None):
        q = self.q_proj(query)
        B, T = q.shape[0], q.shape[1]
        q = q.reshape([B, T, self.num_heads, self.head_dim])
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self.k_proj(key).reshape([B, key.shape[1], self.num_heads, self.head_dim])
            v = self.v_proj(value).reshape([B, value.shape[1], self.num_heads, self.head_dim])
        if isinstance(cache, self.Cache):
            from ...ops.manipulation import concat

            k = concat([cache.k, k], axis=1)
            v = concat([cache.v, v], axis=1)
            cache = self.Cache(k, v)
        return q, k, v, cache

    def gen_cache(self, key, value=None, type=None):
        if type == MultiHeadAttention.StaticCache:
            k = self.k_proj(key).reshape([key.shape[0], key.shape[1], self.num_heads, self.head_dim])
            v = self.v_proj(value if value is not None else key).reshape(
                [key.shape[0], key.shape[1], self.num_heads, self.head_dim]
            )
            return self.StaticCache(k, v)
        from ...ops.creation import zeros

        B = key.shape[0]
        k = zeros([B, 0, self.num_heads, self.head_dim])
        v = zeros([B, 0, self.num_heads, self.head_dim])
        return self.Cache(k, v)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = key if value is None else value
        q, k, v, cache = self._prepare_qkv(query, key, value, cache)
        attn_mask = _convert_attention_mask(attn_mask, None)
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask, training=self.training)
        B, T = out.shape[0], out.shape[1]
        out = out.reshape([B, T, self.embed_dim])
        if self.dropout and self.training:
            out = F.dropout(out, self.dropout, training=self.training)
        out = self.out_proj(out)
        outs = [out]
        if self.need_weights:
            outs.append(None)
        if cache is not None:
            outs.append(cache)
        return out if len(outs) == 1 else tuple(outs)


class TransformerEncoderLayer(Layer):
    def __init__(
        self, d_model, nhead, dim_feedforward, dropout=0.1, activation="relu",
        attn_dropout=None, act_dropout=None, normalize_before=False,
        weight_attr=None, bias_attr=None,
    ):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout if attn_dropout is not None else dropout)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout = Dropout(dropout)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(act_dropout if act_dropout is not None else dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout2(self.activation(self.linear1(src))))
        src = residual + self.dropout(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        from .common import LayerList

        self.layers = LayerList([encoder_layer] + [copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, src_mask)
            else:
                output, new_cache = layer(output, src_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.self_attn.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(
        self, d_model, nhead, dim_feedforward, dropout=0.1, activation="relu",
        attn_dropout=None, act_dropout=None, normalize_before=False,
        weight_attr=None, bias_attr=None,
    ):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout if attn_dropout is not None else dropout)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout if attn_dropout is not None else dropout)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(act_dropout if act_dropout is not None else dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        else:
            tgt, incr_cache = self.self_attn(tgt, tgt, tgt, tgt_mask, cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        else:
            tgt, static_cache = self.cross_attn(tgt, memory, memory, memory_mask, cache[1])
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout3(self.activation(self.linear1(tgt))))
        tgt = residual + tgt
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (incr_cache, static_cache))

    def gen_cache(self, memory):
        incremental_cache = self.self_attn.gen_cache(memory, type=MultiHeadAttention.Cache)
        static_cache = self.cross_attn.gen_cache(memory, memory, type=MultiHeadAttention.StaticCache)
        return incremental_cache, static_cache


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        from .common import LayerList

        self.layers = LayerList([decoder_layer] + [copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        output = tgt
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, memory, tgt_mask, memory_mask)
            else:
                output, new_cache = layer(output, memory, tgt_mask, memory_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        cache = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            cache = list(zip(*cache))
        return cache


class Transformer(Layer):
    def __init__(
        self, d_model=512, nhead=8, num_encoder_layers=6, num_decoder_layers=6,
        dim_feedforward=2048, dropout=0.1, activation="relu", attn_dropout=None,
        act_dropout=None, normalize_before=False, weight_attr=None,
        bias_attr=None, custom_encoder=None, custom_decoder=None,
    ):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            encoder_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation, attn_dropout, act_dropout, normalize_before
            )
            self.encoder = TransformerEncoder(encoder_layer, num_encoder_layers, LayerNorm(d_model) if normalize_before else None)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            decoder_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation, attn_dropout, act_dropout, normalize_before
            )
            self.decoder = TransformerDecoder(decoder_layer, num_decoder_layers, LayerNorm(d_model) if normalize_before else None)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        import numpy as np

        mask = np.triu(np.full((length, length), -np.inf, np.float32), k=1)
        return Tensor(mask)
