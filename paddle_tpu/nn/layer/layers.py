"""Layer base class.

Parity: reference ``python/paddle/fluid/dygraph/layers.py`` — parameter /
sublayer / buffer registries via __setattr__, state_dict with structured
names, train/eval mode, forward hooks, apply, to().
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, Optional, Tuple

import numpy as np

from ...core import dtype as dtypes
from ...core.tensor import Parameter, Tensor
from .. import initializer as init_mod


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype=None):
        self.training = True
        self._dtype = dtypes.convert_dtype(dtype) if dtype else dtypes.get_default_dtype()
        self._parameters: Dict[str, Parameter] = collections.OrderedDict()
        self._sub_layers: Dict[str, "Layer"] = collections.OrderedDict()
        self._buffers: Dict[str, Tensor] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._hook_id = 0
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # -- construction helpers --------------------------------------------
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ) -> Parameter:
        dtype = dtypes.convert_dtype(dtype) if dtype else self._dtype
        initializer = None
        name = None
        trainable = True
        learning_rate = 1.0
        if attr is not None and attr is not False:
            from ..param_attr import ParamAttr

            if isinstance(attr, ParamAttr):
                initializer = attr.initializer
                name = attr.name
                trainable = attr.trainable
                learning_rate = attr.learning_rate
            elif isinstance(attr, init_mod.Initializer):
                initializer = attr
            elif isinstance(attr, str):
                name = attr
        if initializer is None:
            initializer = default_initializer or (
                init_mod._default_bias_init if is_bias else init_mod._default_weight_init
            )
        data = initializer(shape, dtype)
        p = Parameter(data, name=name, trainable=trainable)
        p.optimize_attr["learning_rate"] = learning_rate
        return p

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- attribute magic --------------------------------------------------
    def __getattr__(self, name):
        # only called when normal lookup fails: check registries (buffers are
        # registered without setattr, reference layers.py behavior)
        for registry in ("_buffers", "_parameters", "_sub_layers"):
            d = self.__dict__.get(registry)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ first")
            params[name] = value
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            object.__setattr__(self, name, value)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ first")
            layers[name] = value
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            object.__setattr__(self, name, value)
        else:
            if params is not None and name in params and value is None:
                params.pop(name)
            if buffers is not None and isinstance(value, Tensor) and name in buffers:
                buffers[name] = value
            object.__setattr__(self, name, value)

    # -- iteration --------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer_prefix, layer in self._walk(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is not None and id(p) not in seen:
                    seen.add(id(p))
                    yield ((layer_prefix + "." + pname) if layer_prefix else pname), p

    def _walk(self, prefix="", include_sublayers=True):
        yield None, prefix, self
        if include_sublayers:
            for name, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = (prefix + "." + name) if prefix else name
                for item in sub._walk(sub_prefix, True):
                    yield item

    def sublayers(self, include_self=False):
        out = []
        for _, _, layer in self._walk():
            out.append(layer)
        if not include_self:
            out = out[1:]
        return out

    def named_sublayers(self, prefix="", include_self=False):
        for i, (_, p, layer) in enumerate(self._walk(prefix)):
            if i == 0 and not include_self:
                continue
            yield p, layer

    def children(self):
        return iter(self._sub_layers.values())

    def named_children(self):
        return iter(self._sub_layers.items())

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for _, layer_prefix, layer in self._walk(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is not None:
                    yield ((layer_prefix + "." + bname) if layer_prefix else bname), b

    # -- mode -------------------------------------------------------------
    def train(self):
        self.training = True
        for layer in self.sublayers():
            layer.training = True
        return self

    def eval(self):
        self.training = False
        for layer in self.sublayers():
            layer.training = False
        return self

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    # -- state dict -------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True, use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            dest[name] = p
        for name, b in self.named_buffers(include_sublayers=include_sublayers):
            short = name.rsplit(".", 1)[-1]
            owner = self._locate(name)
            if owner is not None and short in owner._non_persistable_buffer_names:
                continue
            dest[name] = b
        return dest

    def _locate(self, qual_name):
        parts = qual_name.split(".")[:-1]
        layer = self
        for p in parts:
            layer = layer._sub_layers.get(p)
            if layer is None:
                return None
        return layer

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, value in state_dict.items():
            if name not in own:
                unexpected.append(name)
                continue
            target = own[name]
            arr = value.numpy() if isinstance(value, Tensor) else np.asarray(value)
            if tuple(arr.shape) != tuple(target.shape):
                raise ValueError(f"shape mismatch for {name}: {arr.shape} vs {target.shape}")
            target.set_value(arr.astype(target.dtype))
        for name in own:
            if name not in state_dict:
                missing.append(name)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # -- dtype / device movement -----------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        import jax

        from ...core.place import Place

        for p in self.parameters():
            arr = p._data
            if dtype is not None and dtypes.is_floating_point(p.dtype):
                arr = arr.astype(dtypes.convert_dtype(dtype))
            if device is not None:
                place = device if isinstance(device, Place) else None
                if place is None:
                    name, _, idx = str(device).partition(":")
                    place = Place({"xla": "tpu", "cuda": "gpu"}.get(name, name), int(idx) if idx else 0)
                arr = jax.device_put(arr, place.jax_device())
            p._set_data(arr)
        for b in self.buffers():
            if dtype is not None and dtypes.is_floating_point(b.dtype):
                b._set_data(b._data.astype(dtypes.convert_dtype(dtype)))
        if dtype is not None:
            for layer in self.sublayers(include_self=True):
                layer._dtype = dtypes.convert_dtype(dtype)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # -- hooks ------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- call -------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = [sub_repr[0]] + ["  " + l for l in sub_repr[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub_repr))
        main = f"{self.__class__.__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"
