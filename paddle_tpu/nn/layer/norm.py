"""Norm layers (reference python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from ..param_attr import ParamAttr
from .layers import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None, bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_features], attr=ParamAttr._to_attr(weight_attr),
                default_initializer=I.Constant(1.0),
            )
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [num_features], attr=ParamAttr._to_attr(bias_attr), is_bias=True,
                default_initializer=I.Constant(0.0),
            )
        else:
            self.bias = None
        self.register_buffer("_mean", Tensor(np.zeros(num_features, np.float32)))
        self.register_buffer("_variance", Tensor(np.ones(num_features, np.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format, use_global_stats=self._use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}, epsilon={self._epsilon}"


class BatchNorm(_BatchNormBase):
    """fluid-style BatchNorm (reference fluid/dygraph/nn.py BatchNorm) —
    acts like BatchNorm1D/2D/3D depending on input rank."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-05, **kwargs):
        super().__init__(num_channels, momentum, epsilon)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm. Under pjit/GSPMD the batch axis is sharded
    and XLA computes global statistics automatically when the reduction spans
    the full batch — so this is BatchNorm with mesh-aware semantics
    (reference: sync_batch_norm_op.cu + nccl allreduce of stats)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = cls(layer._num_features, layer._momentum, layer._epsilon)
            new.weight = layer.weight
            new.bias = layer.bias
            new._buffers = layer._buffers
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
            object.__setattr__(layer, name, layer._sub_layers[name])
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=ParamAttr._to_attr(weight_attr),
                default_initializer=I.Constant(1.0),
            )
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                self._normalized_shape, attr=ParamAttr._to_attr(bias_attr), is_bias=True,
                default_initializer=I.Constant(0.0),
            )
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05, weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_channels], attr=ParamAttr._to_attr(weight_attr),
                default_initializer=I.Constant(1.0),
            )
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [num_channels], attr=ParamAttr._to_attr(bias_attr), is_bias=True,
                default_initializer=I.Constant(0.0),
            )
        else:
            self.bias = None

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight, self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter([num_features], default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter([num_features], is_bias=True, default_initializer=I.Constant(0.0))
        else:
            self.weight = None
            self.bias = None

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias, eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k, self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12, dtype="float32"):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps

    def forward(self, weight):
        return F.spectral_norm(weight, self.dim, self.power_iters, self.eps)
