"""paddle.nn parity namespace."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .param_attr import ParamAttr  # noqa: F401
from .layer.layers import Layer  # noqa: F401
from .layer.common import (  # noqa: F401
    Identity, Linear, Embedding, Dropout, Dropout2D, Dropout3D, AlphaDropout,
    Flatten, Upsample, UpsamplingBilinear2D, UpsamplingNearest2D, Pad1D,
    Pad2D, Pad3D, ZeroPad2D, CosineSimilarity, PixelShuffle, PixelUnshuffle,
    ChannelShuffle, Bilinear, Unfold, Fold, Sequential, LayerList,
    ParameterList, LayerDict,
)
from .layer.conv import (  # noqa: F401
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose, Conv3DTranspose,
)
from .layer.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, SyncBatchNorm,
    LayerNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
    LocalResponseNorm, SpectralNorm,
)
from .layer.activation import (  # noqa: F401
    ReLU, ReLU6, GELU, Sigmoid, Tanh, Softmax, LogSoftmax, LeakyReLU, PReLU,
    ELU, CELU, SELU, Silu, Swish, Mish, Hardshrink, Softshrink, Hardtanh,
    Hardsigmoid, Hardswish, Softplus, Softsign, Tanhshrink, LogSigmoid,
    Maxout, ThresholdedReLU, RReLU, GLU,
)
from .layer.pooling import (  # noqa: F401
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D,
)
from .layer.loss import (  # noqa: F401
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    SmoothL1Loss, KLDivLoss, MarginRankingLoss, HingeEmbeddingLoss,
    CosineEmbeddingLoss, TripletMarginLoss, CTCLoss,
)
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from .layer.rnn import (  # noqa: F401
    RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN, SimpleRNN,
    LSTM, GRU,
)
from .clip import (  # noqa: F401
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm, clip_grad_norm_,
)
from . import utils  # noqa: F401
