"""paddle.reader — legacy reader decorators (reference
python/paddle/reader/decorator.py). Pure-python generator combinators over
"reader creators" (zero-arg callables returning iterators); kept for v1 API
compatibility — new code feeds paddle.io.DataLoader directly.
"""
from __future__ import annotations

import itertools
import random as _random
from queue import Queue
from threading import Thread

__all__ = ["cache", "map_readers", "shuffle", "chain", "compose", "buffered",
           "firstn", "xmap_readers", "ComposeNotAligned"]


class ComposeNotAligned(ValueError):
    pass


def cache(reader):
    """Cache the first full pass in memory; later passes replay it
    (decorator.py:52). Only a COMPLETE pass is committed — a reader that
    raises mid-pass leaves the cache empty so a retry starts clean."""
    state = {}

    def impl():
        if "data" not in state:
            state["data"] = list(reader())  # commits only on full success
        return iter(state["data"])

    return impl


def map_readers(func, *readers):
    """Apply ``func`` across the zipped outputs of ``readers``
    (decorator.py:92)."""

    def reader():
        rs = [r() for r in readers]
        for items in zip(*rs):
            yield func(*items)

    return reader


def shuffle(reader, buf_size):
    """Buffered shuffle (decorator.py:134): fill a buf_size window, yield in
    random order."""

    def impl():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return impl


def chain(*readers):
    """Concatenate readers sequentially (decorator.py:183)."""

    def reader():
        return itertools.chain(*[r() for r in readers])

    return reader


def compose(*readers, check_alignment=True):
    """Zip readers into flattened tuples (decorator.py:246)."""

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        it = itertools.zip_longest(*rs) if check_alignment else zip(*rs)
        for outputs in it:
            if check_alignment and any(o is None for o in outputs):
                raise ComposeNotAligned(
                    "outputs of readers are not aligned (different lengths)")
            yield sum((make_tuple(o) for o in outputs), ())

    return reader


def buffered(reader, size):
    """Read ahead into a bounded queue on a worker thread (decorator.py:306)."""

    end = object()

    def impl():
        q = Queue(maxsize=size)

        def fill():
            try:
                for d in reader():
                    q.put(d)
                q.put(end)
            except BaseException as exc:  # propagate instead of hanging
                q.put(exc)

        t = Thread(target=fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is end:
                return
            if isinstance(e, BaseException):
                raise e
            yield e

    return impl


def firstn(reader, n):
    """First n items (decorator.py:360)."""

    def impl():
        for i, item in enumerate(reader()):
            if i >= n:
                return
            yield item

    return impl


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with worker threads (decorator.py:372)."""

    end = object()

    def impl():
        in_q = Queue(buffer_size)
        out_q = Queue(buffer_size)

        def feed():
            try:
                for i, d in enumerate(reader()):
                    in_q.put((i, d))
            except BaseException as exc:
                out_q.put(exc)
            for _ in range(process_num):
                in_q.put(end)

        def work():
            while True:
                e = in_q.get()
                if e is end:
                    out_q.put(end)
                    return
                i, d = e
                try:
                    out_q.put((i, mapper(d)))
                except BaseException as exc:  # re-raised by the consumer
                    out_q.put(exc)
                    out_q.put(end)
                    return

        Thread(target=feed, daemon=True).start()
        workers = [Thread(target=work, daemon=True) for _ in range(process_num)]
        for w in workers:
            w.start()
        finished = 0
        pending = {}
        next_i = 0
        while finished < process_num:
            e = out_q.get()
            if e is end:
                finished += 1
                continue
            if isinstance(e, BaseException):
                raise e
            if not order:
                yield e[1]
                continue
            pending[e[0]] = e[1]
            while next_i in pending:
                yield pending.pop(next_i)
                next_i += 1
        while order and next_i in pending:
            yield pending.pop(next_i)
            next_i += 1

    return impl
