"""paddle.linalg namespace (reference python/paddle/linalg.py re-exports)."""
from ..ops.linalg import (  # noqa: F401
    cholesky, inv, inverse, det, slogdet, svd, qr, eig, eigh, eigvals,
    eigvalsh, norm, cond, matrix_power, matrix_rank, pinv, solve,
    triangular_solve, cholesky_solve, lstsq, lu, multi_dot, corrcoef, cov,
    householder_product,
)
from ..ops.math import matmul, dot  # noqa: F401
