"""paddle.io — datasets, samplers, DataLoader.

Parity: reference ``python/paddle/io/`` + the C++ feed pipeline
(``python/paddle/fluid/dataloader/dataloader_iter.py:144,326``, C++
``operators/reader/buffered_reader.cc`` async device prefetch,
``lod_tensor_blocking_queue.h``). Here: worker threads/processes feed a
bounded queue (native C++ queue core in runtime_cpp when built) and batches
are transferred to device asynchronously — PJRT overlaps H2D with compute.
"""
from __future__ import annotations

import itertools
import math
import queue as _queue
import threading
import weakref
from typing import Iterable, List, Optional

import numpy as np

from ..core import random as random_state
from ..core.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __iter__(self):
        return itertools.chain(*self.datasets)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self.cum[-1])

    def __getitem__(self, idx):
        ds = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if ds == 0 else int(self.cum[ds - 1])
        return self.datasets[ds][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths != dataset size")
    perm = np.random.permutation(len(dataset))
    out, offset = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[offset : offset + n].tolist()))
        offset += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        return iter(
            np.random.choice(len(self.weights), self.num_samples, replace=self.replacement, p=p).tolist()
        )

    def __len__(self):
        return self.num_samples


class _SeededRandomSampler(Sampler):
    """Shuffle that is a pure function of ``(seed, epoch)`` — the property
    sample-exact resume needs: an interrupted run that reloads the loader's
    ``state_dict`` replays bit-identical batch order, because nothing about
    the permutation depends on ambient global RNG state at iteration time."""

    def __init__(self, data_source, seed: int, epoch_fn):
        super().__init__(data_source)
        self.seed = int(seed)
        self._epoch_fn = epoch_fn  # () -> current epoch (owned by the loader)

    def __iter__(self):
        # distinct, decorrelated stream per (seed, epoch); SeedSequence does
        # the mixing so seed=0/epoch=1 and seed=1/epoch=0 don't collide
        rng = np.random.default_rng(np.random.SeedSequence([self.seed, self._epoch_fn()]))
        return iter(rng.permutation(len(self.data_source)).tolist())


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the index space across data-parallel ranks (reference
    python/paddle/io/__init__.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None, shuffle=False, drop_last=False):
        from ..distributed import get_world_size, get_rank

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
            self.epoch += 1
        indices = np.concatenate([indices, indices[: self.total_size - n]])
        indices = indices[self.local_rank : self.total_size : self.nranks]
        batch = []
        for idx in indices:
            batch.append(int(idx))
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        return Tensor(np.stack([np.asarray(b._data) for b in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, dtype=np.float32))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(s)) for s in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


def _tree_to_numpy(obj):
    if isinstance(obj, Tensor):
        return obj.numpy()
    if isinstance(obj, (list, tuple)):
        return type(obj)(_tree_to_numpy(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _tree_to_numpy(v) for k, v in obj.items()}
    return obj


def _tree_to_tensor(obj):
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_tree_to_tensor(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _tree_to_tensor(v) for k, v in obj.items()}
    return obj


def _numpy_collate(batch):
    """Worker-side collate: numpy end to end — forked children must never
    touch the inherited JAX/PJRT client (reference workers are CPU-only for
    the same reason: dataloader_iter.py worker processes build LoDTensors
    from numpy, never CUDA)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        # converting would call into the inherited PJRT client inside the
        # forked child — the exact hazard this worker path exists to avoid
        raise TypeError(
            "Dataset.__getitem__ returned a paddle Tensor but num_workers>0 "
            "uses forked worker processes, which must not touch the device "
            "runtime. Return numpy arrays (or python scalars) from "
            "__getitem__, or pass use_shared_memory=False for thread workers."
        )
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (list, tuple)):
        return [_numpy_collate(list(s)) for s in zip(*batch)]
    if isinstance(sample, dict):
        return {k: _numpy_collate([b[k] for b in batch]) for k in sample}
    return batch


def _mp_worker_loop(dataset, collate_fn, index_q, data_q):
    """Forked worker process: indices in, pickled numpy batches out
    (reference ``fluid/dataloader/dataloader_iter.py:326`` worker loop +
    ``worker.py`` — same protocol, minus the shared-memory LoDTensor
    transport which multiprocessing pipes replace here)."""
    import traceback

    while True:
        item = index_q.get()
        if item is None:
            return
        i, indices = item
        try:
            batch = collate_fn([dataset[j] for j in indices])
            data_q.put((i, "ok", batch))
        except Exception:
            data_q.put((i, "err", traceback.format_exc()))


class _MultiprocessIter:
    """num_workers forked processes → mp.Queue → ordered reassembly →
    tensorize on the consumer (reference _DataLoaderIterMultiProcess:
    out-of-order completions are buffered until their turn)."""

    def __init__(self, loader):
        import multiprocessing as mp

        ctx = mp.get_context("fork")
        self.loader = loader
        collate = loader.collate_fn or _numpy_collate
        self.index_q = ctx.Queue()
        self.data_q = ctx.Queue()
        self.n_batches = 0
        index_iter, _ = loader._index_iter()
        for i, indices in enumerate(index_iter):
            self.index_q.put((i, list(indices)))
            self.n_batches = i + 1
        for _ in range(loader.num_workers):
            self.index_q.put(None)
        self.workers = [
            ctx.Process(
                target=_mp_worker_loop,
                args=(loader.dataset, collate, self.index_q, self.data_q),
                daemon=True,
            )
            for _ in range(loader.num_workers)
        ]
        for w in self.workers:
            w.start()
        self._next = 0
        self._hold = {}

    def __iter__(self):
        return self

    def __next__(self):
        if self._next >= self.n_batches:
            self._shutdown()
            raise StopIteration
        import queue as _queue

        while self._next not in self._hold:
            try:
                i, kind, payload = self.data_q.get(timeout=5.0)
            except _queue.Empty:
                # a crashed worker (OOM-kill, segfault) never posts its batch;
                # without this check the consumer would block forever
                if not any(w.is_alive() for w in self.workers):
                    self._shutdown()
                    raise RuntimeError(
                        "DataLoader workers exited unexpectedly before "
                        f"producing batch {self._next}/{self.n_batches}"
                    )
                continue
            self._hold[i] = (kind, payload)
        kind, payload = self._hold.pop(self._next)
        self._next += 1
        if kind == "err":
            self._shutdown()
            raise RuntimeError(f"DataLoader worker failed:\n{payload}")
        batch = _tree_to_tensor(payload)
        if self.loader.return_list and isinstance(batch, (list, tuple)):
            return list(batch)
        return batch

    def _shutdown(self):
        for w in self.workers:
            if w.is_alive():
                w.terminate()
        for w in self.workers:
            w.join(timeout=1.0)
        self.workers = []

    def __del__(self):
        try:
            self._shutdown()
        except Exception:
            pass


class _DataLoaderIter:
    """Worker threads → bounded queue → host→device transfer.

    Mirrors the reference's _DataLoaderIterMultiProcess + C++ BufferedReader
    double-buffering (operators/reader/buffered_reader.cc): `prefetch_factor`
    batches stay staged in the queue; device transfer happens on get. When
    the native runtime is built, the queue is the C++ blocking queue
    (runtime_cpp/queue.cc) — batches cross as pickled numpy trees and Tensor
    creation (device transfer) happens only on the consumer thread.
    """

    def __init__(self, loader):
        self.loader = loader
        self.batch_sampler_iter, _ = loader._index_iter()
        self.num_workers = loader.num_workers
        self.collate_fn = loader.collate_fn or default_collate_fn
        self.done = False
        self.native_q = None
        if self.num_workers > 0:
            cap = max(2, loader.prefetch_factor)
            if loader.use_buffer_reader:
                try:
                    from ..core.native import NativeQueue

                    self.native_q = NativeQueue(cap)
                except Exception:
                    self.native_q = None
            if self.native_q is None:
                self.queue: _queue.Queue = _queue.Queue(maxsize=cap)
            self.index_queue: _queue.Queue = _queue.Queue()
            self.n_pending = 0
            for indices in self.batch_sampler_iter:
                self.index_queue.put(indices)
                self.n_pending += 1
            self.workers = []
            for _ in range(self.num_workers):
                t = threading.Thread(target=self._worker_loop, daemon=True)
                t.start()
                self.workers.append(t)
            self.n_received = 0

    def _fetch(self, indices, numpy_only=False):
        ds = self.loader.dataset
        if isinstance(ds, IterableDataset):
            raise RuntimeError("use _IterableIter")
        batch = self.collate_fn([ds[i] for i in indices])
        return _tree_to_numpy(batch) if numpy_only else batch

    def _worker_loop(self):
        import pickle

        while True:
            try:
                indices = self.index_queue.get_nowait()
            except _queue.Empty:
                return
            try:
                if self.native_q is not None:
                    payload = pickle.dumps(("ok", self._fetch(indices, numpy_only=True)), protocol=4)
                    self.native_q.push(payload)
                else:
                    self.queue.put(("ok", self._fetch(indices)))
            except Exception as e:  # surface worker errors like the reference
                if self.native_q is not None:
                    self.native_q.push(pickle.dumps(("err", e), protocol=4))
                else:
                    self.queue.put(("err", e))

    def __iter__(self):
        return self

    def __next__(self):
        if self.num_workers == 0:
            indices = next(self.batch_sampler_iter)
            batch = self._fetch(indices)
        else:
            if self.n_received >= self.n_pending:
                raise StopIteration
            if self.native_q is not None:
                import pickle

                raw = self.native_q.pop()
                if raw is None:
                    raise StopIteration
                kind, payload = pickle.loads(raw)
                self.n_received += 1
                if kind == "err":
                    raise payload
                batch = _tree_to_tensor(payload)
            else:
                kind, payload = self.queue.get()
                self.n_received += 1
                if kind == "err":
                    raise payload
                batch = payload
        if self.loader.return_list and isinstance(batch, (list, tuple)):
            return list(batch)
        return batch

    def skip_next(self):
        """Advance one batch WITHOUT loading its samples — the stability
        sentinel's quarantine skip stays at the INDEX level on the
        synchronous path (the dataset is never read). Worker paths have
        already prefetched the batch, so it is fetched and discarded (order
        preserved either way). Raises StopIteration at epoch end like
        ``__next__``."""
        if self.num_workers == 0:
            next(self.batch_sampler_iter)
            return
        next(self)


class _IterableIter:
    def __init__(self, loader):
        self.loader = loader
        self.it = iter(loader.dataset)
        self.collate_fn = loader.collate_fn or default_collate_fn
        self.batch_size = loader.batch_size
        # resume fast-forward: an IterableDataset has no index space, so the
        # skip must CONSUME skipped samples (map-style loaders skip at the
        # index level instead)
        skip, loader._resume_skip = loader._resume_skip, 0
        if skip and self.batch_size:
            next(itertools.islice(self.it, skip * self.batch_size - 1,
                                  skip * self.batch_size), None)
        loader._batch_idx = skip

    def __iter__(self):
        return self

    def __next__(self):
        if self.batch_size is None:
            return next(self.it)
        batch = list(itertools.islice(self.it, self.batch_size))
        if not batch:
            raise StopIteration
        if self.loader.drop_last and len(batch) < self.batch_size:
            raise StopIteration
        return self.collate_fn(batch)


class DevicePrefetcher:
    """Device-side input double-buffering (async runtime tentpole; reference
    ``operators/reader/buffered_reader.cc`` async device prefetch).

    The host-side pipeline above stages batches in HOST memory; the step
    still paid the host→device transfer synchronously when it consumed one.
    This stage closes that gap: a daemon thread pulls batches from ``it``,
    issues ``jax.device_put`` for every array leaf — committed to
    ``sharding(i, arr)`` when the training engine provides one — and keeps up
    to ``buffer_size`` device-resident batches staged, so batch k+1's
    transfer overlaps step k's execution (PJRT H2D is async; the thread also
    hides the host-side copy/conversion cost).

    ``sharding`` is ``None`` (default device placement), a fixed jax sharding
    applied to every leaf, or a callable ``(leaf_index, array) -> sharding``
    (what ``HybridParallelEngine.prefetch`` passes so batches land already
    committed to the step's GSPMD layout — the engine's own ``device_put``
    then becomes a no-op).

    Ordering is preserved; a worker exception is re-raised at the consumer's
    ``next()``; ``close()`` (also called on exhaustion and by ``__del__``)
    tears the thread down without draining the source.
    """

    _DONE = object()

    def __init__(self, it, buffer_size=2, sharding=None):
        import jax

        self._jax = jax
        self._source = it
        self._it = iter(it)
        self._sharding = sharding
        # batches handed to the trainer (NOT read-ahead) — the sample-exact-
        # resume anchor, boxed so FLAGS_thread_checks can pin its mutations
        # to the single consumer thread (a second thread iterating the same
        # prefetcher would silently skew resume positions)
        from ..analysis.thread_checks import owned as _owned

        self._consumed = _owned([0], "DevicePrefetcher._consumed")
        self._q: _queue.Queue = _queue.Queue(maxsize=max(1, int(buffer_size)))
        self._stop = threading.Event()
        # The worker must NOT hold a strong ref to self (a bound-method
        # target would): an abandoned prefetcher (early `break`) could then
        # never be collected, so __del__->close() would never fire and the
        # thread would spin in the put-retry loop forever. It gets a weakref
        # plus its own refs to the queue/stop/iterator instead.
        self._thread = threading.Thread(
            target=DevicePrefetcher._loop,
            args=(weakref.ref(self), self._it, self._q, self._stop),
            daemon=True,
            name="device-prefetch",
        )
        self._thread.start()

    # -- producer ----------------------------------------------------------
    def _place(self, i, arr):
        sh = self._sharding(i, arr) if callable(self._sharding) else self._sharding
        return (
            self._jax.device_put(arr, sh)
            if sh is not None
            else self._jax.device_put(arr)
        )

    def _transfer(self, obj, i=None):
        """Move every array leaf to device. ``i`` is the top-level position
        (the engine's per-input sharding index); nested leaves inherit it."""
        if isinstance(obj, Tensor):
            d = obj._data
            from ..core import lazy as lazy_mod

            t = Tensor(self._place(i, lazy_mod.concrete(d)), stop_gradient=obj.stop_gradient)
            return t
        if isinstance(obj, np.ndarray):
            return Tensor(self._place(i, obj))
        if isinstance(obj, (list, tuple)):
            staged = [
                self._transfer(o, idx if i is None else i)
                for idx, o in enumerate(obj)
            ]
            # namedtuples (custom collate_fns return them) need star-args
            if hasattr(obj, "_fields"):
                return type(obj)(*staged)
            return type(obj)(staged)
        if isinstance(obj, dict):
            return {k: self._transfer(v, i) for k, v in obj.items()}
        return obj

    @staticmethod
    def _loop(wref, it, q, stop):
        from .. import profiler

        while not stop.is_set():
            try:
                batch = next(it)
            except StopIteration:
                q.put((DevicePrefetcher._DONE, None))
                return
            except Exception as e:
                q.put(("err", e))
                return
            owner = wref()
            if owner is None:
                return
            try:
                staged = owner._transfer(batch)
                profiler.counter_inc("io_device_prefetched")
            except Exception as e:
                q.put(("err", e))
                return
            finally:
                del owner  # don't pin the prefetcher while blocked below
            # bounded staging: blocks while `buffer_size` batches are already
            # device-resident, with a timeout so close() (or the owner being
            # garbage-collected) can interrupt
            while not stop.is_set():
                try:
                    q.put(("ok", staged), timeout=0.1)
                    break
                except _queue.Full:
                    if wref() is None:
                        return

    # -- consumer ----------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._stop.is_set():
            raise StopIteration
        kind, payload = self._q.get()
        if kind is self._DONE:
            self.close()
            raise StopIteration
        if kind == "err":
            self.close()
            raise payload
        self._consumed[0] += 1
        return payload

    # -- sample-exact resume ------------------------------------------------
    def _epoch_iter(self):
        """The _StatefulIter under this prefetcher, if the source is (or
        yields) one — the object that can translate a consumed-count into a
        loader position."""
        for cand in (self._it, self._source):
            if isinstance(cand, _StatefulIter):
                return cand
        return None

    def state_dict(self) -> dict:
        """Loader position as of the last batch the TRAINER consumed. The
        underlying loader's own counter runs ahead by the staged read-ahead;
        this corrects for it, so a checkpoint taken mid-prefetch resumes at
        the right batch."""
        ei = self._epoch_iter()
        if ei is None:
            raise TypeError(
                "DevicePrefetcher.state_dict: source iterator does not track "
                "loader position (wrap a DataLoader, not a bare iterable)"
            )
        return ei.state_at(self._consumed[0])

    def load_state_dict(self, sd: dict) -> None:
        """Rebind to the source loader's restored position. Tears down the
        current read-ahead (those staged batches belong to the pre-restore
        position) and restarts prefetch from the fast-forwarded iterator."""
        loader = getattr(self._epoch_iter() or self._source, "loader", None)
        if loader is None or not callable(getattr(loader, "load_state_dict", None)):
            raise TypeError(
                "DevicePrefetcher.load_state_dict: no underlying DataLoader "
                "to restore into"
            )
        self.close()
        loader.load_state_dict(sd)
        # fresh box: the restore may hand consumption to a new trainer
        # thread, which becomes the owner on its first batch
        from ..analysis.thread_checks import owned as _owned

        self._consumed = _owned([0], "DevicePrefetcher._consumed")
        # rebind to the position-tracking iterator DIRECTLY: iter(loader) on
        # a device_prefetch>0 loader would return a nested prefetcher whose
        # worker starts staging batches immediately — adopting its inner
        # iterator after the fact drops whatever it already staged
        make = getattr(loader, "_stateful_iter", None)
        self._it = make() if callable(make) else iter(loader)
        if isinstance(self._it, DevicePrefetcher):
            # foreign loader whose __iter__ returns its own prefetcher:
            # tear it down before adopting (staged batches are discarded —
            # better than two racing prefetch threads on one iterator)
            inner = self._it
            self._it = inner._it
            inner.close()
        self._source = self._it
        self._q = _queue.Queue(maxsize=self._q.maxsize)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=DevicePrefetcher._loop,
            args=(weakref.ref(self), self._it, self._q, self._stop),
            daemon=True,
            name="device-prefetch",
        )
        self._thread.start()

    set_state_dict = load_state_dict

    def close(self):
        """Stop the prefetch thread (idempotent). Staged batches are
        discarded; the underlying iterator is NOT drained."""
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()  # unblock a producer stuck on put()
        except _queue.Empty:
            pass
        t = getattr(self, "_thread", None)
        if t is not None and t.is_alive() and t is not threading.current_thread():
            t.join(timeout=2.0)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def device_prefetch(it, buffer_size=2, sharding=None):
    """Functional wrapper: ``for batch in device_prefetch(loader): ...``"""
    return DevicePrefetcher(it, buffer_size=buffer_size, sharding=sharding)


class _StatefulIter:
    """Epoch iterator that keeps the owning loader's ``(epoch, batch_idx)``
    position current as batches are handed out — the bookkeeping behind
    ``DataLoader.state_dict`` (sample-exact resume). Exhaustion rolls the
    loader to the next epoch at batch 0."""

    def __init__(self, loader, inner, start_batch_idx):
        self.loader = loader
        self.inner = inner
        self._start_epoch = loader._epoch
        self._start_idx = int(start_batch_idx)
        self._produced = 0

    def __iter__(self):
        return self

    def __next__(self):
        try:
            batch = next(self.inner)
        except StopIteration:
            self.loader._epoch += 1
            self.loader._batch_idx = 0
            raise
        self._produced += 1
        self.loader._batch_idx = self._start_idx + self._produced
        return batch

    def skip_batch(self) -> bool:
        """Advance past the next batch without training on it (quarantine
        skip) — index-level when the inner iterator supports it. Position
        bookkeeping advances exactly like a consumed batch, so sample-exact
        resume state stays aligned with the uninterrupted order. Returns
        False when the epoch is already exhausted (rolling the loader to the
        next epoch like ``__next__`` does)."""
        skip = getattr(self.inner, "skip_next", None)
        try:
            if skip is not None:
                skip()
            else:
                next(self.inner)
        except StopIteration:
            self.loader._epoch += 1
            self.loader._batch_idx = 0
            return False
        self._produced += 1
        self.loader._batch_idx = self._start_idx + self._produced
        from .. import profiler

        profiler.counter_inc("io_quarantine_skips")
        return True

    def state_at(self, consumed: int) -> dict:
        """Loader position as of ``consumed`` batches handed out by THIS
        epoch iterator — what DevicePrefetcher reports, because its read-
        ahead makes the loader's own counter run early."""
        seed = self.loader.seed
        return {
            "epoch": self._start_epoch,
            "batch_idx": self._start_idx + int(consumed),
            "seed": -1 if seed is None else int(seed),
        }


class DataLoader:
    def __init__(
        self,
        dataset,
        feed_list=None,
        places=None,
        return_list=True,
        batch_sampler=None,
        batch_size=1,
        shuffle=False,
        drop_last=False,
        collate_fn=None,
        num_workers=0,
        use_buffer_reader=True,
        prefetch_factor=2,
        use_shared_memory=True,
        timeout=0,
        worker_init_fn=None,
        persistent_workers=False,
        use_multiprocess=None,
        device_prefetch=0,
        seed=None,
    ):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_buffer_reader = use_buffer_reader
        # device-side double-buffering: N batches staged ON DEVICE ahead of
        # the consumer (0 = off). Training engines wrap the loader with a
        # sharding-aware DevicePrefetcher instead (engine.prefetch()).
        self.device_prefetch = int(device_prefetch or 0)
        # worker PROCESSES (reference default: GIL-free preprocessing via
        # dataloader_iter.py:326 fork+shared-memory); False → thread workers.
        # use_multiprocess overrides explicitly; otherwise follow
        # use_shared_memory for reference-signature compatibility.
        if use_multiprocess is None:
            use_multiprocess = use_shared_memory
        self.use_multiprocess = use_multiprocess
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        # sample-exact resume: with an explicit seed the shuffle is a pure
        # function of (seed, epoch), so state_dict/load_state_dict replays
        # bit-identical batch order. seed=None keeps the legacy global-RNG
        # shuffle (positions still tracked, order not reproducible).
        self.seed = None if seed is None else int(seed)
        self._epoch = 0
        self._batch_idx = 0
        self._resume_skip = 0
        if isinstance(dataset, IterableDataset):
            self.batch_sampler = None
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            sampler = (
                _SeededRandomSampler(dataset, self.seed, lambda: self._epoch)
                if (shuffle and self.seed is not None) else None
            )
            self.batch_sampler = BatchSampler(
                dataset, sampler=sampler, shuffle=shuffle,
                batch_size=batch_size, drop_last=drop_last,
            )

    # -- sample-exact resume ------------------------------------------------
    def state_dict(self) -> dict:
        """Loader position: ``(epoch, batch_idx, seed)``. Save it alongside
        the model/optimizer tree; a reloaded loader fast-forwards to the same
        batch — bit-identical order when the loader was built with ``seed``.
        When iterating through a DevicePrefetcher, use ITS ``state_dict()``
        (read-ahead means the loader's counter runs early)."""
        return {
            "epoch": int(self._epoch),
            "batch_idx": int(self._batch_idx),
            # -1 = no seed (legacy global-RNG shuffle) — kept numeric so the
            # record survives array-normalizing checkpoint trees
            "seed": -1 if self.seed is None else int(self.seed),
        }

    def load_state_dict(self, sd: dict) -> None:
        self._epoch = int(sd.get("epoch", 0))
        self._batch_idx = 0
        self._resume_skip = int(sd.get("batch_idx", 0))
        saved_seed = sd.get("seed")
        if saved_seed is not None:
            saved_seed = int(saved_seed)
            if saved_seed < 0:
                saved_seed = None
        if saved_seed is not None and saved_seed != self.seed:
            import warnings

            warnings.warn(
                f"DataLoader.load_state_dict: checkpoint seed {saved_seed} "
                f"differs from configured seed {self.seed}; adopting the "
                "checkpoint's so the replayed order matches the saved run"
            )
            self.seed = int(saved_seed)
            if isinstance(self.batch_sampler, BatchSampler):
                cur = getattr(self.batch_sampler, "sampler", None)
                if isinstance(cur, _SeededRandomSampler):
                    cur.seed = int(saved_seed)
                elif self.shuffle and isinstance(cur, RandomSampler):
                    # loader was built WITHOUT a seed (global-RNG shuffle):
                    # adopting the checkpoint's seed must also install the
                    # seeded sampler, or the promise above is a lie — the
                    # permutation would still come from ambient RNG state
                    self.batch_sampler.sampler = _SeededRandomSampler(
                        self.dataset, int(saved_seed), lambda: self._epoch
                    )

    # checkpoint-tree participation: distributed/checkpoint.py restores
    # state_dict-bearing objects through set_state_dict
    set_state_dict = load_state_dict

    def batch_indices(self, epoch: int, batch_idx: int):
        """Sample indices of batch ``batch_idx`` in ``epoch`` — the
        stability sentinel's quarantine log names the exact samples of a
        condemned batch with this. Reconstructable only when the batch order
        is a pure function of ``(seed, epoch)`` (seeded shuffle, or no
        shuffle); returns None otherwise (the log then records the position
        only). O(batch_idx) — called on quarantine events, not per step."""
        if self.batch_sampler is None:
            return None
        sampler = getattr(self.batch_sampler, "sampler", None)
        if self.shuffle and not isinstance(sampler, _SeededRandomSampler):
            return None
        saved = self._epoch
        self._epoch = int(epoch)  # _SeededRandomSampler reads via epoch_fn
        try:
            for i, idxs in enumerate(self.batch_sampler):
                if i == int(batch_idx):
                    return [int(x) for x in idxs]
        finally:
            self._epoch = saved
        return None

    def _index_iter(self):
        """Index-batch stream for this epoch, with the resume fast-forward
        applied at the INDEX level — skipped batches are never loaded."""
        it = iter(self.batch_sampler)
        skip, self._resume_skip = self._resume_skip, 0
        for _ in range(skip):
            next(it, None)
        self._batch_idx = skip
        return it, skip

    def _stateful_iter(self):
        """This epoch's position-tracking iterator WITHOUT the device-
        prefetch wrap — what DevicePrefetcher.load_state_dict rebinds to (a
        nested prefetcher would start staging batches before it could be
        adopted, silently dropping them)."""
        if isinstance(self.dataset, IterableDataset):
            it = _IterableIter(self)
            skip = self._batch_idx
        elif self.num_workers > 0 and self.use_multiprocess:
            import multiprocessing as mp

            if "fork" in mp.get_all_start_methods():
                it = _MultiprocessIter(self)
            else:
                it = _DataLoaderIter(self)
            skip = self._batch_idx
        else:
            it = _DataLoaderIter(self)
            skip = self._batch_idx
        return _StatefulIter(self, it, skip)

    def __iter__(self):
        stateful = self._stateful_iter()
        if self.device_prefetch > 0:
            return DevicePrefetcher(stateful, buffer_size=self.device_prefetch)
        return stateful

    def __len__(self):
        if self.batch_sampler is None:
            raise TypeError("IterableDataset has no length")
        return len(self.batch_sampler)


def get_worker_info():
    return None


def data_home():
    """Dataset cache root (reference paddle.dataset.common.DATA_HOME)."""
    import os

    return os.environ.get(
        "PADDLE_TPU_DATA_HOME", os.path.expanduser("~/.cache/paddle_tpu/datasets")
    )


# Variable-length batching (SURVEY §7 hard part (c)) — imported at the end:
# ragged.py imports Sampler from this module.
from .ragged import BucketSampler, bucket_boundaries, pad_to_bucket_collate  # noqa: E402,F401
