"""Variable-length batching for a static-shape compiler — SURVEY §7 hard
part (c).

The reference carries ragged data through the graph as LoD tensors
(``paddle/fluid/framework/lod_tensor.h``) with 6.6k LoC of
``operators/sequence_ops/`` consuming the offsets. XLA shapes are static, so
the TPU-native policy QUANTIZES lengths instead: sequence lengths map to a
small fixed set of bucket boundaries, every batch holds sequences of one
bucket padded to its boundary, and the compile count is bounded by the
number of buckets (the documented recompile budget). Masks — not offsets —
carry the ragged structure through attention and loss (ignore_index /
attention masks), which XLA fuses for free.
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np

from . import Sampler

__all__ = ["BucketSampler", "bucket_boundaries", "pad_to_bucket_collate"]


def bucket_boundaries(lengths, num_buckets: int = 8, multiple: int = 8):
    """Pick bucket boundaries from observed lengths: length quantiles rounded
    UP to a multiple (default 8 — TPU lane alignment), deduplicated. The
    last boundary always covers max(lengths)."""
    lengths = np.asarray(lengths)
    qs = np.quantile(lengths, np.linspace(0, 1, num_buckets + 1)[1:])
    bounds = sorted({int(-(-int(np.ceil(q)) // multiple) * multiple) for q in qs})
    top = int(-(-int(lengths.max()) // multiple) * multiple)
    if not bounds or bounds[-1] < top:
        bounds.append(top)
    return bounds


def _bucket_of(length: int, bounds: Sequence[int]) -> int:
    for i, b in enumerate(bounds):
        if length <= b:
            return i
    return len(bounds) - 1


class BucketSampler(Sampler):
    """Batch sampler that groups indices into length buckets; every yielded
    batch pads to ONE boundary, so a jitted step sees at most
    ``len(boundaries)`` distinct shapes (executables).

    ``lengths``: per-index sequence lengths (array, list, or callable
    ``idx -> len``). Reference capability: LoD batching + the bucketed
    readers of the PS data pipeline; design constraint is XLA's static
    shapes, hence quantized-not-dynamic.
    """

    def __init__(self, lengths, batch_size: int, boundaries: Optional[Sequence[int]] = None,
                 num_buckets: int = 8, shuffle: bool = False, drop_last: bool = False,
                 seed: int = 0, data_source=None):
        if callable(lengths):
            if data_source is None:
                raise ValueError("callable lengths needs data_source for its range")
            lengths = [lengths(i) for i in range(len(data_source))]
        self.lengths = np.asarray(lengths, np.int64)
        self.batch_size = int(batch_size)
        self.boundaries = list(boundaries) if boundaries is not None else bucket_boundaries(
            self.lengths, num_buckets
        )
        if self.lengths.max(initial=0) > self.boundaries[-1]:
            raise ValueError(
                f"max length {int(self.lengths.max())} exceeds last boundary "
                f"{self.boundaries[-1]}"
            )
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.epoch = 0

    def __iter__(self):
        order = np.arange(len(self.lengths))
        rng = None
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self.epoch)
            rng.shuffle(order)
            self.epoch += 1
        buckets: dict = {i: [] for i in range(len(self.boundaries))}
        batches = []
        for idx in order:
            b = _bucket_of(int(self.lengths[idx]), self.boundaries)
            buckets[b].append(int(idx))
            if len(buckets[b]) == self.batch_size:
                batches.append(buckets[b])
                buckets[b] = []
        if not self.drop_last:
            for b, rest in buckets.items():
                if rest:
                    batches.append(rest)
        if self.shuffle:
            rng.shuffle(batches)
        return iter(batches)

    def __len__(self):
        counts: dict = {}
        for L in self.lengths:
            b = _bucket_of(int(L), self.boundaries)
            counts[b] = counts.get(b, 0) + 1
        if self.drop_last:
            return sum(c // self.batch_size for c in counts.values())
        return sum(-(-c // self.batch_size) for c in counts.values())


def pad_to_bucket_collate(boundaries: Sequence[int], pad_value=0,
                          label_pad_value=-100, returns_label: bool = False):
    """Collate building padded batches whose width is the smallest boundary
    covering the batch (consistent with BucketSampler's grouping, so the two
    stay decoupled). Samples are 1-D id arrays, or (ids, label) pairs when
    ``returns_label`` — labels pad with ``ignore_index`` (-100) so the
    standard CE loss masks padding with no extra plumbing.

    Returns (padded, lengths) or (padded, labels, lengths)."""
    bounds = list(boundaries)

    def collate(batch):
        if returns_label:
            seqs = [np.asarray(s[0]) for s in batch]
            labels = [np.asarray(s[1]) for s in batch]
        else:
            seqs = [np.asarray(s) for s in batch]
            labels = None
        maxlen = max(s.shape[0] for s in seqs)
        width = bounds[_bucket_of(maxlen, bounds)]
        lengths = np.asarray([s.shape[0] for s in seqs], np.int64)
        out = np.full((len(seqs), width), pad_value, seqs[0].dtype)
        for i, s in enumerate(seqs):
            out[i, : s.shape[0]] = s
        if labels is None:
            return out, lengths
        lab = np.full((len(labels), width), label_pad_value,
                      np.asarray(labels[0]).dtype)
        for i, l in enumerate(labels):
            lab[i, : l.shape[0]] = l
        return out, lab, lengths

    return collate
