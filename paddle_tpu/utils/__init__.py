"""paddle.utils — dlpack interop, cpp_extension stand-in, misc helpers.

Parity: reference ``python/paddle/utils/`` (dlpack.py over
``framework/dlpack_tensor.cc``; cpp_extension builds C++ custom ops).
"""
from __future__ import annotations

from . import dlpack  # noqa: F401

try:  # optional alias: unique_name lives in framework in the reference
    from ..framework import flags as _flags  # noqa: F401
except ImportError:
    pass

__all__ = ["dlpack"]
