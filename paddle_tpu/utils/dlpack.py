"""DLPack interop.

Parity: reference ``python/paddle/utils/dlpack.py`` over
``paddle/fluid/framework/dlpack_tensor.cc``. Zero-copy where the platform
supports the DLPack protocol (CPU/GPU); on TPU the buffer is not exportable
(PJRT restriction), so a host copy is made — semantics preserved, zero-copy
is not.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import as_tensor
from ..core.tensor import Tensor


class _HostDLPackWrapper:
    """Carries a host copy that supports __dlpack__ (fallback path)."""

    def __init__(self, arr: np.ndarray):
        self._arr = np.ascontiguousarray(arr)

    def __dlpack__(self, stream=None):
        return self._arr.__dlpack__()

    def __dlpack_device__(self):
        return self._arr.__dlpack_device__()


def to_dlpack(x):
    """Tensor -> DLPack-capable capsule holder (consume with
    torch.from_dlpack / np.from_dlpack / jnp.from_dlpack)."""
    t = as_tensor(x)
    arr = t._data
    try:
        arr.__dlpack_device__()
        return arr  # jax.Array implements the DLPack protocol directly
    except Exception:
        return _HostDLPackWrapper(np.asarray(arr))


def from_dlpack(dlpack):
    """DLPack capsule / protocol object -> Tensor."""
    try:
        arr = jnp.from_dlpack(dlpack)
    except Exception:
        arr = jnp.asarray(np.from_dlpack(dlpack))
    return Tensor(arr, stop_gradient=True)


__all__ = ["to_dlpack", "from_dlpack"]
