"""paddle_tpu.serving — production inference: continuous batching + paged KV
cache over the compiled decode programs in ``models/generation.py``.

Front door::

    from paddle_tpu.serving import Engine

    with Engine(model, block_size=16, num_blocks=512, max_batch=64) as eng:
        h = eng.submit(prompt_ids, max_new_tokens=64, eos_token_id=eos,
                       stream=True)
        for tok in h:          # streaming
            ...
        ids = h.result()       # or blocking; h.cancel() mid-stream

Resilience layer (round 12): ``submit(deadline_s=, priority=)`` attaches
per-request deadlines (shed with ``DeadlineExceeded`` when expired/doomed)
and admission/eviction priorities; ``FLAGS_serve_max_queue`` +
``FLAGS_serve_shed`` turn overload into fast-fail ``Overloaded`` with a
``retry_after_s`` hint; ``ServingSupervisor`` detects a crashed/wedged
engine loop within ``FLAGS_serve_watchdog_s`` and restarts it with greedy
in-flight work requeued bit-identically; ``health()``/``ready()`` +
``close(drain=True)`` support rolling restarts.

Serving state durability (round 17): live serving state is a first-class
durable object — ``PagePool.snapshot()/restore()`` capture/rebuild the
allocator with full validation, ``Engine.snapshot()/adopt()`` carry the
whole engine (KV arrays, block tables, prefix chain) across a restart so a
supervised crash with ``FLAGS_serve_snapshot`` RE-ATTACHES survivors with
zero re-prefilled tokens, and ``Engine.handoff()`` quiesces + exports
everything for a successor engine (zero-downtime upgrade). A capture that
fails validation is a structured ``SnapshotError`` and recovery falls back
to re-prefill — never a wrong-KV serve.

SLO observability (round 20, ``serving/observe.py``): ``FLAGS_serve_trace``
gives every request a trace id that survives preemption, crash recovery,
snapshot re-attach and engine handoff, and collects one timeline per
request (exportable as chrome-trace/JSONL) plus TTFT / inter-token /
end-to-end / queue-wait histograms keyed by priority class and
predicted-vs-actual drift gauges for the engine's three cost models;
``FLAGS_serve_metrics_port`` serves ``/metrics``, ``/healthz``,
``/readyz`` and ``/debug/requests`` over stdlib HTTP. Both default off —
the flag-off scheduler never imports the module (``from paddle_tpu.serving
import observe`` explicitly when driving it by hand).

See serving/engine.py for the scheduler, serving/pool.py for the paged KV
block allocator, serving/int8.py for the weight-only int8 path,
serving/supervisor.py for crash/wedge recovery, and the README "Serving"
section for bucketing, backpressure, deadline/shedding and supervision
semantics.
"""
from .engine import (  # noqa: F401
    DeadlineExceeded, Engine, EngineConfig, Overloaded, Readiness,
    RequestCancelled, RequestHandle, ServeError,
)
from .pool import PagePool, SnapshotError, TRASH_BLOCK  # noqa: F401
from .supervisor import ServingSupervisor  # noqa: F401

__all__ = [
    "Engine", "EngineConfig", "RequestHandle", "ServeError",
    "RequestCancelled", "DeadlineExceeded", "Overloaded", "Readiness",
    "ServingSupervisor", "PagePool", "SnapshotError", "TRASH_BLOCK",
]
