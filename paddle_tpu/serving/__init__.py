"""paddle_tpu.serving — production inference: continuous batching + paged KV
cache over the compiled decode programs in ``models/generation.py``.

Front door::

    from paddle_tpu.serving import Engine

    with Engine(model, block_size=16, num_blocks=512, max_batch=64) as eng:
        h = eng.submit(prompt_ids, max_new_tokens=64, eos_token_id=eos,
                       stream=True)
        for tok in h:          # streaming
            ...
        ids = h.result()       # or blocking; h.cancel() mid-stream

See serving/engine.py for the scheduler, serving/pool.py for the paged KV
block allocator, serving/int8.py for the weight-only int8 path, and the
README "Serving" section for bucketing, backpressure and cancellation
semantics.
"""
from .engine import (  # noqa: F401
    Engine, EngineConfig, RequestCancelled, RequestHandle, ServeError,
)
from .pool import PagePool, TRASH_BLOCK  # noqa: F401

__all__ = [
    "Engine", "EngineConfig", "RequestHandle", "ServeError",
    "RequestCancelled", "PagePool", "TRASH_BLOCK",
]
