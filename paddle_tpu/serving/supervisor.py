"""Serving supervision — bounded-time crash/wedge detection + engine restart.

PR 8 gave training the production failure stance: progress heartbeats, a
deadline watchdog, chaos injection, coordinated recovery. The serving engine
needs the same discipline — a crashed or wedged scheduler thread must not
strand every client handle, and a restart must not change a single greedy
token. :class:`ServingSupervisor` wraps an :class:`~.engine.Engine` with:

* **liveness probes** — the scheduler thread heartbeats (``Engine._beat``)
  every loop iteration and before every potentially-long compiled-program
  call; a supervised engine also publishes ``serve.step`` phase records
  through the PR 8 watchdog progress table (``distributed/watchdog.py``
  ``publish(unit=...)``), so cross-rank post-mortems carry serving progress
  next to training progress;
* **bounded-time detection** — a monitor thread (the supervisor's ONLY
  thread; unsupervised engines keep the PR 11 zero-extra-thread profile)
  watches thread aliveness and heartbeat staleness and declares the engine
  failed within ``FLAGS_serve_watchdog_s``: a *crash* (the loop raised — the
  engine kicks the monitor immediately via ``_failed``) or a *wedge* (thread
  alive, heartbeat stale past 3/4 of the watchdog deadline);
* **recovery** — a fresh Engine over the same model/pool config. After a
  CRASH the dead loop's state is frozen and safe to adopt: queued requests
  and in-flight sequences are **requeued**, mid-decode sequences continuing
  from their accumulated tokens through the engine's existing re-prefill
  path — greedy outputs stay **bit-identical** to an uninterrupted run
  (sampled continuations are valid but re-seeded). With
  ``FLAGS_serve_snapshot`` (or ``snapshot=True``) the crash path goes
  further: the dead engine's frozen serving state is captured whole
  (``Engine.snapshot`` — pool bookkeeping, KV arrays, block tables, prefix
  chain) and the replacement **re-attaches** it (``Engine.adopt``) —
  survivors resume mid-decode with ZERO re-prefilled tokens, still
  bit-identical; a capture that fails validation (``SnapshotError``) falls
  back to the requeue path above, so recovery is never worse than PR 12.
  After a WEDGE the
  abandoned thread may still own its sequences, so in-flight work **fails**
  with a structured ``ServeError`` (never a hang) while queued requests —
  untouched by the wedged loop — are requeued (a live wedged thread could
  tear a capture, so the snapshot path is crash-only). ``max_restarts``
  exhaustion fails everything and marks the supervisor broken;
* **probes + drain** — ``health()``/``ready()`` aggregate engine liveness
  with supervisor state for rolling-restart orchestration;
  ``close(drain=True)`` stops admission and completes outstanding work
  before stopping (the engine's drain mode).

Chaos coverage: ``serve.crash`` / ``serve.wedge`` / ``serve.slow_step`` /
``serve.pool_corrupt`` / ``serve.snapshot_corrupt`` (fault/inject.py) drive
the recovery paths in tests/test_serving_chaos.py; the tier-1 pins live in
tests/test_serving_resilience.py and tests/test_serving_snapshot.py.
"""
from __future__ import annotations

import copy
import itertools
import queue as _queue
import threading
import time
import weakref
from typing import List, Optional, Tuple

from ..framework import flags
from ..profiler import counter_inc, flight
from ..profiler.spans import span
from .engine import (
    DeadlineExceeded, Engine, Readiness, RequestHandle, ServeError,
    SnapshotError, _finish,
)

__all__ = ["ServingSupervisor"]

_sup_ids = itertools.count(1)


def _drain_stream(req, inner) -> None:
    """Forward the continuation's streamed tokens into the original
    request's stream queue (skipping the inner sentinel — the original's is
    sent by its own ``_finish``)."""
    if req.stream_q is None or inner.stream_q is None:
        return
    while True:
        try:
            item = inner.stream_q.get_nowait()
        except _queue.Empty:
            return
        if item is not None:
            req.stream_q.put(item)


def _relay_many(pairs) -> None:
    """ONE relay thread per recovery (not per request — a crash harvested
    with hundreds of queued requests must not burst hundreds of threads):
    a polling multiplexer that forwards each continuation's stream tokens
    and terminal state into the client's ORIGINAL request, and propagates
    late cancels (the engine that would have drained them is gone). A
    continuation caught by a SECOND crash resolves through the next
    recovery's relay — this loop just keeps waiting on its done event."""
    pending = list(pairs)
    while pending:
        still = []
        for req, handle in pending:
            inner = handle._req
            if req.cancelled and not inner.cancelled:
                handle.cancel()
            _drain_stream(req, inner)
            if inner.done.is_set():
                # the sentinel lands BEFORE done.set(): one more drain
                # cannot miss tokens. count=False — the new engine already
                # counted the continuation's outcome; counting the original
                # too would double serve_retired/serve_failed per recovered
                # request (serve_relayed tracks these instead)
                _drain_stream(req, inner)
                if inner.error is not None:
                    _finish(req, error=inner.error, count=False)
                else:
                    _finish(req, tokens=inner.tokens, count=False)
                counter_inc("serve_relayed")
                if req.trace is not None:
                    # recovered-request timeline: the relay is the last hop
                    from . import observe as _observe

                    _observe.on_relay(
                        req, len(inner.tokens or ()),
                        None if inner.error is None
                        else type(inner.error).__name__)
            else:
                still.append((req, handle))
        pending = still
        if pending:
            time.sleep(0.02)


def _monitor_loop(wr) -> None:
    """Monitor thread body. Weakref discipline (the engine-loop pattern): an
    abandoned supervisor stays GC-collectable — ``__del__`` closes it and
    the next deref here returns None, ending the thread."""
    while True:
        sup = wr()
        if sup is None or sup._stop.is_set():
            return
        with sup._lock:
            eng = sup._engine
            broken = sup._broken
        kind = err = None
        if eng is not None and broken is None and not eng._stop:
            if eng._failed.is_set() or not eng._thread.is_alive():
                kind = "crash"
                err = eng._broken or ServeError(
                    "serving engine scheduler thread exited unexpectedly")
            else:
                age = time.monotonic() - eng._beat
                # a first-call jit compile legitimately dwarfs a step: give
                # it 10x before declaring a wedge (a thread wedged INSIDE
                # the compile is still caught, just later)
                limit = sup._stale_s * (10.0 if eng._compiling else 1.0)
                if age > limit:
                    kind = "wedge"
                    err = ServeError(
                        f"serving engine scheduler thread wedged: heartbeat "
                        f"stale {age:.2f}s (watchdog {sup.watchdog_s}s"
                        + (", compile grace 10x exhausted)" if eng._compiling
                           else ")"))
        if kind is not None:
            try:
                sup._recover(eng, kind, err)
            except Exception as e:  # a failed recovery breaks the supervisor
                from ..fault import memory as _mem

                if _mem.is_oom(e):
                    # the respawn's pool allocation can itself exhaust HBM
                    _mem.note_oom("serve.respawn", e)
                with sup._lock:
                    if sup._broken is None:
                        sup._broken = e
                sup._fail_all(ServeError(f"serving recovery failed: {e!r}"))
            continue  # re-evaluate immediately against the fresh engine
        poll = sup._poll_s
        # wait on the crash kick only while it can still trigger a recovery:
        # after exhaustion (broken set) or a deliberate engine stop,
        # eng._failed stays set forever and waiting on it would busy-spin
        # this thread at 100% CPU until close()
        evt = (eng._failed if eng is not None and broken is None
               and not eng._stop and not eng._failed.is_set()
               else sup._stop)
        del sup, eng
        # a crash kick wakes us immediately; otherwise poll for staleness
        evt.wait(timeout=poll)


class ServingSupervisor:
    """Crash/wedge supervision over a serving :class:`Engine` (front-door
    compatible: ``submit``/``generate``/``stats`` delegate to the current
    engine and survive restarts)."""

    def __init__(self, model, config=None, max_restarts: int = 3,
                 watchdog_s: Optional[float] = None,
                 snapshot: Optional[bool] = None, **overrides):
        self.watchdog_s = float(
            watchdog_s if watchdog_s is not None
            else flags.flag("FLAGS_serve_watchdog_s", 10.0))
        # crash-recovery re-attach (serving state durability): resolved once
        # at construction — the unconfigured path never reaches the
        # snapshot/adopt code at all (inert tripwire)
        self._snapshot = bool(
            snapshot if snapshot is not None
            else flags.flag("FLAGS_serve_snapshot", False))
        if self.watchdog_s < 1.0:
            # the engine's idle loop only refreshes its heartbeat every
            # 0.5s (cv.wait timeout): a sub-second staleness threshold
            # would flag a perfectly idle engine as wedged
            raise ValueError("supervisor: watchdog_s must be >= 1.0")
        # detect within watchdog_s: staleness trips at 3/4 of the deadline,
        # the poll adds at most 1/5 — worst case ~0.95 * watchdog_s
        self._stale_s = 0.75 * self.watchdog_s
        self._poll_s = max(0.02, min(0.5, self.watchdog_s / 5.0))
        self.max_restarts = int(max_restarts)
        self._model = model
        self._config = config
        self._overrides = dict(overrides)
        self._t_start = time.monotonic()
        # telemetry endpoint (PR 20): the SUPERVISOR owns the port — probes
        # must survive engine restarts, and a replacement engine re-binding
        # the same port mid-recovery would race the dying one. Engines are
        # spawned with metrics_port=0 so they never bind their own.
        if config is not None:
            self._config = copy.copy(config)
            self._metrics_port = self._config.metrics_port
            self._config.metrics_port = 0
        else:
            self._metrics_port = self._overrides.pop("metrics_port", None)
            self._overrides["metrics_port"] = 0
        if self._metrics_port is None:
            self._metrics_port = flags.flag("FLAGS_serve_metrics_port", 0)
        self._metrics_port = int(self._metrics_port or 0)
        self._lock = threading.Lock()
        self._engine: Optional[Engine] = self._spawn()  # guarded_by: _lock
        self._restarts = 0                              # guarded_by: _lock
        self._broken: Optional[BaseException] = None    # guarded_by: _lock
        self._relays: List[threading.Thread] = []       # guarded_by: _lock
        # most recent recovery outcome for health() probes: mode is
        # "none" | "reattach" | "reprefill"
        self._last_recovery = {"mode": "none"}          # guarded_by: _lock
        self._stop = threading.Event()
        self._provider = f"serving_supervisor_{next(_sup_ids)}"
        wr = weakref.ref(self)
        flight.add_context_provider(
            self._provider,
            lambda _wr=wr: (
                s._flight_context() if (s := _wr()) is not None
                else {"closed": True}
            ),
        )
        self._monitor = threading.Thread(
            target=_monitor_loop, args=(wr,), daemon=True,
            name=self._provider)
        self._monitor.start()
        self._endpoint = None
        if self._metrics_port:
            from . import observe as _observe

            self._endpoint = _observe.start_endpoint(self, self._metrics_port)

    def _spawn(self) -> Engine:
        eng = Engine(self._model, config=self._config, **self._overrides)
        eng._supervised = True
        try:
            from ..distributed import watchdog as _wd

            eng._watchdog = _wd
        except Exception:  # lint: ok(oom-handler) — watchdog import guard, nothing dispatches in this try
            eng._watchdog = None
        return eng

    # ------------------------------------------------------------------ API
    def submit(self, prompt_ids, **kw) -> RequestHandle:
        """Front door (any thread): delegates to the current engine, waiting
        out a concurrent restart (bounded by ~2x the watchdog deadline)
        instead of surfacing the dead engine's ServeError. Structured
        rejections — Overloaded, DeadlineExceeded, validation — pass
        through untouched."""
        deadline = time.monotonic() + 2.0 * self.watchdog_s + 5.0
        while True:
            with self._lock:
                broken, eng = self._broken, self._engine
            if broken is not None or eng is None:
                raise ServeError(
                    "serving supervisor is broken") from broken
            try:
                return eng.submit(prompt_ids, **kw)
            except ServeError:
                if eng._broken is None and not eng._stop:
                    raise  # a real rejection (Overloaded/draining), not a death
                if self._stop.is_set() or time.monotonic() >= deadline:
                    raise
                time.sleep(self._poll_s)  # the monitor is swapping engines

    def generate(self, prompt_ids, **kw):
        return self.submit(prompt_ids, **kw).result()

    def stats(self) -> dict:
        with self._lock:
            eng, restarts = self._engine, self._restarts
        st = eng.stats() if eng is not None else {}
        st["restarts"] = restarts
        return st

    @property
    def restarts(self) -> int:
        with self._lock:
            return self._restarts

    def health(self) -> dict:
        """Engine liveness + supervisor state; ``ok`` requires both. The
        ``_engine`` read is under ``_lock``, so after a restart the probe's
        heartbeat/uptime fields are the REPLACEMENT engine's (its
        ``uptime_s`` restarts young; ``supervisor_uptime_s`` is the
        process-level monotonic clock)."""
        with self._lock:
            eng, restarts, broken = self._engine, self._restarts, self._broken
            last = dict(self._last_recovery)
        t_rec = last.pop("t", None)
        if t_rec is not None:
            last["age_s"] = round(time.monotonic() - t_rec, 3)
        h = eng.health() if eng is not None else {"ok": False}
        h.update(
            restarts=restarts,
            max_restarts=self.max_restarts,
            watchdog_s=self.watchdog_s,
            supervisor_ok=broken is None,
            supervisor_uptime_s=round(time.monotonic() - self._t_start, 3),
            # supervisor-level record wins over the engine's adopt()-local
            # view: it also covers requeue-only and wedge recoveries
            last_recovery=last,
        )
        h["ok"] = bool(h.get("ok") and broken is None)
        return h

    def ready(self) -> "Readiness":
        with self._lock:
            broken, eng = self._broken, self._engine
        sup_up = round(time.monotonic() - self._t_start, 3)
        if broken is not None or eng is None:
            return Readiness(ready=False, reason="supervisor_broken",
                             supervisor_uptime_s=sup_up)
        r = eng.ready()
        r["supervisor_uptime_s"] = sup_up
        return r

    def debug_requests(self) -> list:
        with self._lock:
            eng = self._engine
        return [] if eng is None else eng.debug_requests()

    def close(self, timeout: float = 30.0, drain: bool = False) -> None:
        """Stop monitoring, then the engine (``drain=True`` completes queued
        and running work first); outstanding recovery relays are joined.
        Idempotent."""
        self._stop.set()
        if self._monitor is not None \
                and self._monitor is not threading.current_thread():
            self._monitor.join(timeout=max(1.0, 2.0 * self._poll_s))
        # close every engine we can see — looped, because a recovery that
        # was mid-flight when _stop landed may still swap in a replacement
        # (its install path re-checks _stop, so this converges in <= 2)
        closed = set()
        while True:
            with self._lock:
                eng = self._engine
            if eng is None or id(eng) in closed:
                break
            closed.add(id(eng))
            eng.close(timeout=timeout, drain=drain)
        with self._lock:
            relays = list(self._relays)
        for t in relays:  # their continuation handles just failed/finished
            t.join(timeout=2.0)
        if self._endpoint is not None:
            self._endpoint.close()
            self._endpoint = None
        flight.remove_context_provider(self._provider)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close(timeout=2.0)
        except Exception:  # lint: ok(oom-handler) — teardown guard, nothing dispatches in this try
            pass

    # ------------------------------------------------------------- recovery
    def _recover(self, old: Engine, kind: str, err: BaseException) -> None:
        t_detect = time.monotonic()  # restart-MTTR clock starts at detection
        with self._lock:
            if self._engine is not old or self._stop.is_set():
                return  # stale detection: already recovered / closing
            exhausted = self._restarts >= self.max_restarts
            if exhausted:
                self._broken = ServeError(
                    f"serving supervisor: max_restarts={self.max_restarts} "
                    f"exhausted ({err})")
            else:
                self._restarts += 1
            restarts = self._restarts
        counter_inc("serve_wedge_detected" if kind == "wedge"
                    else "serve_crash_detected")
        # post-mortem BEFORE quarantining: the old engine's context provider
        # still reports its in-flight table
        try:
            flight.dump(
                f"serving_supervisor_{kind}",
                extra={"reason": str(err), "restarts": restarts,
                       "exhausted": exhausted},
            )
        except Exception:  # lint: ok(oom-handler) — flight-dump guard, nothing dispatches in this try
            pass
        # quarantine: a late-resuming BOUNDED wedge must exit at its next
        # loop check instead of double-driving a restarted request's stream
        old._broken = old._broken or err
        with old._cv:
            old._stop = True
        flight.remove_context_provider(old._provider)
        if old._watchdog is not None:
            try:  # the dead engine's progress-table unit goes with it
                old._watchdog.remove_unit(old._provider)
            except Exception:  # lint: ok(oom-handler) — store bookkeeping, nothing dispatches in this try
                pass
        # snapshot BEFORE the harvest empties the dead loop's lists: the
        # capture walks _running/_resume/_admitting. Crash-only — a live
        # wedged thread could tear it (Engine.snapshot refuses one anyway).
        # Any capture failure degrades to the requeue path, never breaks
        # the recovery itself.
        snap = None
        if self._snapshot and kind == "crash" and not exhausted:
            try:
                snap = old.snapshot()
            except Exception as e:
                from ..fault import memory as _mem

                if _mem.is_oom(e):
                    # the fingerprint reduction dispatches device work
                    _mem.note_oom("serve.snapshot", e)
                counter_inc("serve_snapshot_failed")
                snap = None
        work = self._harvest(old, kind, err)
        if exhausted:
            for req, _prefix, why in work:
                _finish(req, error=why or ServeError(
                    f"serving supervisor gave up after "
                    f"{self.max_restarts} restarts: {err}"))
            return
        with span("supervise_restart", kind=kind, restarts=restarts,
                  work=len(work), snapshot=snap is not None):
            try:
                info = self._restart(work, restarts, snap)
            except BaseException as e:
                # the harvest already emptied the old engine's lists, so
                # nothing else can ever finish these handles: a failed
                # restart (e.g. OOM respawning the engine) must fail them
                # here or clients block forever in result(). Done-guard
                # makes this a no-op for entries that already resolved.
                for req, _prefix, why in work:
                    _finish(req, error=why or ServeError(
                        f"serving engine restart failed: {e!r}"))
                raise  # the monitor records the supervisor as broken
        dur = time.monotonic() - t_detect
        counter_inc("serve_restart_mttr_ms", max(1, int(dur * 1000)))
        rec = {
            "mode": "none" if info is None
            else ("reattach" if info.get("adopted") else "reprefill"),
            "kind": kind,
            "reattached": 0 if info is None else info.get("reattached", 0),
            "blocks_reattached": (0 if info is None
                                  else info.get("blocks_reattached", 0)),
            "reprefill_tokens_saved": (
                0 if info is None else info.get("reprefill_tokens_saved", 0)),
            "requeued": 0 if info is None else info.get("requeued", 0),
            "duration_s": round(dur, 6),
            # monotonic stamp; health() reports it as age_s, never raw
            "t": time.monotonic(),
        }
        with self._lock:
            self._last_recovery = rec

    def _restart(self, work, restarts: int, snap=None):
        """Spawn + install the replacement. With a snapshot in hand, the
        replacement ADOPTS it first (strict — a ``SnapshotError`` falls back
        to requeue for everything): re-attached requests are live in the new
        engine under their ORIGINAL handles and need no relay; the rest go
        through the PR 12 requeue + relay machinery. Returns a recovery info
        dict (None when aborted by a racing close())."""
        new = self._spawn()
        installed: set = set()
        adopt_info = None
        if snap is not None:
            eligible = {req.id for req, _p, why in work if why is None}
            try:
                with span("serve_adopt_on_restart", restarts=restarts):
                    adopt_info = new.adopt(snap, only=eligible,
                                           fallback="raise")
                installed = set(adopt_info["installed"])
            except SnapshotError:
                adopt_info = None  # serve_snapshot_rejected counted in adopt
            except Exception as e:  # lint: ok(oom-handler) — classified below, fallback is the requeue path
                from ..fault import memory as _mem

                if _mem.is_oom(e):
                    _mem.note_oom("serve.adopt", e)
                counter_inc("serve_snapshot_failed")
                adopt_info = None
        with self._lock:
            # close() may have raced this recovery (it only waits ~1s
            # for the monitor): installing the replacement after close()
            # returned would leak a live scheduler thread past shutdown
            aborted = self._stop.is_set()
            if not aborted:
                self._engine = new
        if aborted:
            new.close(timeout=5.0)
            for req, _prefix, why in work:
                _finish(req, error=why or ServeError(
                    "serving supervisor closed during recovery"))
            return None
        counter_inc("serve_restarts")
        pairs = []
        requeued = 0
        for req, prefix, why in work:
            if why is not None:
                _finish(req, error=why)
            elif req.id in installed:
                continue  # re-attached: live in the new engine, original handle
            else:
                requeued += 1
                pair = self._requeue(new, req, prefix)
                if pair is not None:
                    pairs.append(pair)
        if pairs:
            t = threading.Thread(
                target=_relay_many, args=(pairs,), daemon=True,
                name=f"serve-relay-r{restarts}")
            with self._lock:
                self._relays = [r for r in self._relays
                                if r.is_alive()] + [t]
            t.start()
        info = dict(adopt_info or {})
        info["adopted"] = adopt_info is not None
        info["requeued"] = requeued
        return info

    def _harvest(self, old: Engine, kind: str,
                 err: BaseException) -> List[Tuple[object, Optional[list], Optional[BaseException]]]:
        """Adopt the failed engine's request state: ``(request,
        accumulated_tokens_or_None, fail_error_or_None)`` per pending
        request. A crash freezes the loop's state (the thread is dead), so
        everything requeues; a wedged thread may still hold its in-flight
        sequences, so those fail structurally while the untouched queue
        requeues."""
        with old._cv:
            queued = list(old._waiting)
            old._waiting.clear()
        seqs = list(old._admitting) + list(old._running) + list(old._resume)
        if kind == "crash":
            old._running, old._resume, old._admitting = [], [], []
        now = time.monotonic()
        work: List[Tuple[object, Optional[list], Optional[BaseException]]] = []
        # a crash inside _prefill leaves landed rows in BOTH _admitting and
        # _running (the same _Seq object) — dedup by request id or a stream
        # would get two relays pushing into one queue
        seen = set()
        for req in queued:
            if req.done.is_set() or req.id in seen:
                continue
            seen.add(req.id)
            if req.deadline is not None and now >= req.deadline:
                work.append((req, None, DeadlineExceeded(
                    f"request {req.id} deadline expired during engine "
                    f"recovery", request_id=req.id)))
            else:
                work.append((req, None, None))
        for s in seqs:
            req = s.req
            if req.done.is_set() or req.id in seen:
                continue
            seen.add(req.id)
            if kind == "wedge":
                work.append((req, None, ServeError(
                    f"request {req.id} lost: engine scheduler thread wedged "
                    f"mid-flight ({s.generated}/{req.max_new_tokens} "
                    f"generated)")))
            elif req.deadline is not None and now >= req.deadline:
                work.append((req, None, DeadlineExceeded(
                    f"request {req.id} deadline expired during engine "
                    f"recovery", request_id=req.id)))
            else:
                work.append((req, list(s.tokens), None))
        return work

    def _requeue(self, new: Engine, req, prefix: Optional[list]):
        """Resubmit one harvested request on the fresh engine, returning the
        ``(original_request, continuation_handle)`` pair for the recovery's
        relay (or None when it resolved inline). ``prefix`` is the
        accumulated ``prompt + generated`` token list of a mid-flight
        sequence — submitted as the continuation prompt, it re-prefills
        exactly like the engine's own preemption path, so greedy decode
        continues bit-identically; the relay stitches the continuation back
        into the client's original handle."""
        prompt = list(prefix) if prefix is not None else list(req.prompt)
        generated = len(prompt) - len(req.prompt)
        remaining = req.max_new_tokens - generated
        # a crash DURING retirement (e.g. a corrupt-pool free) can harvest a
        # sequence that already finished its work — its tokens ARE the
        # result, no continuation needed (and a continuation past an eos
        # would wrongly keep generating)
        gen = prompt[len(req.prompt):]
        if req.eos_token_id is not None and req.eos_token_id in gen:
            cut = len(req.prompt) + gen.index(req.eos_token_id) + 1
            _finish(req, tokens=prompt[:cut])
            return None
        if remaining < 1:
            _finish(req, tokens=prompt)
            return None
        dl = (None if req.deadline is None
              else max(1e-3, req.deadline - time.monotonic()))
        try:
            # _shed_exempt: the old engine already ACCEPTED this work — its
            # own recovery must not fast-fail it with Overloaded
            # _trace: the continuation inherits the original's trace id, so
            # the recovered request keeps ONE timeline across engines
            h = new.submit(prompt, max_new_tokens=remaining,
                           eos_token_id=req.eos_token_id,
                           temperature=req.temperature,
                           stream=req.stream_q is not None,
                           deadline_s=dl, priority=req.priority,
                           _shed_exempt=True, _trace=req.trace)
        except Exception as e:  # lint: ok(oom-handler) — submit() only enqueues; prefill dispatch happens on the engine thread
            _finish(req, error=e if isinstance(e, ServeError)
                    else ServeError(f"requeue after restart failed: {e!r}"))
            return None
        counter_inc("serve_requeued")
        if prefix is not None:
            # mid-flight survivor going through re-prefill: the tokens the
            # snapshot path would have saved (recovery-cost observability)
            counter_inc("serve_reprefill_tokens", len(prompt))
        return (req, h)

    def _fail_all(self, err: BaseException) -> None:
        with self._lock:
            eng = self._engine
        if eng is not None:
            eng._fail_outstanding(err)

    # -- flight-recorder context ----------------------------------------------
    def _flight_context(self) -> dict:
        with self._lock:
            eng, restarts, broken = self._engine, self._restarts, self._broken
        return {
            "restarts": restarts,
            "max_restarts": self.max_restarts,
            "watchdog_s": self.watchdog_s,
            "supervisor_ok": broken is None,
            "engine": None if eng is None else {
                "thread_alive": eng._thread.is_alive(),
                "beat_age_s": round(time.monotonic() - eng._beat, 3),
                "broken": repr(eng._broken) if eng._broken else None,
            },
        }
