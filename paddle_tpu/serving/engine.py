"""Serving engine — continuous batching + paged KV cache over compiled decode.

The inference stack's Predictor serves one fixed-shape request at a time;
real traffic is many concurrent autoregressive streams of ragged lengths.
This engine is the production front door over the scheduler-drivable decode
programs in ``models/generation.py``:

* **async request queue + continuous batching** — ``submit()`` enqueues from
  any thread; a dedicated engine thread admits and retires sequences EVERY
  decode step (a finished stream's slot is refilled next step, not at the
  end of a static batch), so batch occupancy tracks offered load;
* **bucketed batch shapes** — prompts prefill in length buckets (powers of
  two in block units) at a fixed prefill batch width, decode runs at the
  smallest power-of-two batch width covering the live set; each bucket jits
  ONCE per engine (``serve_compiles``) and warm executables reuse the
  persistent compilation cache across processes (PR 1);
* **paged KV cache** — fixed-size KV blocks in a preallocated pool, a
  per-sequence block table, gather-based paged attention reads
  (``build_paged_decode``), so HBM holds ``Σ ceil(len/block)`` blocks
  instead of ``B × T_max`` dense caches. Pool exhaustion is backpressure:
  admission stalls the queue, and a running sequence that can't grow evicts
  the youngest peer (freed blocks, state requeued for re-prefill from its
  accumulated tokens) rather than failing anything;
* **prefill/decode phase separation** — prompt prefill is a dense causal
  pass batched by length bucket; decode is one packed batch with per-row
  positions and live masks;
* **int8 serving** (``int8=True``) — weight-only int8 via the PTQ rounding
  (serving/int8.py), dequantized inside the compiled programs;
* **deadlines, priorities, load shedding** (resilience layer) —
  ``submit(deadline_s=, priority=)`` attaches a completion deadline and an
  admission/eviction priority to a request. The scheduler sheds expired and
  doomed requests at admission and at every step boundary (a queued request
  that cannot meet its deadline even if admitted now — prefill + full token
  budget at the measured decode-step EMA — fails early with a structured
  :class:`DeadlineExceeded` instead of occupying the batch), eviction under
  pool pressure is priority-then-youngest, and the overload policy
  (``FLAGS_serve_max_queue`` + ``FLAGS_serve_shed``) turns unbounded queue
  growth into fast-fail :class:`Overloaded` with a Retry-After-style
  ``retry_after_s`` hint. None of it costs anything unconfigured: the sweep
  is gated on a has-deadlines bool, priority selection on a has-priorities
  bool, the shed check is two attribute probes — zero threads, zero host
  syncs (pinned by the inert tripwire in tests/test_serving_resilience.py);
* **liveness + drain** — the scheduler thread heartbeats every loop
  iteration (``health()``/``ready()`` probes read it; a ServingSupervisor
  monitors it), and ``close(drain=True)`` stops admission, completes queued
  and running work, then stops — the graceful-rolling-restart half of the
  supervisor's crash/wedge recovery (serving/supervisor.py).

* **HBM pressure** (fault/memory.py) — a ``RESOURCE_EXHAUSTED`` inside a
  serving step is classified and answered by PARKING free KV blocks
  (``PagePool.park`` — admission headroom shrinks, continuous batching
  backs off to a smaller resident working set) and retrying on the next
  scheduler iteration: the PR 11 invariant "pool exhaustion is never a
  crash" extends to HBM exhaustion — streams complete late under
  backpressure. Training-side pressure reaches live engines through
  ``request_pool_shrink`` (the registered ``free_pressure`` handler), and a
  shrink-proof OOM streak falls through to the crash-containment path so
  clients are never hung.

Every scheduler action is a profiler span (``admit``/``schedule``/
``prefill``/``decode_step``/``page_alloc``/``evict``) with ``serve_*``
counters, and the engine registers a flight-recorder context provider so
crash dumps carry the in-flight request table. Chaos points ``serve.crash``
/ ``serve.wedge`` / ``serve.slow_step`` / ``serve.pool_corrupt`` /
``hbm.oom`` / ``hbm.pressure`` (fault/inject.py) fire at the scheduler
step boundary when armed; ``serve.snapshot_corrupt`` tears a state capture
inside :meth:`Engine.snapshot` so adoption must fall back.

Serving state durability (snapshot/adopt/handoff): the engine's whole live
state — page pool bookkeeping, KV pool arrays, per-sequence block tables,
and the prefix-cache chain — is capturable at a step boundary
(:meth:`Engine.snapshot`), adoptable by a fresh engine
(:meth:`Engine.adopt`: survivors resume mid-decode with ZERO re-prefilled
tokens; a capture that fails validation falls back whole to re-prefill
through the preemption/resume machinery), and transferable end-to-end by
:meth:`Engine.handoff` (quiesce → export snapshot + queue + in-flight
handles → successor adopts) — the zero-downtime restart/upgrade primitive.
"""
from __future__ import annotations

import collections
import copy
import itertools
import queue as _queue
import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..fault import inject as _inject
from ..framework import flags
from ..profiler import counter_inc, flight
from ..profiler.spans import span, update_attrs
from .pool import PagePool, SnapshotError, TRASH_BLOCK

__all__ = [
    "Engine", "EngineConfig", "RequestHandle", "Readiness", "ServeError",
    "RequestCancelled", "DeadlineExceeded", "Overloaded", "SnapshotError",
]

SNAPSHOT_VERSION = 1  # engine-level snapshot format (pool has its own)

_engine_ids = itertools.count(1)


class ServeError(RuntimeError):
    pass


class RequestCancelled(ServeError):
    pass


class DeadlineExceeded(ServeError):
    """The request's ``deadline_s`` passed (or provably cannot be met) before
    completion — shed by the scheduler at admission or a step boundary."""

    def __init__(self, msg: str, request_id: Optional[int] = None):
        super().__init__(msg)
        self.request_id = request_id


class Overloaded(ServeError):
    """Fast-fail load shed: the submission queue hit ``FLAGS_serve_max_queue``
    with ``FLAGS_serve_shed`` armed. ``retry_after_s`` is the Retry-After-style
    backoff hint (estimated time for one queue slot to drain)."""

    def __init__(self, msg: str, retry_after_s: float = 0.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class Readiness(dict):
    """``ready()`` payload: a JSON-able dict (the ``/readyz`` body) whose
    truth value is the ready bit itself, so ``if eng.ready():`` call sites
    keep their boolean semantics."""

    def __bool__(self) -> bool:
        return bool(self.get("ready"))


class EngineConfig:
    """Serving knobs. ``None`` fields resolve from the ``FLAGS_serve_*``
    registry at engine construction, so fleet-wide defaults are one
    ``set_flags`` away while tests override per-engine."""

    def __init__(self, block_size=None, num_blocks=None, max_batch=None,
                 max_seq_len=None, prefill_batch=None, int8=None,
                 decode_buckets=None, seed=0, max_queue=None, shed=None,
                 prefix_cache=None, spec_k=None, drafter=None,
                 draft_window=None, tp=None, prefill_chunk=None,
                 tp_int8=None, trace=None, metrics_port=None):
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.max_batch = max_batch
        self.max_seq_len = max_seq_len
        self.prefill_batch = prefill_batch
        self.int8 = int8
        self.decode_buckets = decode_buckets
        self.seed = seed
        self.max_queue = max_queue
        self.shed = shed
        # throughput multipliers (PR 16): prefix-cache KV sharing and
        # speculative decoding. ``drafter`` is "ngram" or a small
        # same-family model instance (same tokenizer/vocab as the target).
        self.prefix_cache = prefix_cache
        self.spec_k = spec_k
        self.drafter = drafter
        self.draft_window = draft_window
        # mesh-native serving (PR 19): tensor-parallel degree, chunked
        # prefill grain, and EQuARX-style int8 tp collectives
        self.tp = tp
        self.prefill_chunk = prefill_chunk
        self.tp_int8 = tp_int8
        # SLO observability (PR 20): request tracing + token-latency
        # histograms + cost-drift gauges, and the opt-in telemetry endpoint
        # (0 = no HTTP thread)
        self.trace = trace
        self.metrics_port = metrics_port

    def resolve(self, model_max_positions: int) -> "EngineConfig":
        def pick(v, name):
            # explicit 0 must reach validation, not silently fall back
            return int(v if v is not None else flags.flag(name))

        self.block_size = pick(self.block_size, "FLAGS_serve_block_size")
        self.num_blocks = pick(self.num_blocks, "FLAGS_serve_num_blocks")
        self.max_batch = pick(self.max_batch, "FLAGS_serve_max_batch")
        self.prefill_batch = pick(self.prefill_batch, "FLAGS_serve_prefill_batch")
        max_seq = pick(self.max_seq_len, "FLAGS_serve_max_seq_len")
        self.max_seq_len = min(max_seq, int(model_max_positions))
        if self.int8 is None:
            self.int8 = bool(flags.flag("FLAGS_serve_int8", False))
        # 0 is the meaningful default here (unbounded queue), so only None
        # falls back to the flag
        self.max_queue = int(self.max_queue if self.max_queue is not None
                             else flags.flag("FLAGS_serve_max_queue", 0))
        if self.shed is None:
            self.shed = bool(flags.flag("FLAGS_serve_shed", False))
        if self.prefix_cache is None:
            self.prefix_cache = bool(flags.flag("FLAGS_serve_prefix_cache",
                                                False))
        self.spec_k = int(self.spec_k if self.spec_k is not None
                          else flags.flag("FLAGS_serve_spec_k", 0))
        if self.drafter is None:
            self.drafter = flags.flag("FLAGS_serve_drafter", "ngram")
        self.draft_window = int(self.draft_window
                                if self.draft_window is not None
                                else flags.flag("FLAGS_serve_draft_window", 64))
        self.tp = pick(self.tp, "FLAGS_serve_tp")
        self.prefill_chunk = pick(self.prefill_chunk,
                                  "FLAGS_serve_prefill_chunk")
        if self.tp_int8 is None:
            self.tp_int8 = bool(flags.flag("FLAGS_serve_tp_int8", False))
        if self.trace is None:
            self.trace = bool(flags.flag("FLAGS_serve_trace", False))
        self.metrics_port = int(
            self.metrics_port if self.metrics_port is not None
            else flags.flag("FLAGS_serve_metrics_port", 0))
        if self.metrics_port < 0:
            raise ValueError("serving: metrics_port must be >= 0 (0 = off)")
        if self.tp < 0:
            raise ValueError("serving: tp must be >= 0 (0/1 = single-chip)")
        if self.prefill_chunk < 0:
            raise ValueError("serving: prefill_chunk must be >= 0 "
                             "(0 = monolithic prefill)")
        if self.prefill_chunk and self.block_size \
                and self.prefill_chunk % self.block_size:
            raise ValueError(
                "serving: prefill_chunk must be a multiple of block_size "
                "(chunk boundaries write K/V through the paged scatter)")
        if self.tp >= 2 and (self.spec_k or 0) > 0:
            raise ValueError(
                "serving: speculative decoding is not yet supported under "
                "tensor-parallel serving (set spec_k=0 or tp<=1)")
        if self.spec_k < 0:
            raise ValueError("serving: spec_k must be >= 0")
        if self.spec_k and self.draft_window < 2:
            raise ValueError("serving: draft_window must be >= 2")
        if self.block_size < 1 or self.num_blocks < 2 or self.max_batch < 1 \
                or self.prefill_batch < 1 or self.max_seq_len < 1:
            raise ValueError(
                "serving: block_size/max_batch/prefill_batch/max_seq_len "
                ">= 1 and num_blocks >= 2 required"
            )
        if self.max_queue < 0:
            raise ValueError("serving: max_queue must be >= 0 (0 = unbounded)")
        if self.decode_buckets is None:
            b, buckets = 1, []
            while b < self.max_batch:
                buckets.append(b)
                b *= 2
            self.decode_buckets = tuple(buckets) + (self.max_batch,)
        else:
            # drop widths past the ceiling, keep ascending order, and make
            # sure max_batch itself is present so every live set has a bucket
            kept = sorted({int(b) for b in self.decode_buckets
                           if 0 < int(b) <= self.max_batch})
            if not kept or kept[-1] != self.max_batch:
                kept.append(self.max_batch)
            self.decode_buckets = tuple(kept)
        return self


class _Request:
    __slots__ = (
        "id", "prompt", "max_new_tokens", "eos_token_id", "temperature",
        "tokens", "error", "done", "stream_q", "cancelled",
        "t_submit", "t_done", "priority", "deadline",
        # SLO observability (PR 20): the trace id rides the request object
        # itself, so snapshot/harvest/handoff records (which carry requests
        # whole) preserve it across recovery with no extra plumbing
        "trace", "t_submit_ns", "t_first_tok", "t_last_tok",
    )

    def __init__(self, rid, prompt, max_new_tokens, eos_token_id, temperature,
                 stream, priority=0, deadline=None):
        self.id = rid
        self.prompt = prompt  # list[int]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.temperature = float(temperature)
        self.tokens: Optional[List[int]] = None  # final ids, set at finish
        self.error: Optional[BaseException] = None
        self.done = threading.Event()
        self.stream_q = _queue.Queue() if stream else None
        self.cancelled = False
        self.t_submit = time.monotonic()
        self.t_done: Optional[float] = None
        self.priority = int(priority)           # higher = more important
        self.deadline = deadline                # absolute monotonic, or None
        self.trace: Optional[str] = None        # set by observe.on_submit
        self.t_submit_ns = 0                    # span-clock submit stamp
        self.t_first_tok = 0.0                  # first-token wall time (TTFT)
        self.t_last_tok = 0.0                   # last-token wall time (gaps)


def _finish(req: _Request, tokens=None, error=None, count=True) -> bool:
    """Terminal state for a request: result lands, the stream closes, the
    handle's waiters wake. Returns False when the request was already
    finished — crash sweeps, supervisor relays, and the scheduler may race,
    and first-writer-wins keeps that benign. Shared with the
    ServingSupervisor, which finishes ORPHANED requests (their engine is
    dead) without an Engine instance in hand. ``count=False`` skips the
    lifecycle counters: a relay completing the ORIGINAL of a requeued
    request would otherwise double-count the continuation the new engine
    already counted."""
    if req.done.is_set():
        return False
    req.tokens = list(tokens) if tokens is not None else None
    req.error = error
    req.t_done = time.monotonic()
    if count:
        counter_inc("serve_cancelled" if isinstance(error, RequestCancelled)
                    else "serve_failed" if error is not None
                    else "serve_retired")
    if req.stream_q is not None:
        req.stream_q.put(None)
    req.done.set()
    return True


def _ngram_propose(tokens, k: int, max_n: int = 3) -> List[int]:
    """Prompt-lookup drafting (the zero-model fallback drafter): find the
    most recent EARLIER occurrence of the longest suffix n-gram
    (n = max_n..1) and propose the up-to-k tokens that followed it. Returns
    [] when nothing recurs — the verify step then degenerates to plain
    decode for that row. O(len²) worst case on pathological prompts; real
    traffic hits in the first few candidates."""
    L = len(tokens)
    for n in range(min(max_n, L - 1), 0, -1):
        pat = tokens[L - n:]
        for i in range(L - n - 1, -1, -1):
            if tokens[i:i + n] == pat:
                fol = tokens[i + n:i + n + k]
                if fol:
                    return fol
    return []


class _PrefixCache:
    """Hash-keyed index of shared prompt-prefix KV blocks (engine-thread
    only, like the pool it feeds). Chained block-granularity hashes: block
    j's key is ``(parent block id, tuple of chunk-j tokens)`` — the parent
    link pins the exact content of everything before the chunk, so two
    different prefixes can never alias through a hash collision (dict
    hashing is a fast path, equality is exact). The index holds its OWN
    reference on every cached block (``PagePool.share``), so retirement of
    the inserting sequence leaves the KV resident for future admissions;
    :meth:`evict` drops LRU LEAF entries whose block nobody else maps
    (refcount 1) — pinned shared blocks and chain interiors are never
    evicted from under a reader."""

    __slots__ = ("_pool", "_bs", "_entries", "_by_bid", "_tick")

    def __init__(self, pool: PagePool, block_size: int):
        self._pool = pool
        self._bs = block_size
        # key -> [block id, last-use tick, cached-child count]
        self._entries: Dict[tuple, list] = {}
        self._by_bid: Dict[int, tuple] = {}  # reverse map for chain edits
        self._tick = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def blocks(self) -> int:
        """Pool blocks currently pinned by the index."""
        return len(self._entries)

    def match(self, tokens, limit: int) -> List[int]:
        """Longest cached chain over full block-size chunks of ``tokens``,
        capped at ``limit`` blocks. Returns block ids WITHOUT bumping
        refcounts — the caller shares them once the rest of admission is
        known to succeed."""
        self._tick += 1
        bids: List[int] = []
        parent = -1
        for j in range(limit):
            ent = self._entries.get(
                (parent, tuple(tokens[j * self._bs:(j + 1) * self._bs])))
            if ent is None:
                break
            ent[1] = self._tick
            bids.append(ent[0])
            parent = ent[0]
        return bids

    def insert(self, tokens, blocks, start: int, full: int) -> int:
        """Index ``blocks[start:full]`` of a freshly prefilled sequence
        (chunk j's chain parent is ``blocks[j-1]``, cached and fresh blocks
        alike). Stops at the first already-present key: that content is
        cached under a DIFFERENT block id, and chaining ours beside it
        would orphan the children. Takes one index-owned reference per
        inserted block."""
        inserted = 0
        for j in range(start, full):
            parent = -1 if j == 0 else blocks[j - 1]
            key = (parent, tuple(tokens[j * self._bs:(j + 1) * self._bs]))
            if key in self._entries:
                break
            self._pool.share([blocks[j]])
            self._tick += 1
            self._entries[key] = [blocks[j], self._tick, 0]
            self._by_bid[blocks[j]] = key
            pk = self._by_bid.get(parent)
            if pk is not None:
                self._entries[pk][2] += 1
            inserted += 1
        return inserted

    def evict(self, need: int) -> int:
        """Free up to ``need`` blocks by dropping LRU leaf entries whose
        block only the index maps; dropping a leaf may expose its parent as
        the next candidate. Returns blocks actually returned to the free
        list (0 when everything left is pinned)."""
        freed = 0
        while freed < need:
            leaves = [(ent[1], key) for key, ent in self._entries.items()
                      if ent[2] == 0 and self._pool.refcount(ent[0]) == 1]
            if not leaves:
                break
            freed += self._drop(min(leaves)[1])
        if freed:
            counter_inc("serve_prefix_evicted", freed)
        return freed

    def _drop(self, key) -> int:
        bid = self._entries.pop(key)[0]
        del self._by_bid[bid]
        pk = self._by_bid.get(key[0])
        if pk is not None:
            self._entries[pk][2] -= 1
        self._pool.free([bid])
        return 1

    def release_all(self) -> None:
        """Drop every index-owned reference (engine shutdown)."""
        bids = [ent[0] for ent in self._entries.values()]
        self._entries.clear()
        self._by_bid.clear()
        if bids:
            self._pool.free(bids)


class _Seq:
    """Scheduler-side state of one admitted sequence. ``tokens`` holds
    prompt + generated ids; the newest id's KV is NOT yet in cache — its
    write position is ``pos = len(tokens) - 1``, which is also the next
    decode step's fed token. ``cached_blocks`` counts the leading blocks
    admission matched from the prefix cache (shared, already filled — the
    prefill pass runs only the tail). ``chunk_pos`` is the chunked-prefill
    cursor: prompt tokens below it have K/V in cache (0 outside the chunked
    path, where the whole prompt lands in one prefill pass)."""

    __slots__ = ("req", "tokens", "blocks", "prompt_len", "cached_blocks",
                 "chunk_pos")

    def __init__(self, req: _Request, tokens: List[int]):
        self.req = req
        self.tokens = tokens
        self.blocks: List[int] = []
        self.prompt_len = len(req.prompt)
        self.cached_blocks = 0
        self.chunk_pos = 0

    @property
    def pos(self) -> int:
        return len(self.tokens) - 1

    @property
    def generated(self) -> int:
        return len(self.tokens) - self.prompt_len


class RequestHandle:
    """Client-side handle: blocking ``result()``, streaming iteration, and
    ``cancel()``."""

    def __init__(self, req: _Request, engine: "Engine"):
        self._req = req
        self._engine = engine

    @property
    def request_id(self) -> int:
        return self._req.id

    @property
    def done(self) -> bool:
        return self._req.done.is_set()

    @property
    def latency_s(self) -> Optional[float]:
        t = self._req.t_done
        return None if t is None else t - self._req.t_submit

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Full token ids (prompt + generated), like ``generate()``. Raises
        the request's failure (``RequestCancelled`` after ``cancel()``)."""
        if not self._req.done.wait(timeout):
            raise TimeoutError(f"request {self._req.id} still in flight")
        if self._req.error is not None:
            raise self._req.error
        return list(self._req.tokens)

    def cancel(self) -> None:
        self._engine._cancel(self._req)

    def __iter__(self):
        """Generated token ids as they land (``submit(stream=True)``). Ends
        cleanly on completion OR cancellation; terminal errors re-raise.
        One-shot: tokens are consumed destructively, and iterating a handle
        whose stream was already drained terminates instead of blocking."""
        if self._req.stream_q is None:
            raise ServeError("submit(stream=True) to iterate tokens")

        def finish():
            if self._req.error is not None and not isinstance(
                    self._req.error, RequestCancelled):
                raise self._req.error

        while True:
            try:
                # the timeout only matters on an already-drained stream
                # (sentinel consumed by a prior iteration); live streams
                # return as soon as a token lands
                item = self._req.stream_q.get(timeout=0.1)
            except _queue.Empty:
                if self._req.done.is_set() and self._req.stream_q.empty():
                    finish()
                    return
                continue
            if item is None:
                finish()
                return
            yield item


class Engine:
    """Continuous-batching serving engine over a paged KV cache.

    ``model`` is a ``GPTForPretraining`` or ``LlamaForCausalLM`` instance
    with full logical weights. The engine thread owns all scheduler state;
    only the submission queue and stop flag cross threads (guarded below).
    """

    def __init__(self, model, config: Optional[EngineConfig] = None, **overrides):
        import jax
        import jax.numpy as jnp

        from ..models import generation as G

        self._jax, self._jnp, self._G = jax, jnp, G
        if hasattr(model, "gpt"):
            arch_key, arch, params, max_pos = G.gpt_decode_state(model)
        elif hasattr(model, "lm_head") and hasattr(model, "model"):
            arch_key, arch, params, max_pos = G.llama_decode_state(model)
        else:
            raise TypeError(
                f"serving.Engine: unsupported model {type(model).__name__} "
                "(expected GPTForPretraining or LlamaForCausalLM)"
            )
        if config is not None and overrides:
            raise ValueError("pass EngineConfig OR keyword overrides, not both")
        # resolve a COPY: the caller's EngineConfig stays pristine (this
        # engine's model clamps max_seq_len, so a reused config must not
        # carry one model's clamp into the next engine)
        cfg = copy.copy(config or EngineConfig(**overrides)).resolve(max_pos)
        self.config = cfg
        self._arch = arch
        self._arch_key = arch_key
        self._dtype = params["wte"].dtype
        self._compute_params = params
        # tensor-parallel serving (PR 19): tp >= 2 shards heads / FFN
        # columns / the LM head / the KV pool over a "tp" mesh axis. 0/1
        # leaves every code path below byte-for-byte the single-chip one.
        self._tp = int(cfg.tp) if int(cfg.tp) >= 2 else 0
        self._tp_mesh = None
        self._tp_vocab = None
        if self._tp:
            ndev = len(jax.devices())
            if self._tp > ndev:
                raise ValueError(
                    f"serving: tp={self._tp} exceeds the {ndev} visible "
                    "devices")
            from jax.sharding import Mesh

            self._tp_mesh = Mesh(
                np.array(jax.devices()[:self._tp]), ("tp",))
            G.tp_validate(arch_key, params, self._tp)
        if cfg.int8:
            from .int8 import attach_int8_head, dequantize_tree, \
                quantize_params

            self._compute_params = quantize_params(params)
            if flags.flag("FLAGS_serve_int8_kernel", False):
                # keep the head's int8 bytes visible to the compiled step so
                # the decode head runs the weight-only int8_matmul kernel
                self._dequant = lambda p, _d=self._dtype: attach_int8_head(
                    dequantize_tree(p, _d), p)
            else:
                self._dequant = lambda p, _d=self._dtype: dequantize_tree(
                    p, _d)
        else:
            self._dequant = None
        if self._tp:
            # pack the (possibly int8-tagged) tree into per-device column
            # slices stacked on a leading tp axis; dequantization moves
            # INSIDE the shard_map body (per-tensor scales make
            # slice-then-dequantize bitwise dequantize-then-slice), so the
            # engine-side wrapper is retired. FLAGS_serve_int8_kernel is a
            # single-chip head fusion and is ignored under tp.
            packed, self._tp_vocab = G.tp_pack_params(
                arch_key, self._compute_params, self._tp)
            rep_s, shard_s = G.tp_param_shardings(self._tp_mesh)
            self._compute_params = {
                "rep": jax.device_put(packed["rep"], rep_s),
                "shard": jax.device_put(packed["shard"], shard_s),
            }
            self._dequant = None
        self._n_layers = len(params["layers"])
        kv, hd = arch["kv_heads"], arch["head_dim"]
        self._spec_k = int(cfg.spec_k)
        # speculative verify writes reach pos + spec_k: widen the block
        # tables so a real write can never clamp into the trash block
        self._max_blocks = -(-(cfg.max_seq_len + self._spec_k)
                             // cfg.block_size)
        shape = (self._n_layers, cfg.num_blocks, cfg.block_size, kv, hd)
        if self._tp:
            # KV pool sharded on the kv-heads axis: every device owns
            # heads/tp of EVERY block, so the replicated host-side block
            # tables / PagePool bookkeeping index all shards identically
            pool_s = G.tp_pool_sharding(self._tp_mesh)
            self._kpool = jax.device_put(jnp.zeros(shape, self._dtype),
                                         pool_s)
            self._vpool = jax.device_put(jnp.zeros(shape, self._dtype),
                                         pool_s)
        else:
            self._kpool = jnp.zeros(shape, self._dtype)
            self._vpool = jnp.zeros(shape, self._dtype)
        self._pool = PagePool(cfg.num_blocks)
        self._prefill_buckets = self._make_prefill_buckets()
        self._prefix = (_PrefixCache(self._pool, cfg.block_size)
                        if cfg.prefix_cache else None)
        # drafter: None when spec is off, True for the host-side n-gram
        # proposer, or (arch, params, window) for a small model drafter
        self._drafter = None
        if self._spec_k:
            d = cfg.drafter
            if d is None or d == "ngram":
                self._drafter = True
            elif isinstance(d, str):
                raise ValueError(f"serving: unknown drafter {d!r}")
            elif hasattr(d, "gpt"):
                _, darch, dparams, dmax = G.gpt_decode_state(d)
                self._drafter = (darch, dparams,
                                 max(2, min(cfg.draft_window,
                                            int(dmax) - self._spec_k)))
            elif hasattr(d, "lm_head") and hasattr(d, "model"):
                _, darch, dparams, dmax = G.llama_decode_state(d)
                self._drafter = (darch, dparams,
                                 max(2, min(cfg.draft_window,
                                            int(dmax) - self._spec_k)))
            else:
                raise TypeError(
                    f"serving: unsupported drafter {type(d).__name__}"
                )

        # engine-thread-only scheduler state
        self._fns: Dict[tuple, object] = {}
        # per-decode-bucket gather width (blocks), high-water, pow2-rounded
        self._decode_mb: Dict[int, int] = {}
        self._running: List[_Seq] = []
        self._resume: List[_Seq] = []  # preempted, awaiting re-prefill
        self._admitting: List[_Seq] = []  # popped off the queue, mid-prefill
        # chunked prefill (PR 19): seqs whose prompt is being prefilled one
        # FLAGS_serve_prefill_chunk-token chunk per scheduler step, so a
        # long admit no longer stalls the live decode batch for a whole
        # prefill. 0 = monolithic prefill, the exact prior path.
        self._chunk = int(cfg.prefill_chunk) if int(cfg.prefill_chunk) > 0 \
            else 0
        self._prefilling: List[_Seq] = []
        # analytic floor for the shed ETA while the decode EMA is cold: the
        # cost model's estimate of the per-step tp collective term (0.0 on
        # a single chip or when the backend is unknown to the model)
        self._step_floor_s = 0.0
        if self._tp:
            from ..cost_model import CostModel

            fp32_b, int8_b = G.tp_collective_bytes(
                arch_key, params, cfg.max_batch, self._tp)
            wire = int8_b if cfg.tp_int8 else fp32_b
            self._step_floor_s = CostModel().kernel_estimate(
                "tp_collective", (int(wire), int(self._tp)), {}) / 1e3
        self._key = jax.random.PRNGKey(cfg.seed)
        self._rng = np.random.default_rng(cfg.seed)
        self._step_i = 0
        self._occ_live = 0
        self._occ_slots = 0
        # resilience gauges (engine-thread writes, racy cross-thread reads by
        # design): decode service-time EMA (compile steps excluded — it feeds
        # deadline feasibility), completed-request latency EMA (Retry-After
        # hints), and the scheduler-thread heartbeat that health()/the
        # supervisor read
        self._ema_step_s = 0.0
        self._ema_req_s = 0.0
        self._beat = time.monotonic()
        # True while a FIRST-CALL compiled program is building (jit compile
        # can dwarf a step): the supervisor widens its staleness limit 10x
        # so a cold start is not misread as a wedge — a thread genuinely
        # wedged inside a compile is still caught, just later
        self._compiling = False
        # HBM pressure (fault/memory.py): cross-thread shrink request the
        # scheduler applies at its next step boundary (engine-thread-only
        # pool ownership holds; -1 = default fraction; guarded by _cv), the
        # consecutive OOM-step streak that bounds in-place recovery before
        # the crash containment path takes over, and the clean-step
        # countdown that gradually returns parked blocks once pressure
        # clears (a transient OOM must not ratchet capacity down forever)
        self._shrink_req = 0  # guarded_by: _cv
        self._oom_streak = 0
        self._unpark_countdown = 0
        # serving SLO observability (PR 20): when armed, `_obs` is the
        # serving.observe module and the scheduler tags spans / feeds the
        # token-latency histograms / records cost drift. Unconfigured, the
        # module is never even imported and every hook site is one
        # attribute-is-None probe (inert tripwire).
        self._t_start = time.monotonic()
        self._obs = None
        self._endpoint = None
        if cfg.trace:
            from . import observe as _observe

            _observe.trace_book()  # create + register the span observer
            self._obs = _observe

        # cross-thread state
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._waiting: "collections.deque[_Request]" = collections.deque()  # guarded_by: _cv
        self._stop = False  # guarded_by: _cv
        self._draining = False  # guarded_by: _cv
        # serving state durability: handoff() sets the request word and the
        # scheduler consumes it at its next step boundary (quiesce, then the
        # thread exits WITHOUT failing handles — the exported snapshot owns
        # them). The unconfigured path costs one bool probe per iteration
        # inside an already-held _cv block (inert tripwire). _last_recovery
        # is the most recent adopt() outcome for health() probes (written
        # once per adopt on the adopting thread, racy reads by design).
        self._handoff_req = False  # guarded_by: _cv
        self._quiesced = threading.Event()
        self._last_recovery: Optional[dict] = None
        self._broken: Optional[BaseException] = None
        self._ids = itertools.count(1)
        # once-true latches (set under _cv, read lock-free by the scheduler):
        # the deadline sweep and the priority admission scan run ONLY after a
        # deadline'd / prioritized request has ever been submitted — the
        # unconfigured path stays a flag probe (inert tripwire)
        self._deadline_seen = False
        self._has_prio = False
        # supervision hooks (set by ServingSupervisor; None/False = PR 11
        # behavior exactly): a supervised crash leaves scheduler state for
        # the supervisor to harvest instead of failing every handle, and the
        # loop publishes serve.step phase records into the PR 8 watchdog
        # progress table
        self._supervised = False
        self._watchdog = None
        self._failed = threading.Event()

        # Both the flight registry and the scheduler thread hold only a
        # weakref: an abandoned (never-closed) engine stays collectable —
        # __del__ then runs close(), the thread exits at its next deref,
        # and the provider reports itself gone (the DevicePrefetcher
        # teardown discipline from PR 6).
        self._provider = f"serving_{next(_engine_ids)}"
        wr = weakref.ref(self)
        flight.add_context_provider(
            self._provider,
            lambda _wr=wr: (
                e._flight_context() if (e := _wr()) is not None
                else {"closed": True}
            ),
        )
        # the serving rung of fault/memory.free_pressure: a training-side
        # OOM can ask every live engine to give HBM back (pool headroom
        # shrink → admission backpressure). Weakly bound — a collected
        # engine drops out of the registry by itself.
        from ..fault import memory as _fmem

        _fmem.register_pressure_handler(
            self._provider, lambda eng: eng.request_pool_shrink(), owner=self)
        self._thread = threading.Thread(
            target=_engine_loop, args=(wr,), daemon=True, name=self._provider)
        self._thread.start()
        # telemetry endpoint last: its handlers probe health()/stats() on a
        # fully-constructed engine. Holds only a weakref to the engine; a
        # failed bind is a counter, never a serving failure.
        if cfg.metrics_port:
            from . import observe as _observe

            self._endpoint = _observe.start_endpoint(self, cfg.metrics_port)

    # ------------------------------------------------------------------ API
    def submit(self, prompt_ids, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None, temperature: float = 0.0,
               stream: bool = False, deadline_s: Optional[float] = None,
               priority: int = 0, _shed_exempt: bool = False,
               _trace: Optional[str] = None) -> RequestHandle:
        """Enqueue one request (any thread). ``temperature == 0`` is greedy.
        ``stream=True`` additionally feeds the handle's iterator per token.
        ``deadline_s`` (seconds from now) attaches a completion deadline: the
        scheduler sheds the request with :class:`DeadlineExceeded` — raised
        from ``result()`` — once it expires or provably cannot finish in
        time. ``priority`` (higher = more important, default 0) orders
        admission and inverts eviction (priority-then-youngest). Under the
        shed policy (``max_queue`` + ``shed``) a full queue fast-fails this
        call with :class:`Overloaded` instead of queuing without bound —
        except for ``_shed_exempt`` submissions (supervisor-internal:
        requeued work the engine already accepted once must not be shed by
        its own recovery)."""
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if not prompt:
            raise ValueError("serving: empty prompt")
        if int(max_new_tokens) < 1:
            # prefill always yields the first generated token, so a 0-token
            # budget cannot honor the prompt+max_new output contract
            raise ValueError("serving: max_new_tokens must be >= 1")
        if deadline_s is not None and float(deadline_s) <= 0.0:
            raise ValueError("serving: deadline_s must be positive")
        total = len(prompt) + int(max_new_tokens)
        if total > self.config.max_seq_len:
            raise ValueError(
                f"serving: prompt + max_new_tokens = {total} exceeds "
                f"max_seq_len {self.config.max_seq_len}"
            )
        # spec verify maps up to spec_k write slots past the last token
        if -(-(total + self._spec_k) // self.config.block_size) \
                > self._pool.num_blocks - 1:
            raise ValueError(
                "serving: request needs more KV blocks than the whole pool; "
                "raise FLAGS_serve_num_blocks"
            )
        cfg = self.config
        with self._cv:
            if self._stop or self._broken is not None:
                raise ServeError("serving engine is closed") from self._broken
            if self._draining:
                raise ServeError(
                    "serving engine is draining (close(drain=True)); "
                    "submit to its replacement"
                )
            if cfg.shed and not _shed_exempt and cfg.max_queue > 0 \
                    and len(self._waiting) >= cfg.max_queue:
                counter_inc("serve_shed")
                hint = round(max(0.05, len(self._waiting)
                                 * (self._ema_req_s or 0.1) / cfg.max_batch), 3)
                raise Overloaded(
                    f"serving queue full ({len(self._waiting)} >= "
                    f"max_queue={cfg.max_queue}); retry after ~{hint}s",
                    retry_after_s=hint,
                )
            req = _Request(next(self._ids), prompt, max_new_tokens,
                           eos_token_id, temperature, stream,
                           priority=priority,
                           deadline=(time.monotonic() + float(deadline_s))
                           if deadline_s is not None else None)
            if req.deadline is not None:
                self._deadline_seen = True
            if req.priority != 0:
                self._has_prio = True
            if self._obs is not None:
                # assign (or, for a supervisor requeue carrying ``_trace``,
                # re-attach) the trace id before the scheduler can see the
                # request — the timeline must exist before its first event
                self._obs.on_submit(req, trace=_trace)
            self._waiting.append(req)
            counter_inc("serve_requests")
            self._cv.notify()
        return RequestHandle(req, self)

    def generate(self, prompt_ids, **kw) -> List[int]:
        """Synchronous convenience: submit + wait."""
        return self.submit(prompt_ids, **kw).result()

    def stats(self) -> dict:
        """Scheduler gauges (safe from any thread; running-set reads are
        racy snapshots by design)."""
        with self._lock:
            depth = len(self._waiting)
        occ = self._occ_live / self._occ_slots if self._occ_slots else 0.0
        return {
            "queue_depth": depth,
            "running": len(self._running),
            "preempted_waiting": len(self._resume),
            "batch_occupancy_mean": round(occ, 4),
            "pages_total": self._pool.num_blocks - 1,
            "pages_used": self._pool.used_blocks,
            "pages_free": self._pool.free_blocks,
            "pages_parked": self._pool.parked_blocks,
            "pages_cached": (self._prefix.blocks
                            if self._prefix is not None else 0),
            "compiles": len(self._fns),
            "decode_steps": self._step_i,
        }

    def debug_requests(self) -> List[dict]:
        """Live in-flight request table (``/debug/requests``): phase, age,
        blocks held, trace id — the flight-provider data on demand instead
        of only post-mortem. Any thread; racy snapshot by design, same
        contract as :meth:`stats`."""
        now = time.monotonic()
        with self._lock:
            waiting = list(self._waiting)
        rows = [{
            "id": req.id, "phase": "queued",
            "age_s": round(now - req.t_submit, 3),
            "priority": req.priority, "prompt_len": len(req.prompt),
            "generated": 0, "blocks": 0, "trace": req.trace,
        } for req in waiting]
        for phase, seqs in (("prefilling", self._admitting),
                            ("chunk_prefill", self._prefilling),
                            ("running", self._running),
                            ("preempted", self._resume)):
            for s in list(seqs):
                rows.append({
                    "id": s.req.id, "phase": phase,
                    "age_s": round(now - s.req.t_submit, 3),
                    "priority": s.req.priority, "prompt_len": s.prompt_len,
                    "generated": s.generated, "blocks": len(s.blocks),
                    "trace": s.req.trace,
                })
        return rows

    def health(self) -> dict:
        """Liveness probe (any thread): scheduler-thread aliveness, heartbeat
        age, and failure state. ``ok`` is the single bit an external monitor
        should alarm on; the rest is diagnosis."""
        alive = self._thread.is_alive()
        with self._lock:
            depth = len(self._waiting)
            draining = self._draining
            stopped = self._stop
        beat_age = time.monotonic() - self._beat
        # heartbeat staleness folds into ok: an alive-but-wedged scheduler
        # must flip the probe even without a supervisor. Same staleness
        # contract as the supervisor: watchdog_s, 10x while a first-call
        # compile runs
        thr = max(1.0, float(flags.flag("FLAGS_serve_watchdog_s", 10.0) or 10.0))
        stale = beat_age > thr * (10.0 if self._compiling else 1.0)
        # last adopt() outcome (reattach|reprefill), or mode "none": probes
        # distinguish a degraded (re-prefill) recovery from clean. The
        # internal monotonic stamp becomes an AGE — a probe scraping two
        # replicas must not compare raw monotonic clocks across processes.
        lr = (dict(self._last_recovery) if self._last_recovery
              else {"mode": "none"})
        t_rec = lr.pop("t", None)
        if t_rec is not None:
            lr["age_s"] = round(time.monotonic() - t_rec, 3)
        return {
            "ok": alive and self._broken is None and not stopped and not stale,
            "thread_alive": alive,
            "beat_age_s": round(beat_age, 3),
            "stale": stale,
            "broken": repr(self._broken) if self._broken is not None else None,
            "draining": draining,
            "queue_depth": depth,
            "running": len(self._running),
            "pages_free": self._pool.free_blocks,
            "uptime_s": round(time.monotonic() - self._t_start, 3),
            "last_recovery": lr,
        }

    def ready(self) -> "Readiness":
        """Readiness probe: accepting new submissions right now — healthy,
        not draining, and (under the shed policy) queue below the cap. The
        rolling-restart contract: flip a replica's traffic away when this
        goes False, then ``close(drain=True)`` it. Returns a
        :class:`Readiness` dict (the ``/readyz`` body) that is truthy
        exactly when ready."""
        h = self.health()
        ready, reason = True, None
        if not h["ok"]:
            ready, reason = False, "unhealthy"
        elif h["draining"]:
            ready, reason = False, "draining"
        else:
            cfg = self.config
            if cfg.shed and cfg.max_queue > 0 \
                    and h["queue_depth"] >= cfg.max_queue:
                ready, reason = False, "queue_full"
        return Readiness(ready=ready, reason=reason,
                         queue_depth=h["queue_depth"],
                         uptime_s=h["uptime_s"],
                         last_recovery=h["last_recovery"])

    def close(self, timeout: float = 30.0, drain: bool = False) -> None:
        """Stop the engine thread. Plain ``close()`` fails outstanding
        requests with ``ServeError``; ``close(drain=True)`` first stops
        admission (``submit`` raises, ``ready()`` goes False) and lets
        queued + running work complete within ``timeout`` — the graceful
        half of a rolling restart. A ``join`` that times out (wedged
        scheduler thread) marks the engine broken and fails every
        outstanding handle instead of returning with clients blocked
        forever in ``result()``. Idempotent."""
        deadline = time.monotonic() + max(0.0, float(timeout))
        on_sched_thread = threading.current_thread() is self._thread
        if drain:
            with self._cv:
                self._draining = True
                self._cv.notify()
            if not on_sched_thread:
                self._thread.join(max(0.0, deadline - time.monotonic()))
        with self._cv:
            self._stop = True
            self._cv.notify()
        # provider first: it must go even when the join below is skipped
        # (close() can run ON the scheduler thread — __del__ fires there
        # when the loop's deref holds the last reference); same for this
        # engine's watchdog unit record — stale units must not outlive it
        flight.remove_context_provider(self._provider)
        if self._endpoint is not None:
            self._endpoint.close()
            self._endpoint = None
        from ..fault import memory as _fmem

        _fmem.unregister_pressure_handler(self._provider)
        if self._watchdog is not None:
            try:
                self._watchdog.remove_unit(self._provider)
            except Exception:  # lint: ok(oom-handler) — store bookkeeping, nothing dispatches in this try
                pass
        if not on_sched_thread:
            # drain path: the drain join above may have consumed the whole
            # budget on legitimate work — give the post-stop join a real
            # floor (a healthy thread exits within ~one step of _stop), so
            # a merely-slow drain is not misdiagnosed as a wedged scheduler
            self._thread.join(max(2.0 if drain else 0.1,
                                  deadline - time.monotonic()))
            if self._thread.is_alive():
                counter_inc("serve_wedged_close")
                self._broken = self._broken or ServeError(
                    f"serving engine scheduler thread wedged: close() join "
                    f"timed out after {timeout}s"
                )
        # Wedged join, a supervised crash whose supervisor never harvested,
        # or __del__ firing on the scheduler thread all leave handles
        # pending — fail them (handle state only, no pool mutation: a
        # wedged thread may still own the pool). No-op on a clean shutdown.
        self._fail_outstanding(self._broken or ServeError("serving engine closed"))

    def _fail_outstanding(self, err: BaseException) -> None:
        """Fail every pending handle without touching the page pool — safe
        to run from any thread, idempotent per request via the done-guard
        in ``_finish``."""
        with self._cv:
            waiting = list(self._waiting)
            self._waiting.clear()
        seqs = list(self._running) + list(self._resume) \
            + list(self._admitting) + list(self._prefilling)
        for req in waiting + [s.req for s in seqs]:
            try:
                self._finish_request(req, error=ServeError(str(err)))
            except Exception:  # lint: ok(oom-handler) — handle-state sweep, nothing dispatches in this try
                pass

    # -- serving state durability: snapshot / adopt / handoff -----------------
    def _compat_key(self) -> tuple:
        """Shape/dtype fingerprint an adopted snapshot must match exactly —
        the KV pool arrays and block tables are only meaningful against the
        same paged-cache geometry."""
        cfg = self.config
        # tp degree + KV shard layout close a silent-corruption hole: a
        # tp=2 pool array is numerically identical gathered, but adopting
        # it onto a different mesh shape would re-shard live KV under the
        # replicated block tables — refuse instead (structured error,
        # re-prefill fallback)
        return (self._n_layers, int(cfg.num_blocks), int(cfg.block_size),
                int(self._arch["kv_heads"]), int(self._arch["head_dim"]),
                str(self._dtype), int(self._tp),
                "kv-shard/tp" if self._tp else "replicated")

    def snapshot(self) -> dict:
        """O(blocks) consistent capture of the live serving state: pool
        bookkeeping (with CRC), the KV pool arrays, every in-flight
        sequence's tokens + block table, the prefix-cache chain, and
        per-block KV content fingerprints.

        Caller contract: the scheduler must be quiesced (``handoff``) or
        dead (supervised crash — the loop's state is frozen) — a LIVE
        scheduler would tear the capture, so this refuses one. The capture
        shares the engine's immutable jnp arrays (cheap); on donating
        backends discard it after ``adopt`` — the successor's first step
        consumes the buffers."""
        if self._thread.is_alive() and not self._quiesced.is_set() \
                and not self._failed.is_set():
            raise ServeError(
                "snapshot requires a quiesced or dead scheduler thread "
                "(use handoff(), or capture after a supervised crash)")
        with span("serve_snapshot", step=self._step_i,
                  running=len(self._running)) as sp:
            pool_snap = self._pool.snapshot()
            seqs, seen = [], set()
            for phase, group in (("running", self._running),
                                 ("resume", self._resume),
                                 ("admitting", self._admitting),
                                 ("prefilling", self._prefilling)):
                for s in group:
                    if s.req.id in seen:
                        continue  # landed mid-prefill: the _running view wins
                    seen.add(s.req.id)
                    seqs.append({"phase": phase, "req": s.req,
                                 "tokens": list(s.tokens),
                                 "blocks": list(s.blocks),
                                 "prompt_len": s.prompt_len,
                                 "cached_blocks": s.cached_blocks})
            prefix = None
            if self._prefix is not None:
                prefix = {"entries": {k: list(v) for k, v
                                      in self._prefix._entries.items()},
                          "tick": self._prefix._tick}
            owned = sorted(self._pool._owned)
            sums = self._G.kv_block_checksums(self._kpool, self._vpool, owned)
            snap = {"version": SNAPSHOT_VERSION, "compat": self._compat_key(),
                    "pool": pool_snap, "kpool": self._kpool,
                    "vpool": self._vpool, "seqs": seqs, "prefix": prefix,
                    "step_i": self._step_i,
                    "fingerprint": {"bids": owned, "sums": sums}}
            if _inject.should_fire("serve.snapshot_corrupt"):
                # chaos: tear the pool capture mid-write — the CRC no longer
                # matches, and adopt()'s validation MUST reject it whole
                if pool_snap["free"]:
                    pool_snap["free"].pop()
                else:
                    pool_snap["ref"] = dict(pool_snap["ref"],
                                            **{TRASH_BLOCK: 1})
            sp.set(seqs=len(seqs), owned_blocks=len(owned))
            counter_inc("serve_snapshots")
            return snap

    def adopt(self, snap: dict, only=None, fallback: str = "reprefill"):
        """Adopt a :meth:`snapshot` into THIS (fresh, traffic-free) engine.

        Validation first, mutation after: compat key, pool restore
        (conservation + CRC), per-sequence block-table coverage, prefix
        chain bijection/acyclicity, exact refcount↔mapping agreement, and
        KV content fingerprints all must hold before any state is
        installed. On success the survivors' ORIGINAL request objects go
        straight into the running set — they resume mid-decode with zero
        re-prefilled tokens and their existing handles/streams keep
        working. A capture that fails validation raises
        :class:`SnapshotError` when ``fallback="raise"``; with the default
        ``fallback="reprefill"`` every in-flight record is re-admitted
        whole through the preemption/resume machinery instead (re-prefill
        from accumulated tokens — never worse than the PR 12 path).

        ``only`` (set of request ids, or None for all) filters which
        records are adopted; the rest have their block references released.
        Returns an info dict: ``mode`` (reattach|reprefill), ``installed``
        (request ids now owned by this engine), block/token counts, and
        ``duration_s``."""
        t0 = time.monotonic()
        with span("serve_adopt", seqs=len(snap.get("seqs", ()))) as sp:
            try:
                pool = self._validate_snapshot(snap)
                info = self._attach(snap, pool, only)
            except SnapshotError as e:
                counter_inc("serve_snapshot_rejected")
                if fallback != "reprefill":
                    raise
                info = self._adopt_reprefill(snap, only)
                info["reject_reason"] = str(e)
            info["duration_s"] = round(time.monotonic() - t0, 6)
            sp.set(mode=info["mode"])
        # stamped copy: health() turns "t" into an age; the caller's info
        # dict stays exactly the documented shape
        self._last_recovery = dict(info, t=time.monotonic())
        counter_inc("serve_adoptions")
        return info

    def _validate_snapshot(self, snap: dict) -> PagePool:
        """The extended check(): everything that must hold before adoption.
        Raises SnapshotError; never mutates engine state."""
        try:
            version = snap.get("version")
            compat = tuple(snap.get("compat", ()))
            seqs = snap["seqs"]
            prefix = snap.get("prefix")
            kpool, vpool = snap["kpool"], snap["vpool"]
            fp = snap["fingerprint"]
        except Exception as e:  # lint: ok(oom-handler) — dict probing, nothing dispatches in this try
            raise SnapshotError(f"malformed engine snapshot: {e!r}") from e
        if version != SNAPSHOT_VERSION:
            raise SnapshotError(
                f"engine snapshot version {version!r} != {SNAPSHOT_VERSION}")
        if compat != self._compat_key():
            raise SnapshotError(
                f"snapshot geometry {compat} does not match this engine's "
                f"{self._compat_key()} — cross-config adoption refused")
        if kpool.shape != self._kpool.shape or kpool.dtype != self._dtype \
                or vpool.shape != self._vpool.shape:
            raise SnapshotError("KV pool array shape/dtype mismatch")
        pool = PagePool.restore(snap["pool"])
        bs = self.config.block_size
        refs: Dict[int, int] = {}
        for rec in seqs:
            blocks, tokens = rec["blocks"], rec["tokens"]
            rid = rec["req"].id
            if not tokens or len(tokens) < rec["prompt_len"]:
                raise SnapshotError(f"seq {rid}: empty/short token record")
            for b in blocks:
                if b == TRASH_BLOCK or pool.refcount(b) < 1:
                    raise SnapshotError(
                        f"seq {rid} maps unowned block {b}")
                refs[b] = refs.get(b, 0) + 1
            if rec["phase"] == "running":
                # written KV covers positions [0, pos): the table must too
                if len(blocks) * bs < len(tokens) - 1:
                    raise SnapshotError(
                        f"seq {rid}: block table covers {len(blocks) * bs} "
                        f"positions < written {len(tokens) - 1}")
                if len(blocks) > self._max_blocks:
                    raise SnapshotError(f"seq {rid}: table too wide")
        if prefix is not None:
            by_bid: Dict[int, tuple] = {}
            kids: Dict[int, int] = {}
            for key, ent in prefix["entries"].items():
                bid = ent[0]
                if bid in by_bid:
                    raise SnapshotError(
                        f"prefix index maps block {bid} twice")
                if pool.refcount(bid) < 1:
                    raise SnapshotError(
                        f"prefix index holds unowned block {bid}")
                by_bid[bid] = key
                refs[bid] = refs.get(bid, 0) + 1
            for key, ent in prefix["entries"].items():
                parent = key[0]
                if parent != -1:
                    if parent not in by_bid:
                        raise SnapshotError(
                            f"prefix chain parent {parent} not in index")
                    kids[parent] = kids.get(parent, 0) + 1
                hops = 0
                while parent != -1:
                    parent = by_bid[parent][0]
                    hops += 1
                    if hops > len(by_bid):
                        raise SnapshotError("prefix chain cycle")
            for key, ent in prefix["entries"].items():
                if ent[2] != kids.get(ent[0], 0):
                    raise SnapshotError(
                        f"prefix child-count diverged on block {ent[0]}")
        # refcount ↔ mapping agreement must be EXACT: every owned block is
        # referenced precisely refcount times by sequences + the index —
        # any torn mid-mutation state (leaked alloc, half-finished retire,
        # stale table) lands here and falls back instead of serving
        for b in sorted(pool._owned):
            if pool.refcount(b) != refs.get(b, 0):
                raise SnapshotError(
                    f"block {b}: pool refcount {pool.refcount(b)} != "
                    f"{refs.get(b, 0)} mapped references")
        # KV content fingerprints: the bytes the survivors will read must be
        # the bytes the dead engine wrote — never a wrong-KV serve
        if list(fp["bids"]) != sorted(pool._owned):
            raise SnapshotError("fingerprint block set diverged from pool")
        sums = self._G.kv_block_checksums(kpool, vpool, fp["bids"])
        if not np.array_equal(sums, fp["sums"]):
            raise SnapshotError("KV content fingerprint mismatch")
        return pool

    def _attach(self, snap: dict, pool: PagePool, only) -> dict:
        """Install a validated snapshot (re-attach). Builds everything
        off-lock against the restored local pool, then installs under _cv in
        one notify — the idle scheduler thread picks the survivors up at its
        next iteration."""
        running, resume, installed = [], [], []
        blocks_attached = tokens_saved = 0
        max_id = 0
        any_deadline = any_prio = False
        for rec in snap["seqs"]:
            req = rec["req"]
            max_id = max(max_id, req.id)
            if (only is not None and req.id not in only) \
                    or req.done.is_set():
                if rec["blocks"]:
                    pool.free(rec["blocks"])
                continue
            s = _Seq(req, list(rec["tokens"]))
            s.prompt_len = rec["prompt_len"]
            if rec["phase"] == "running":
                s.blocks = list(rec["blocks"])
                s.cached_blocks = rec["cached_blocks"]
                running.append(s)
                blocks_attached += len(s.blocks)
                tokens_saved += len(s.tokens)
            else:
                # resume/admitting rows re-prefill from accumulated tokens
                # through the engine's own preemption machinery — exactly
                # what an uninterrupted engine would have done with them
                if rec["blocks"]:
                    pool.free(rec["blocks"])
                resume.append(s)
            installed.append(req.id)
            any_deadline |= req.deadline is not None
            any_prio |= req.priority != 0
        queue = []
        for req in snap.get("queue", ()):
            max_id = max(max_id, req.id)
            if (only is not None and req.id not in only) \
                    or req.done.is_set():
                continue
            queue.append(req)
            installed.append(req.id)
            any_deadline |= req.deadline is not None
            any_prio |= req.priority != 0
        # prefix index: rebind the chain to the restored pool when armed on
        # both sides; otherwise release the index-held references so
        # conservation holds without it
        new_prefix = (None if self._prefix is None
                      else _PrefixCache(pool, self.config.block_size))
        if snap.get("prefix") is not None:
            ps = snap["prefix"]
            if new_prefix is not None:
                new_prefix._entries = {k: list(v)
                                       for k, v in ps["entries"].items()}
                new_prefix._by_bid = {ent[0]: k for k, ent
                                      in new_prefix._entries.items()}
                new_prefix._tick = int(ps["tick"])
            else:
                bids = [ent[0] for ent in ps["entries"].values()]
                if bids:
                    pool.free(bids)
        with self._cv:
            if self._stop or self._draining or self._broken is not None:
                raise ServeError("adopt: engine is stopped/draining/broken")
            if self._step_i or self._running or self._resume \
                    or self._admitting or self._waiting:
                raise ServeError("adopt requires a fresh engine (no traffic)")
            self._pool = pool
            self._kpool = snap["kpool"]
            self._vpool = snap["vpool"]
            self._prefix = new_prefix
            self._running.extend(running)
            self._resume.extend(resume)
            self._waiting.extend(queue)
            if any_deadline:
                self._deadline_seen = True
            if any_prio:
                self._has_prio = True
            if max_id:
                # adopted ids stay unique against future submissions (the
                # supervisor's harvest dedup and spans key on req.id)
                self._ids = itertools.count(max_id + 1)
            self._cv.notify()
        counter_inc("serve_reattached", len(running))
        counter_inc("serve_reattached_blocks", blocks_attached)
        counter_inc("serve_reprefill_tokens_saved", tokens_saved)
        return {"mode": "reattach", "installed": sorted(installed),
                "reattached": len(running), "resumed": len(resume),
                "queued": len(queue), "blocks_reattached": blocks_attached,
                "reprefill_tokens_saved": tokens_saved,
                "reprefill_tokens": 0}

    def _adopt_reprefill(self, snap: dict, only) -> dict:
        """Whole-state fallback for a rejected snapshot: every in-flight
        record becomes a resume entry (re-prefill from its accumulated
        tokens into the ORIGINAL request/handle), queued requests re-queue.
        No pool/KV state from the snapshot is trusted or touched."""
        resume, queue, installed = [], [], []
        tokens_reprefilled = 0
        max_id = 0
        any_deadline = any_prio = False
        for rec in snap.get("seqs", ()):
            req = rec["req"]
            max_id = max(max_id, req.id)
            if (only is not None and req.id not in only) \
                    or req.done.is_set():
                continue
            s = _Seq(req, list(rec["tokens"]))
            s.prompt_len = rec["prompt_len"]
            resume.append(s)
            installed.append(req.id)
            tokens_reprefilled += len(s.tokens)
            any_deadline |= req.deadline is not None
            any_prio |= req.priority != 0
        for req in snap.get("queue", ()):
            max_id = max(max_id, req.id)
            if (only is not None and req.id not in only) \
                    or req.done.is_set():
                continue
            queue.append(req)
            installed.append(req.id)
            any_deadline |= req.deadline is not None
            any_prio |= req.priority != 0
        with self._cv:
            if self._stop or self._draining or self._broken is not None:
                raise ServeError("adopt: engine is stopped/draining/broken")
            self._resume.extend(resume)
            self._waiting.extend(queue)
            if any_deadline:
                self._deadline_seen = True
            if any_prio:
                self._has_prio = True
            if max_id:
                self._ids = itertools.count(max_id + 1)
            self._cv.notify()
        counter_inc("serve_reprefill_tokens", tokens_reprefilled)
        return {"mode": "reprefill", "installed": sorted(installed),
                "reattached": 0, "resumed": len(resume),
                "queued": len(queue), "blocks_reattached": 0,
                "reprefill_tokens_saved": 0,
                "reprefill_tokens": tokens_reprefilled}

    def handoff(self, timeout: float = 30.0) -> dict:
        """Planned zero-downtime handoff: quiesce the scheduler at its next
        step boundary, then export snapshot + queue + in-flight handles.

        After this returns, THIS engine is terminally stopped (``submit``
        raises, ``close()`` releases only plumbing — the handles live
        inside the returned snapshot) and a successor adopts the snapshot:
        ``new.adopt(old.handoff())``. Survivors resume mid-decode without
        re-prefill; a validation failure falls back whole to re-prefill
        inside ``adopt``. If the engine crashes before the quiesce lands,
        this raises ``ServeError`` and the normal crash path owns the
        handles (failed, or supervisor-recovered) — every interleaving
        either completes the handoff or falls back whole."""
        if threading.current_thread() is self._thread:
            raise ServeError("handoff() cannot run on the scheduler thread")
        with self._cv:
            if self._stop or self._draining or self._broken is not None:
                raise ServeError("handoff: engine is stopped/draining/broken")
            if self._handoff_req:
                raise ServeError("handoff already in progress")
            self._handoff_req = True
            self._cv.notify()
        deadline = time.monotonic() + max(0.0, float(timeout))
        while not self._quiesced.wait(timeout=0.05):
            if self._broken is not None or self._failed.is_set() \
                    or not self._thread.is_alive():
                raise ServeError(
                    "engine failed before handoff quiesce"
                ) from self._broken
            if time.monotonic() > deadline:
                raise ServeError(
                    f"handoff quiesce timed out after {timeout}s")
        # the loop exits right after signalling; join so the state is frozen
        self._thread.join(max(1.0, deadline - time.monotonic()))
        with span("serve_handoff", step=self._step_i):
            snap = self.snapshot()
            with self._cv:
                snap["queue"] = list(self._waiting)
                self._waiting.clear()
            # the snapshot is the single owner of every handle now: clear
            # the scheduler lists so close() cannot fail adopted streams
            self._running, self._resume, self._admitting = [], [], []
        counter_inc("serve_handoffs")
        return snap

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close(timeout=2.0)
        except Exception:  # lint: ok(oom-handler) — teardown guard, nothing dispatches in this try
            pass

    # ------------------------------------------------------- engine thread
    def _run_once(self):
        """One scheduler iteration (bounded idle wait). Truthy = stopped;
        the ``"handoff"`` sentinel additionally tells the loop to exit
        WITHOUT ``_shutdown`` — the handoff snapshot owns the handles."""
        self._beat = time.monotonic()  # heartbeat: health() / supervisor
        with self._cv:
            if self._handoff_req and not self._stop:
                # handoff quiesce: this is a step boundary (no _step in
                # flight), so the capture is consistent by construction.
                # _stop flips under the same lock, so submit() raises and a
                # supervisor monitor sees a closed engine, never a crash.
                self._stop = True
                self._quiesced.set()
                return "handoff"
            idle = not (self._waiting or self._running or self._resume
                        or self._prefilling)
            if self._draining and idle:
                self._stop = True  # drain complete: fall through to stop
            if not self._stop and idle:
                self._cv.wait(timeout=0.5)
            if self._stop:
                return True
            has_work = bool(self._waiting or self._running or self._resume
                            or self._prefilling)
        if has_work:
            self._step()
        if self._watchdog is not None:
            # supervised engines ride the PR 8 progress table: the scheduler
            # thread's serving step/phase lands in every crash dump's
            # cross-rank view (rate-limited inside publish)
            self._watchdog.publish(step=self._step_i, phase="serve.step",
                                   unit=self._provider)
        return False

    def _step(self):
        self._apply_pool_shrink()
        try:
            if _inject._armed:
                self._chaos_step()
            self._step_impl()
        except Exception as e:
            from ..fault import memory as _mem

            if not _mem.is_oom(e):
                raise
            # RESOURCE_EXHAUSTED inside a serving step: give HBM back (pool
            # headroom shrink → admission backpressure) and let the next
            # scheduler iteration retry — streams complete late, never crash.
            # A streak that shrinking cannot break falls through to the
            # crash-containment path (handles failed / supervisor restart),
            # so sustained exhaustion can never hang clients either.
            self._on_oom(e)

    def _step_impl(self):
        with span("schedule", step=self._step_i,
                  running=len(self._running)) as sp:
            self._drain_cancels()
            if self._deadline_seen:
                self._shed_sweep()
            # track mid-prefill sequences so a loop crash fails their
            # handles instead of orphaning them (they are in neither
            # _waiting nor _running until prefill lands); cleared only on
            # success — _shutdown sweeps it after a crash
            self._admitting = self._admit()
            if self._admitting and self._chunk:
                self._admitting = self._chunk_divert(self._admitting)
            if self._admitting:
                self._prefill(self._admitting)
            self._admitting = []
            if self._prefilling:
                self._chunk_step()
            if self._running:
                if self._spec_k:
                    self._decode_spec()
                else:
                    self._decode()
            sp.set(running_after=len(self._running))
            if self._obs is not None and flags.flag(
                    "FLAGS_hbm_admission", "off") != "off":
                # drift predictor (b): the admission preflight's predicted
                # peak vs the realized post-step live census. Only priced
                # when admission is armed — preflight already pays a census
                # per dispatch, so this adds one more per scheduler step.
                self._hbm_drift()
            self._oom_streak = 0
            self._maybe_unpark()

    def _hbm_drift(self):
        from .. import profiler as _prof
        from ..fault import memory as _fmem

        pred = _fmem.last_prediction().get("hbm_predicted_peak_bytes")
        if pred:
            live = int(_prof.memory_census().get("live_bytes", 0))
            if live:
                self._obs.drift("hbm_admission", pred, live)

    # clean scheduler steps (work done, no OOM) before parked blocks start
    # returning to circulation; halved-back gradually so a recurrence
    # re-parks quickly (class attr so tests can compress the window)
    _UNPARK_AFTER = 64

    def _maybe_unpark(self):
        """Pressure decay: after a clean-step window, return parked blocks
        to the free list half at a time — a transient OOM must not leave the
        pool permanently shrunk."""
        if not self._pool.parked_blocks:
            return
        if self._unpark_countdown > 0:
            self._unpark_countdown -= 1
            return
        # (PagePool.unpark counts serve_pages_unparked — the one decay
        # counter; no engine-level duplicate)
        self._pool.unpark(max(self._pool.parked_blocks // 2, 1))
        self._unpark_countdown = self._UNPARK_AFTER

    def _apply_pool_shrink(self):
        """Apply a cross-thread shrink request (engine thread only — the
        scheduler is the pool's single owner; the request word is read and
        cleared under _cv so a writer landing mid-apply is never lost)."""
        with self._cv:
            req = self._shrink_req
            self._shrink_req = 0
        if not req:
            return
        n = req if req > 0 else max(self._pool.free_blocks // 4, 1)
        parked = self._pool.park(n)
        if parked:
            counter_inc("serve_pool_shrunk", parked)
            self._unpark_countdown = self._UNPARK_AFTER

    def request_pool_shrink(self, blocks: Optional[int] = None) -> dict:
        """(any thread) Ask the scheduler to park KV blocks at its next step
        boundary — admission headroom shrinks, continuous batching backs
        off, nothing crashes. ``blocks=None`` parks a quarter of the free
        list. The serving callback fault/memory.free_pressure runs."""
        with self._cv:
            self._shrink_req = int(blocks) if blocks else -1
            self._cv.notify()
        return {"requested_blocks": blocks or "free/4",
                "pages_free": self._pool.free_blocks,
                "pages_parked": self._pool.parked_blocks}

    def _on_oom(self, exc: BaseException) -> None:
        from ..fault import memory as _mem

        self._oom_streak += 1
        if self._oom_streak > 8:
            # shrinking is not helping — contain, don't loop (the engine
            # loop's containment handler notes THIS exhaustion, so it is
            # not recorded twice)
            raise exc
        _mem.note_oom("serve.step", exc)
        # a mid-prefill OOM strands sequences in _admitting (blocks granted,
        # KV never written): free the grant and route them through the
        # preemption/resume path — they re-prefill from their accumulated
        # tokens once headroom allows, exactly like an evicted peer
        for seq in self._admitting:
            try:
                if seq.blocks:
                    self._pool.free(seq.blocks)
            except Exception:  # lint: ok(oom-handler) — pool itself may be what broke; the sweep must reach every seq
                pass
            seq.blocks = []
            if not seq.req.done.is_set():
                self._resume.append(seq)
        self._admitting = []
        # ditto a mid-chunked-prefill OOM: partial chunk K/V is abandoned
        # with the blocks — resume re-prefills the whole prompt
        for seq in self._prefilling:
            try:
                if seq.blocks:
                    self._pool.free(seq.blocks)
            except Exception:  # lint: ok(oom-handler) — pool itself may be what broke; the sweep must reach every seq
                pass
            seq.blocks = []
            seq.chunk_pos = 0
            if not seq.req.done.is_set():
                self._resume.append(seq)
        self._prefilling = []
        if self._prefix is not None and len(self._prefix):
            # cached-prefix KV is the most expendable resident state under
            # exhaustion — drop half before parking shrinks live headroom
            self._prefix.evict(max(len(self._prefix) // 2, 1))
        parked = self._pool.park(max(self._pool.free_blocks // 4, 1))
        if parked:
            counter_inc("serve_pool_shrunk", parked)
        self._unpark_countdown = self._UNPARK_AFTER

    def _chaos_step(self):
        """``serve.*`` chaos points, consulted once per scheduler step while
        injection is armed (the unarmed path is one module-attribute probe in
        ``_step``). ``serve.crash`` raises out of the loop, ``serve.wedge``
        hangs the scheduler thread (forever unless ``ms=`` bounds it),
        ``serve.slow_step`` is a straggler delay, ``serve.pool_corrupt``
        breaks pool conservation so a later free raises."""
        step = self._step_i
        if _inject.should_fire("serve.slow_step", step=step):
            time.sleep(_inject.point_cfg("serve.slow_step").get("ms", 100) / 1000.0)
        if _inject.should_fire("serve.pool_corrupt", step=step):
            self._pool.damage()
        if _inject.should_fire("serve.wedge", step=step):
            ms = _inject.point_cfg("serve.wedge").get("ms")
            if ms:
                time.sleep(ms / 1000.0)
            else:
                _inject._hang("serve.wedge")
        if _inject.should_fire("serve.crash", step=step):
            raise ServeError(f"injected serve.crash at engine step {step}")
        if _inject.should_fire("hbm.pressure", step=step):
            blocks = _inject.point_cfg("hbm.pressure").get("blocks")
            if blocks:
                self.request_pool_shrink(blocks)
        # synthesized RESOURCE_EXHAUSTED at the serving dispatch site —
        # raises into _step's OOM handler (shrink + backpressure, no crash)
        _inject.maybe_hbm_oom("serve.step", step=step)

    def _shed_sweep(self):
        """Step-boundary deadline enforcement. Runs only once a deadline'd
        request has ever been submitted (``_deadline_seen``) — the
        unconfigured path never reaches here. Expired requests fail wherever
        they sit; a queued request that cannot meet its deadline even if
        admitted NOW (prefill + full token budget at the measured decode-step
        EMA) is shed at admission — rejecting early is cheaper than paying a
        prefill it will abandon."""
        now = time.monotonic()
        # while the measured decode EMA is cold, the cost model's analytic
        # per-step tp-collective term is the feasibility floor — a sharded
        # engine's first deadline'd admits would otherwise assume 0-cost
        # steps and accept doomed work
        ema = max(self._ema_step_s, self._step_floor_s)
        shed = []
        with self._cv:
            for req in [r for r in self._waiting if r.deadline is not None]:
                eta = (1 + req.max_new_tokens) * ema
                if now >= req.deadline:
                    self._waiting.remove(req)
                    shed.append((req, f"expired in queue "
                                 f"({now - req.deadline:.3f}s late)"))
                elif now + eta > req.deadline:
                    self._waiting.remove(req)
                    shed.append((req, f"doomed at admission: needs "
                                 f"~{eta:.3f}s but the deadline is in "
                                 f"{req.deadline - now:.3f}s"))
        for req, why in shed:
            counter_inc("serve_deadline_shed")
            if self._obs is not None:
                self._obs.on_shed(
                    req, "expired" if now >= (req.deadline or now) else "doomed")
            self._finish_request(req, error=DeadlineExceeded(
                f"request {req.id} {why}", request_id=req.id))
        for seq in [s for s in self._running
                    if s.req.deadline is not None
                    and now >= s.req.deadline]:
            counter_inc("serve_deadline_expired")
            self._retire(seq, error=DeadlineExceeded(
                f"request {seq.req.id} deadline expired mid-decode "
                f"({seq.generated}/{seq.req.max_new_tokens} generated)",
                request_id=seq.req.id))
        for seq in [s for s in self._resume
                    if s.req.deadline is not None
                    and now >= s.req.deadline]:
            self._resume.remove(seq)
            counter_inc("serve_deadline_expired")
            self._finish_request(seq.req, error=DeadlineExceeded(
                f"request {seq.req.id} deadline expired while preempted "
                f"({seq.generated}/{seq.req.max_new_tokens} generated)",
                request_id=seq.req.id))
        for seq in [s for s in self._prefilling
                    if s.req.deadline is not None
                    and now >= s.req.deadline]:
            self._prefilling.remove(seq)
            if seq.blocks:
                self._pool.free(seq.blocks)
                seq.blocks = []
            counter_inc("serve_deadline_expired")
            self._finish_request(seq.req, error=DeadlineExceeded(
                f"request {seq.req.id} deadline expired mid-chunked-prefill "
                f"({seq.chunk_pos}/{len(seq.tokens)} tokens cached)",
                request_id=seq.req.id))

    # -- admission ----------------------------------------------------------
    def _make_prefill_buckets(self) -> Sequence[int]:
        bs, t_pad = self.config.block_size, self._max_blocks * self.config.block_size
        buckets, b = [], bs
        while b < t_pad:
            buckets.append(b)
            b *= 2
        buckets.append(t_pad)
        return tuple(buckets)

    def _bucket_for(self, n: int) -> int:
        for b in self._prefill_buckets:
            if b >= n:
                return b
        raise ValueError(f"no prefill bucket covers length {n}")

    def _headroom_ok(self, need: int, extra_running: int) -> bool:
        # AFTER granting `need`, keep one spare block per running sequence so
        # the next decode steps don't immediately preempt what admission
        # just packed in (a prefill paid, then discarded, is pure waste)
        return self._pool.free_blocks - need >= len(self._running) + extra_running

    def _alloc_with_reclaim(self, need: int, extra_running: int):
        """Block grant with prefix-cache reclaim: unpinned cached blocks are
        free headroom in disguise, so LRU cache entries are evicted before
        admission declares backpressure or a grower preempts a peer."""
        if self._headroom_ok(need, extra_running):
            got = self._pool.alloc(need)
            if got is not None:
                return got
        if self._prefix is not None and len(self._prefix):
            want = (need + len(self._running) + extra_running
                    - self._pool.free_blocks)
            if self._prefix.evict(max(want, 1)) \
                    and self._headroom_ok(need, extra_running):
                return self._pool.alloc(need)
        return None

    def _match_prefix(self, tokens) -> List[int]:
        """Longest-prefix cache lookup for an admission candidate; matched
        blocks are shared (refcount-bumped) here — callers must ``free``
        them on any later admission failure. Capped one token short of the
        whole sequence: prefill must always produce first-token logits."""
        limit = (len(tokens) - 1) // self.config.block_size
        bids = self._prefix.match(tokens, limit)
        if bids:
            self._pool.share(bids)
            counter_inc("serve_prefix_hits")
            counter_inc("serve_prefix_blocks_shared", len(bids))
        else:
            counter_inc("serve_prefix_misses")
        return bids

    def _admit(self) -> List[_Seq]:
        admitted: List[_Seq] = []
        with span("admit") as sp:
            # preempted sequences first: they already hold tokens and their
            # latency clock is running
            still_resume = []
            for seq in self._resume:
                if len(self._running) + len(admitted) >= self.config.max_batch:
                    still_resume.append(seq)
                    continue
                matched = (self._match_prefix(seq.tokens)
                           if self._prefix is not None else [])
                need = (-(-len(seq.tokens) // self.config.block_size)
                        - len(matched))
                blocks = self._alloc_with_reclaim(need, len(admitted) + 1)
                if blocks is None:
                    if matched:
                        self._pool.free(matched)
                    still_resume.append(seq)
                    continue
                seq.blocks = matched + blocks
                seq.cached_blocks = len(matched)
                admitted.append(seq)
                if self._obs is not None and matched:
                    self._obs.on_prefix_match(
                        seq.req, len(matched) * self.config.block_size,
                        len(matched))
            self._resume = still_resume
            # ONE ordered snapshot per admission pass, not an O(queue) scan
            # per batch slot: strict priority order, FIFO within a class,
            # and only the best remaining candidate is considered at each
            # slot — if it doesn't fit, nothing behind it bypasses it (no
            # starvation of large high-priority requests). Submits landing
            # mid-pass wait for the next step (ms away). Concurrent removal
            # (close/harvest while the engine is dying) is handled by the
            # remove() ValueError guards below.
            with self._cv:
                if self._has_prio and len(self._waiting) > 1:
                    cand = sorted(self._waiting,
                                  key=lambda r: (-r.priority, r.id))
                else:
                    cand = list(self._waiting)
            for req in cand:
                if len(self._running) + len(admitted) >= self.config.max_batch:
                    break
                with self._cv:
                    if req.cancelled:
                        try:
                            self._waiting.remove(req)
                        except ValueError:
                            continue  # already drained elsewhere
                        self._finish_request(req, error=RequestCancelled(
                            f"request {req.id} cancelled"))
                        continue
                    matched = (self._match_prefix(req.prompt)
                               if self._prefix is not None else [])
                    need = (-(-len(req.prompt) // self.config.block_size)
                            - len(matched))
                    blocks = self._alloc_with_reclaim(need, len(admitted) + 1)
                    if blocks is None:
                        if matched:
                            self._pool.free(matched)
                        counter_inc("serve_backpressure")
                        break
                    try:
                        self._waiting.remove(req)
                    except ValueError:  # raced away mid-pass — undo the grant
                        self._pool.free(matched + blocks)
                        continue
                seq = _Seq(req, list(req.prompt))
                seq.blocks = matched + blocks
                seq.cached_blocks = len(matched)
                admitted.append(seq)
                if self._obs is not None:
                    self._obs.on_admit(req)
                    if matched:
                        self._obs.on_prefix_match(
                            req, len(matched) * self.config.block_size,
                            len(matched))
            if admitted:
                counter_inc("serve_admitted", len(admitted))
            sp.set(admitted=len(admitted), resume_waiting=len(self._resume))
        return admitted

    # -- prefill -------------------------------------------------------------
    def _prefill(self, seqs: List[_Seq]):
        jnp = self._jnp
        bw = self.config.prefill_batch
        bs = self.config.block_size
        # rows that matched the prefix cache run the TAIL program (bucketed
        # by tail length, reading the shared prefix from the pool); misses
        # run the PR 11 full-prompt program unchanged
        groups: Dict[int, List[_Seq]] = {}
        tail_groups: Dict[int, List[_Seq]] = {}
        for s in seqs:
            if s.cached_blocks:
                tail = len(s.tokens) - s.cached_blocks * bs
                tail_groups.setdefault(self._bucket_for(tail), []).append(s)
            else:
                groups.setdefault(self._bucket_for(len(s.tokens)), []).append(s)
        for t_bucket in sorted(groups):
            group = groups[t_bucket]
            for i in range(0, len(group), bw):
                chunk = group[i:i + bw]
                with span("prefill", bucket_t=t_bucket, bucket_b=bw,
                          rows=len(chunk)) as sp:
                    if self._obs is not None:
                        sp.set(traces=tuple(s.req.trace for s in chunk))
                    # heartbeat before a potentially-long op (first-call jit
                    # compile): the supervisor's staleness clock starts HERE,
                    # so only a genuinely wedged op trips it
                    self._beat = time.monotonic()
                    n_fns = len(self._fns)
                    fn = self._get_fn("prefill", bw, t_bucket)
                    self._compiling = len(self._fns) != n_fns
                    ids = np.zeros((bw, t_bucket), np.int32)
                    lens = np.ones((bw,), np.int32)
                    tables = np.full((bw, self._max_blocks), TRASH_BLOCK,
                                     np.int32)
                    for r, s in enumerate(chunk):
                        ids[r, :len(s.tokens)] = s.tokens
                        lens[r] = len(s.tokens)
                        tables[r, :len(s.blocks)] = s.blocks
                    self._kpool, self._vpool, logits = fn(
                        self._compute_params, jnp.asarray(ids),
                        jnp.asarray(lens), jnp.asarray(tables),
                        self._kpool, self._vpool,
                    )
                    counter_inc("serve_prefills")
                    rows = np.asarray(logits)
                    # beat BEFORE dropping the compile grace: a monitor poll
                    # between the two would see a stale beat at the 1x limit
                    # and declare a spurious wedge after a long compile
                    self._beat = time.monotonic()
                    self._compiling = False
                    self._land_prefill(chunk, rows)
        for t_bucket in sorted(tail_groups):
            group = tail_groups[t_bucket]
            for i in range(0, len(group), bw):
                chunk = group[i:i + bw]
                with span("prefill", bucket_t=t_bucket, bucket_b=bw,
                          rows=len(chunk), shared=True) as sp:
                    if self._obs is not None:
                        sp.set(traces=tuple(s.req.trace for s in chunk))
                    self._beat = time.monotonic()
                    n_fns = len(self._fns)
                    fn = self._get_fn("prefill_tail", bw, t_bucket)
                    self._compiling = len(self._fns) != n_fns
                    ids = np.zeros((bw, t_bucket), np.int32)
                    starts = np.zeros((bw,), np.int32)
                    lens = np.ones((bw,), np.int32)
                    tables = np.full((bw, self._max_blocks), TRASH_BLOCK,
                                     np.int32)
                    for r, s in enumerate(chunk):
                        start = s.cached_blocks * bs
                        ids[r, :len(s.tokens) - start] = s.tokens[start:]
                        starts[r] = start
                        lens[r] = len(s.tokens) - start
                        tables[r, :len(s.blocks)] = s.blocks
                    self._kpool, self._vpool, logits = fn(
                        self._compute_params, jnp.asarray(ids),
                        jnp.asarray(starts), jnp.asarray(lens),
                        jnp.asarray(tables), self._kpool, self._vpool,
                    )
                    counter_inc("serve_prefills")
                    counter_inc("serve_tail_prefills")
                    rows = np.asarray(logits)
                    self._beat = time.monotonic()
                    self._compiling = False
                    self._land_prefill(chunk, rows)

    def _land_prefill(self, chunk: List[_Seq], rows: np.ndarray):
        """Post-prefill landing: index cacheable prompt blocks (while the
        sequence still owns them — the index takes its own reference, so a
        first-token retirement keeps the KV resident), then sample the
        first generated token and move the sequence into the running set."""
        for r, s in enumerate(chunk):
            if self._prefix is not None:
                full = s.prompt_len // self.config.block_size
                if full > s.cached_blocks:
                    self._prefix.insert(s.tokens, s.blocks,
                                        s.cached_blocks, full)
            self._append_token(s, self._sample_host(rows[r], s.req))
            if not s.req.done.is_set():
                self._running.append(s)
        if self._obs is not None:
            # ONE host clock read covers the whole landed group: prefill
            # always emits each row's first token (TTFT)
            self._obs.on_tokens([s.req for s in chunk], time.monotonic())

    # -- chunked prefill (PR 19) ---------------------------------------------
    def _chunk_divert(self, seqs: List[_Seq]) -> List[_Seq]:
        """Route admitted sequences whose un-cached prompt tail exceeds one
        chunk into the incremental queue; the rest (short prompts gain
        nothing from chunking) keep the monolithic path. The diverted
        sequence already owns ALL its prompt blocks — only the K/V writes
        are spread over steps."""
        keep: List[_Seq] = []
        bs = self.config.block_size
        for s in seqs:
            if len(s.tokens) - s.cached_blocks * bs > self._chunk:
                s.chunk_pos = s.cached_blocks * bs
                self._prefilling.append(s)
            else:
                keep.append(s)
        return keep

    def _chunk_step(self):
        """Advance chunked prefill by AT MOST one program call (<=
        prefill_batch rows x one chunk of tokens each), then fall through
        to the live decode batch — the scheduler-step interleave that keeps
        a 4k-token admit from freezing every in-flight stream. Each chunk
        is a tail feed at absolute positions: chunk boundaries are
        block-aligned (prefill_chunk % block_size == 0, cached prefixes are
        whole blocks), earlier chunks' K/V is read back through the block
        table, and the write goes through the existing paged scatter — so
        prefix-cached tails compose and the result is bit-identical to
        monolithic prefill. Intermediate chunk logits are discarded; the
        final chunk lands the sequence exactly like a monolithic pass."""
        jnp = self._jnp
        bw = self.config.prefill_batch
        batch = self._prefilling[:bw]
        feeds = [min(self._chunk, len(s.tokens) - s.chunk_pos)
                 for s in batch]
        t_bucket = self._bucket_for(max(feeds))
        with span("prefill", bucket_t=t_bucket, bucket_b=bw,
                  rows=len(batch), chunked=True) as sp:
            if self._obs is not None:
                sp.set(traces=tuple(s.req.trace for s in batch))
            self._beat = time.monotonic()
            n_fns = len(self._fns)
            fn = self._get_fn("prefill_tail", bw, t_bucket)
            self._compiling = len(self._fns) != n_fns
            ids = np.zeros((bw, t_bucket), np.int32)
            starts = np.zeros((bw,), np.int32)
            lens = np.ones((bw,), np.int32)
            tables = np.full((bw, self._max_blocks), TRASH_BLOCK, np.int32)
            for r, s in enumerate(batch):
                ids[r, :feeds[r]] = s.tokens[s.chunk_pos:s.chunk_pos
                                             + feeds[r]]
                starts[r] = s.chunk_pos
                lens[r] = feeds[r]
                tables[r, :len(s.blocks)] = s.blocks
            self._kpool, self._vpool, logits = fn(
                self._compute_params, jnp.asarray(ids),
                jnp.asarray(starts), jnp.asarray(lens),
                jnp.asarray(tables), self._kpool, self._vpool,
            )
            counter_inc("serve_prefill_chunks")
            done = [r for r, s in enumerate(batch)
                    if s.chunk_pos + feeds[r] >= len(s.tokens)]
            rows = (np.asarray(logits) if done
                    else None)  # only final chunks need the logits host-side
            self._beat = time.monotonic()
            self._compiling = False
            for r, s in enumerate(batch):
                s.chunk_pos += feeds[r]
            if done:
                finished = [batch[r] for r in done]
                self._prefilling = [s for s in self._prefilling
                                    if s not in finished]
                counter_inc("serve_prefills", len(finished))
                self._land_prefill(finished, rows[done])

    def _sample_host(self, logits_row: np.ndarray, req: _Request) -> int:
        """First generated token (prefill output) is sampled host-side; the
        greedy argmax matches the in-graph decode argmax bit-for-bit."""
        if req.temperature <= 0.0:
            return int(np.argmax(logits_row))
        z = logits_row.astype(np.float64) / max(req.temperature, 1e-6)
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self._rng.choice(len(p), p=p))

    # -- decode --------------------------------------------------------------
    def _grow_blocks(self):
        """Every live sequence needs block ``pos // block_size`` mapped
        before the step; pool exhaustion preempts a peer (evict → requeue
        for re-prefill) — backpressure, never failure. Victim selection is
        priority-then-youngest: the lowest-priority peer goes first, ties
        broken by the youngest request; a grower never evicts a
        higher-priority peer — it preempts ITSELF instead."""
        for seq in list(self._running):
            if seq not in self._running:
                continue  # evicted by an earlier iteration
            # spec verify writes k slots past pos — map those blocks too
            need = ((seq.pos + self._spec_k) // self.config.block_size + 1
                    - len(seq.blocks))
            while need > 0:
                with span("page_alloc", request=seq.req.id, blocks=need):
                    got = self._pool.alloc(need)
                    if got is None and self._prefix is not None \
                            and len(self._prefix):
                        # reclaim unpinned cache before preempting a peer
                        self._prefix.evict(need - self._pool.free_blocks)
                        got = self._pool.alloc(need)
                if got is not None:
                    seq.blocks.extend(got)
                    break
                victims = [s for s in self._running if s is not seq]
                if not victims:
                    # a lone sequence always fits (submit() bounds it), so
                    # this is unreachable unless accounting broke
                    raise ServeError(
                        f"page pool exhausted by a single sequence "
                        f"(request {seq.req.id})"
                    )
                victim = min(victims,
                             key=lambda s: (s.req.priority, -s.req.id))
                if victim.req.priority > seq.req.priority:
                    self._evict(seq)
                    break
                self._evict(victim)

    def _evict(self, seq: _Seq):
        with span("evict", request=seq.req.id, generated=seq.generated) as sp:
            if self._obs is not None:
                sp.set(traces=(seq.req.trace,))
            self._pool.free(seq.blocks)
            seq.blocks = []
            self._running.remove(seq)
            self._resume.append(seq)
            counter_inc("serve_preempted")

    def _gather_width(self, bb: int) -> int:
        """Per-decode-bucket gather width (ROADMAP item 1 leftover): the
        compiled step gathers this many blocks per row instead of the
        engine-wide ``_max_blocks`` — sized to the bucket's HIGH-WATER live
        block count, rounded up to a power of two (recompiles bounded at
        log2 per bucket), never shrinking. A width upgrade REPLACES the
        bucket's compiled entry, so ``stats()['compiles']`` stays bounded
        by the bucket count. Bit-identity is free: the dropped columns were
        all trash-block padding behind every row's live mask."""
        hw = max(len(s.blocks) for s in self._running)
        mb = self._decode_mb.get(bb, 0)
        if hw > mb:
            mb = 1
            while mb < hw:
                mb *= 2
            mb = min(mb, self._max_blocks)
            old = self._decode_mb.get(bb)
            if old is not None:
                self._fns.pop(("decode", bb, old), None)
                self._fns.pop(("spec", bb, old), None)
            self._decode_mb[bb] = mb
        return mb

    def _cow_guard(self, seq: _Seq):
        """Copy-on-write: a write-range block still shared with the prefix
        index or a peer is copied into a private block before the step
        writes it. The admission policy keeps shared prefix blocks strictly
        BELOW every write column (matching is capped at full prompt blocks,
        writes start at ``prompt_len``), so this is defense in depth — it
        keeps peers bit-intact even if a future scheduler maps shared
        blocks more aggressively."""
        bs = self.config.block_size
        lo, hi = seq.pos // bs, (seq.pos + self._spec_k) // bs
        for col in range(lo, min(hi + 1, len(seq.blocks))):
            bid = seq.blocks[col]
            if self._pool.refcount(bid) <= 1:
                continue
            repl = self._alloc_with_reclaim(1, 0)
            if repl is None:
                raise ServeError(
                    f"page pool exhausted during copy-on-write "
                    f"(request {seq.req.id})"
                )
            new = repl[0]
            self._kpool = self._kpool.at[:, new].set(self._kpool[:, bid])
            self._vpool = self._vpool.at[:, new].set(self._vpool[:, bid])
            seq.blocks[col] = new
            self._pool.free([bid])
            counter_inc("serve_cow_copies")
            if self._obs is not None:
                self._obs.on_cow(seq.req.trace, 1)

    def _decode(self):
        jnp, jax = self._jnp, self._jax
        self._grow_blocks()
        if not self._running:
            return
        if self._prefix is not None:
            for s in self._running:
                self._cow_guard(s)
        n = len(self._running)
        bb = next(b for b in self.config.decode_buckets if b >= n)
        mb = self._gather_width(bb)
        tables = np.full((bb, mb), TRASH_BLOCK, np.int32)
        pos = np.zeros((bb,), np.int32)
        toks = np.zeros((bb,), np.int32)
        temps = np.zeros((bb,), np.float32)
        for r, s in enumerate(self._running):
            tables[r, :len(s.blocks)] = s.blocks
            pos[r] = s.pos
            toks[r] = s.tokens[-1]
            temps[r] = s.req.temperature
        self._key, sub = jax.random.split(self._key)
        # a width upgrade pops the old entry, so compare by key presence,
        # not _fns length
        warm = ("decode", bb, mb) in self._fns
        with span("decode_step", bucket=bb, rows=n, step=self._step_i) as sp:
            if self._obs is not None:
                sp.set(traces=tuple(s.req.trace for s in self._running))
            self._beat = time.monotonic()  # staleness clock covers this op
            fn = self._get_fn("decode", bb, mb)
            self._compiling = not warm
            t0 = time.monotonic()
            self._kpool, self._vpool, nxt = fn(
                self._compute_params, self._kpool, self._vpool,
                jnp.asarray(tables), jnp.asarray(pos), jnp.asarray(toks),
                jnp.asarray(temps), sub,
            )
        nxt = np.asarray(nxt)
        self._beat = time.monotonic()  # beat before dropping compile grace
        self._compiling = False
        # decode service-time EMA feeds deadline feasibility + Retry-After
        # hints; compile steps are excluded — they would make every early
        # deadline look doomed
        if warm:
            dt = time.monotonic() - t0
            if self._obs is not None and self._ema_step_s:
                # drift predictor (a): the shed-ETA per-step estimate the
                # sweep would have used for THIS step vs its measured time
                rel = self._obs.drift(
                    "step_eta", max(self._ema_step_s, self._step_floor_s), dt)
                update_attrs(sp, cost_drift=round(rel, 6))
            self._ema_step_s = (dt if not self._ema_step_s
                                else 0.8 * self._ema_step_s + 0.2 * dt)
        self._step_i += 1
        self._occ_live += n
        self._occ_slots += bb
        counter_inc("serve_decode_steps")
        counter_inc("serve_occupancy_live", n)
        counter_inc("serve_occupancy_slots", bb)
        rows_live = list(self._running)
        for r, s in enumerate(rows_live):
            self._append_token(s, int(nxt[r]))
        if self._obs is not None:
            # one host clock read at step retire, attributed to every row
            # that emitted a token this step (TTFT / inter-token gap)
            self._obs.on_tokens([s.req for s in rows_live], time.monotonic())

    # -- speculative decode ---------------------------------------------------
    def _propose(self, bb: int) -> np.ndarray:
        """Per-row draft proposals (bb, spec_k) for the greedy rows, -1
        padded (a -1 can never equal a verify argmax, so unproposed slots
        accept nothing and the step degenerates to plain decode)."""
        k = self._spec_k
        drafts = np.full((bb, k), -1, np.int32)
        greedy_rows = [(r, s) for r, s in enumerate(self._running)
                       if s.req.temperature <= 0.0]
        if not greedy_rows:
            return drafts
        if self._drafter is True:  # host-side n-gram prompt lookup
            for r, s in greedy_rows:
                got = _ngram_propose(s.tokens, k)
                drafts[r, :len(got)] = got
            return drafts
        darch, dparams, W = self._drafter
        ids = np.zeros((bb, W), np.int32)
        lens = np.ones((bb,), np.int32)
        for r, s in greedy_rows:
            tl = min(len(s.tokens), W)
            ids[r, :tl] = s.tokens[-tl:]
            lens[r] = tl
        warm = ("draft", bb) in self._fns
        with span("draft", bucket=bb, rows=len(greedy_rows)):
            self._beat = time.monotonic()
            fn = self._get_fn("draft", bb)
            self._compiling = not warm
            out = np.asarray(fn(dparams, self._jnp.asarray(ids),
                                self._jnp.asarray(lens)))
            self._beat = time.monotonic()
            self._compiling = False
        for r, _ in greedy_rows:
            drafts[r] = out[r]
        return drafts

    def _decode_spec(self):
        """One speculative scheduler step: draft k tokens per row, verify
        all of them (plus the pending next-input token) in ONE compiled
        paged step, accept the longest agreeing prefix. Greedy rows emit
        1..k+1 tokens per step bit-identically to plain decode; sampling
        rows take the j=0 sampled token and accept no drafts."""
        jnp, jax = self._jnp, self._jax
        k = self._spec_k
        self._grow_blocks()
        if not self._running:
            return
        if self._prefix is not None:
            for s in self._running:
                self._cow_guard(s)
        n = len(self._running)
        bb = next(b for b in self.config.decode_buckets if b >= n)
        mb = self._gather_width(bb)
        drafts = self._propose(bb)
        tables = np.full((bb, mb), TRASH_BLOCK, np.int32)
        pos = np.zeros((bb,), np.int32)
        toks = np.zeros((bb, k + 1), np.int32)
        temps = np.zeros((bb,), np.float32)
        for r, s in enumerate(self._running):
            tables[r, :len(s.blocks)] = s.blocks
            pos[r] = s.pos
            toks[r, 0] = s.tokens[-1]
            toks[r, 1:] = drafts[r]
            temps[r] = s.req.temperature
        self._key, sub = jax.random.split(self._key)
        warm = ("spec", bb, mb) in self._fns
        with span("decode_step", bucket=bb, rows=n, step=self._step_i,
                  spec_k=k) as sp:
            if self._obs is not None:
                sp.set(traces=tuple(s.req.trace for s in self._running))
            self._beat = time.monotonic()
            fn = self._get_fn("spec", bb, mb)
            self._compiling = not warm
            t0 = time.monotonic()
            self._kpool, self._vpool, greedy, sampled = fn(
                self._compute_params, self._kpool, self._vpool,
                jnp.asarray(tables), jnp.asarray(pos), jnp.asarray(toks),
                jnp.asarray(temps), sub,
            )
            greedy, sampled = np.asarray(greedy), np.asarray(sampled)
            proposed = accepted = 0
            rows_live = list(self._running)
            for r, s in enumerate(rows_live):
                if temps[r] > 0.0:
                    self._append_token(s, int(sampled[r]))
                    continue
                nprop = int(np.sum(drafts[r] >= 0))
                m = 0
                while m < nprop and drafts[r, m] == greedy[r, m]:
                    m += 1
                proposed += nprop
                accepted += m
                # the m accepted drafts re-emerge as the target's own argmax
                # continuations, plus the bonus token after the last one
                for j in range(m + 1):
                    if s.req.done.is_set():
                        break
                    self._append_token(s, int(greedy[r, j]))
            sp.set(drafted=proposed, accepted=accepted)
        self._beat = time.monotonic()
        self._compiling = False
        if self._obs is not None:
            # every live row emits at least its j=0 token per spec step; the
            # gap histogram sees one sample per row per step (a multi-accept
            # step IS one inter-token interval at step granularity)
            self._obs.on_tokens([s.req for s in rows_live], time.monotonic())
        if warm:
            dt = time.monotonic() - t0
            if self._obs is not None and self._ema_step_s:
                rel = self._obs.drift(
                    "step_eta", max(self._ema_step_s, self._step_floor_s), dt)
                update_attrs(sp, cost_drift=round(rel, 6))
            self._ema_step_s = (dt if not self._ema_step_s
                                else 0.8 * self._ema_step_s + 0.2 * dt)
        self._step_i += 1
        self._occ_live += n
        self._occ_slots += bb
        counter_inc("serve_decode_steps")
        counter_inc("serve_occupancy_live", n)
        counter_inc("serve_occupancy_slots", bb)
        counter_inc("serve_draft_proposed", proposed)
        counter_inc("serve_draft_accepted", accepted)

    def _append_token(self, seq: _Seq, tok: int):
        """Record one generated token; retire the sequence when it hits eos,
        its budget, or a cancel flag."""
        req = seq.req
        seq.tokens.append(tok)
        counter_inc("serve_tokens")
        if req.stream_q is not None:
            req.stream_q.put(tok)
        if req.cancelled:
            self._retire(seq, error=RequestCancelled(
                f"request {req.id} cancelled"))
        elif (req.eos_token_id is not None and tok == req.eos_token_id) \
                or seq.generated >= req.max_new_tokens:
            self._retire(seq)

    def _retire(self, seq: _Seq, error: Optional[BaseException] = None):
        self._pool.free(seq.blocks)
        seq.blocks = []
        if seq in self._running:
            self._running.remove(seq)
        self._finish_request(seq.req, tokens=seq.tokens, error=error)

    def _finish_request(self, req: _Request, tokens=None, error=None):
        if _finish(req, tokens=tokens, error=error):
            if self._obs is not None:
                self._obs.on_done(req, error)
            if error is None:
                # completed-request latency EMA drives the Overloaded
                # retry_after_s hint
                lat = req.t_done - req.t_submit
                self._ema_req_s = (lat if not self._ema_req_s
                                   else 0.8 * self._ema_req_s + 0.2 * lat)

    # -- cancellation / teardown ---------------------------------------------
    def _cancel(self, req: _Request):
        with self._cv:
            req.cancelled = True
            self._cv.notify()

    def _drain_cancels(self):
        for seq in [s for s in self._running if s.req.cancelled]:
            self._retire(seq, error=RequestCancelled(
                f"request {seq.req.id} cancelled"))
        for seq in [s for s in self._resume if s.req.cancelled]:
            self._resume.remove(seq)
            self._finish_request(seq.req, error=RequestCancelled(
                f"request {seq.req.id} cancelled"))
        # mid-chunked-prefill cancels free their (fully allocated) prompt
        # blocks immediately — chunks already written are simply abandoned
        for seq in [s for s in self._prefilling if s.req.cancelled]:
            self._prefilling.remove(seq)
            if seq.blocks:
                self._pool.free(seq.blocks)
                seq.blocks = []
            self._finish_request(seq.req, error=RequestCancelled(
                f"request {seq.req.id} cancelled"))
        # queued-but-unadmitted cancels must not wait for a batch slot: a
        # saturated engine would otherwise sit on them for minutes
        with self._cv:
            cancelled = [r for r in self._waiting if r.cancelled]
            for req in cancelled:
                self._waiting.remove(req)
        for req in cancelled:
            self._finish_request(req, error=RequestCancelled(
                f"request {req.id} cancelled"))

    def _shutdown(self):
        err = self._broken or ServeError("serving engine closed")
        if self._prefix is not None:
            try:
                self._prefix.release_all()
            except Exception:  # lint: ok(oom-handler) — corrupt-pool containment sweep, crash already classified in _step
                pass
        with self._cv:
            waiting = list(self._waiting)
            self._waiting.clear()
        for req in waiting:
            self._finish_request(req, error=ServeError(str(err)))
        # _admitting covers sequences a crash caught mid-prefill; the
        # done-guard in _finish_request dedupes any that made it to _running.
        # Per-sequence guards: when the crash WAS a pool inconsistency, the
        # same free() would raise again here — one bad sequence must not
        # stop us failing the remaining handles.
        for seq in list(self._running) + list(self._resume) \
                + list(self._admitting) + list(self._prefilling):
            try:
                if seq.blocks:
                    self._pool.free(seq.blocks)
            except Exception:  # lint: ok(oom-handler) — corrupt-pool containment sweep, crash already classified in _step
                pass
            seq.blocks = []
            try:
                self._finish_request(seq.req, error=ServeError(str(err)))
            except Exception:  # lint: ok(oom-handler) — handle-state sweep, nothing dispatches in this try
                pass
        self._running, self._resume, self._admitting = [], [], []
        self._prefilling = []

    # -- compiled-program cache ----------------------------------------------
    def _get_fn(self, kind: str, *bucket):
        """One jitted program per (kind, bucket shape); the count of entries
        IS the compile count the bucket policy promises (<= buckets used)."""
        key = (kind,) + bucket
        fn = self._fns.get(key)
        if fn is None:
            jax, G = self._jax, self._G
            if self._tp:
                # tensor-parallel builders: packed param tree, shard_map
                # body, dequantization inside the body — no outer dequant
                # wrapper. Same call signatures, same donation slots.
                tpkw = dict(mesh=self._tp_mesh, vocab=self._tp_vocab,
                            dtype=self._dtype,
                            int8_wire=bool(self.config.tp_int8))
                if kind == "prefill":
                    bw, t_bucket = bucket
                    raw = G.build_tp_paged_prefill(
                        self._arch_key, bw, t_bucket,
                        self.config.block_size, self._max_blocks, **tpkw)
                    donate = (4, 5)
                elif kind == "prefill_tail":
                    bw, t_bucket = bucket
                    raw = G.build_tp_paged_tail_prefill(
                        self._arch_key, bw, t_bucket,
                        self.config.block_size, self._max_blocks, **tpkw)
                    donate = (5, 6)
                elif kind == "decode":
                    bb, mb = bucket
                    raw = G.build_tp_paged_decode(
                        self._arch_key, bb, self.config.block_size, mb,
                        use_kernel=bool(
                            flags.flag("FLAGS_serve_paged_kernel", False)),
                        **tpkw)
                    donate = (1, 2)
                else:  # spec/draft excluded by EngineConfig validation
                    raise RuntimeError(
                        f"serving: program kind {kind!r} has no "
                        "tensor-parallel build")
                if jax.default_backend() == "cpu":
                    fn = jax.jit(raw)
                else:
                    fn = jax.jit(raw, donate_argnums=donate)
                self._fns[key] = fn
                counter_inc("serve_compiles")
                return fn
            if kind == "prefill":
                bw, t_bucket = bucket
                raw = G.build_paged_prefill(
                    self._arch, bw, t_bucket, self.config.block_size,
                    self._max_blocks)
                donate = (4, 5)
            elif kind == "prefill_tail":
                bw, t_bucket = bucket
                raw = G.build_paged_tail_prefill(
                    self._arch, bw, t_bucket, self.config.block_size,
                    self._max_blocks)
                donate = (5, 6)
            elif kind == "spec":
                bb, mb = bucket
                raw = G.build_paged_spec_decode(
                    self._arch, bb, self._spec_k, self.config.block_size, mb)
                donate = (1, 2)
            elif kind == "draft":
                # drafter weights, not the (possibly int8) target params —
                # no dequant wrapper, nothing donated
                (bb,) = bucket
                darch, _, W = self._drafter
                fn = jax.jit(G.build_window_draft(darch, bb, W, self._spec_k))
                self._fns[key] = fn
                counter_inc("serve_compiles")
                return fn
            else:
                bb, mb = bucket
                # opt-in Pallas paged-attention decode (bit-identical to the
                # gather builder; spec-decode above keeps the gather path)
                if flags.flag("FLAGS_serve_paged_kernel", False):
                    raw = G.build_paged_decode_kernel(
                        self._arch, bb, self.config.block_size, mb)
                else:
                    raw = G.build_paged_decode(
                        self._arch, bb, self.config.block_size, mb)
                donate = (1, 2)
            if self._dequant is not None:
                dq, inner = self._dequant, raw

                def raw(params, *args, _dq=dq, _inner=inner):
                    return _inner(_dq(params), *args)

            # donation lets XLA update the pools in place; CPU ignores the
            # hint (it would only warn), so only pass it off-CPU
            if jax.default_backend() == "cpu":
                fn = jax.jit(raw)
            else:
                fn = jax.jit(raw, donate_argnums=donate)
            self._fns[key] = fn
            counter_inc("serve_compiles")
        return fn

    # -- flight-recorder context ----------------------------------------------
    def _flight_context(self) -> dict:
        with self._lock:
            depth = len(self._waiting)
        return {
            "queue_depth": depth,
            "step": self._step_i,
            "spec_k": self._spec_k,
            # mesh + chunked-prefill state (PR 19): post-mortems on a
            # sharded engine must name the mesh, and a stall diagnosis
            # needs the chunk backlog at the crash step
            "tp": self._tp,
            "prefill_chunk": self._chunk,
            "chunk_queue_depth": len(self._prefilling),
            "pending_chunks": sum(
                -(-(len(s.tokens) - s.chunk_pos) // max(self._chunk, 1))
                for s in list(self._prefilling)),
            "prefix_cached_blocks": (self._prefix.blocks
                                     if self._prefix is not None else 0),
            "pages": {"used": self._pool.used_blocks,
                      "free": self._pool.free_blocks,
                      "parked": self._pool.parked_blocks},
            "running": [
                {"id": s.req.id, "prompt_len": s.prompt_len,
                 "generated": s.generated, "pos": s.pos,
                 "blocks": len(s.blocks)}
                for s in list(self._running)
            ],
        }

    # -- test/debug hook -------------------------------------------------------
    def _debug_prefill_logits(self, prompt_ids) -> np.ndarray:
        """Logits at the prompt's last token through the REAL bucketed
        prefill program, with every table entry pointed at the trash block
        (no allocation, pool contents untouched where it matters). Callers
        must hold the engine idle — this runs on the calling thread."""
        jnp = self._jnp
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        t_bucket = self._bucket_for(len(prompt))
        bw = self.config.prefill_batch
        fn = self._get_fn("prefill", bw, t_bucket)
        ids = np.zeros((bw, t_bucket), np.int32)
        ids[0, :len(prompt)] = prompt
        lens = np.ones((bw,), np.int32)
        lens[0] = len(prompt)
        tables = np.full((bw, self._max_blocks), TRASH_BLOCK, np.int32)
        self._kpool, self._vpool, logits = fn(
            self._compute_params, jnp.asarray(ids), jnp.asarray(lens),
            jnp.asarray(tables), self._kpool, self._vpool,
        )
        return np.asarray(logits[0])


def _engine_loop(wr):
    """Scheduler thread body. Holds the engine only through a weakref and
    re-derefs every iteration, so an abandoned engine is GC-collectable
    (its __del__ runs close(); a dead deref also just ends the thread)."""
    while True:
        eng = wr()
        if eng is None:
            return
        try:
            stopped = eng._run_once()
        except Exception as e:
            # fail loudly into every pending handle rather than leave
            # clients blocked on events that will never fire — and nothing
            # (not even a failing post-mortem) may stand between the crash
            # and that sweep. An exhaustion that defeated the in-step shrink
            # ladder lands here too — classified, then contained.
            from ..fault import memory as _mem

            if _mem.is_oom(e):
                _mem.note_oom("serve.loop", e)
            eng._broken = e
            try:
                counter_inc("serve_engine_errors")
                flight.dump("serving_loop_error", extra={"exception": repr(e)})
            finally:
                if eng._supervised:
                    # leave queued/in-flight scheduler state intact for the
                    # supervisor to harvest (requeue onto the restarted
                    # engine, or fail structurally) — _shutdown here would
                    # fail handles the restart could still save. The kick
                    # wakes the monitor without waiting out its poll.
                    eng._failed.set()
                else:
                    eng._shutdown()
            return
        if stopped:
            # handoff quiesce exits WITHOUT failing handles: the exported
            # snapshot is their owner from here (Engine.handoff docstring)
            if stopped != "handoff":
                eng._shutdown()
            return
        del eng
