"""Fixed-size KV block pool — the paged-cache allocator.

HBM holds ONE preallocated pool of ``num_blocks`` KV blocks per engine
(``(layers, num_blocks, block_size, kv_heads, head_dim)`` for K and V);
sequences own ``ceil(len / block_size)`` block ids each, recorded in a
per-sequence block table, so resident cache memory is ``Σ ceil(len/block)``
blocks instead of ``batch × T_max`` dense caches.

Block 0 is the reserved TRASH block: padding rows of a bucketed batch and
padded tail entries of short rows point their table slots at it, so the
compiled programs can scatter unconditionally — trash is written freely and
never read (the live mask excludes every position it could back).

The allocator is free-list + owned-set bookkeeping with hard invariants:
allocating more than is free returns ``None`` (the scheduler turns that into
queue backpressure or preemption, never a crash), freeing an unowned id
raises (double-free), and ``check()`` asserts conservation. Engine-thread
only — the scheduler is the single owner, so no lock is needed here.

Under HBM pressure (fault/memory.py recovery ladder) the scheduler PARKS
blocks: :meth:`park` moves free blocks to a reserved set that ``alloc``
cannot see, shrinking admission headroom so continuous batching backs off
to a smaller resident working set — backpressure, never a crash. ``check``
counts parked blocks in the conservation invariant; :meth:`unpark` gives
them back once pressure clears.

Blocks are REFCOUNTED (prefix-cache KV sharing): ``alloc`` hands out blocks
at refcount 1, :meth:`share` bumps an owned block so several sequences (or
the engine's prefix index) can map the same physical block, and ``free``
decrements — the block returns to the free list only when the last
reference drops. Freeing an unowned id still raises (double-free), and
``park`` only ever draws from the free list, so a block with live
references can structurally never be parked — PR 14's OOM pool-shrink is
safe under sharing by construction.

The bookkeeping is SNAPSHOTTABLE (serving state durability): ``snapshot``
captures free list, ownership, refcounts, and parked set in O(blocks) plus
a CRC over the canonical encoding, and ``restore`` rebuilds a pool from a
capture — re-running ``check()`` plus structural validation so a torn or
tampered snapshot surfaces as a structured :class:`SnapshotError`, never a
silently-wrong allocator.

Tensor-parallel serving does not change ANY of this: the device-side KV
arrays are sharded over the mesh on the kv_heads axis (each chip owns
``kv_heads/tp`` of every block), but a block id names the same slot on
every shard, so this host-side allocator — free list, refcounts, parked
set, snapshots, conservation — stays REPLICATED and tp-oblivious. One
bookkeeping truth drives ``tp`` physical shards.
"""
from __future__ import annotations

import zlib
from typing import Dict, List, Optional

from ..profiler import counter_inc

__all__ = ["PagePool", "SnapshotError", "TRASH_BLOCK"]

TRASH_BLOCK = 0

POOL_SNAPSHOT_VERSION = 1


class SnapshotError(RuntimeError):
    """A serving-state snapshot failed validation (torn capture, tampering,
    or an incompatible target) — callers fall back to re-prefill recovery
    rather than serving from suspect KV state."""


def _pool_crc(num_blocks: int, free, ref, parked) -> int:
    payload = (num_blocks, tuple(free), tuple(sorted(ref.items())),
               tuple(parked))
    return zlib.crc32(repr(payload).encode())


class PagePool:
    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("PagePool needs >= 2 blocks (block 0 is trash)")
        self.num_blocks = int(num_blocks)
        # LIFO free list: recently-freed blocks are re-used first (warm)
        self._free: List[int] = list(range(self.num_blocks - 1, TRASH_BLOCK, -1))
        self._owned = set()
        self._ref: Dict[int, int] = {}  # owned block id -> reference count
        # blocks withdrawn from circulation under memory pressure (park()):
        # invisible to alloc, still conserved by check()
        self._parked: List[int] = []

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return len(self._owned)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` block ids, or None when the pool can't cover them (the
        caller's backpressure signal — nothing is partially allocated)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        ids = [self._free.pop() for _ in range(n)]
        self._owned.update(ids)
        for b in ids:
            self._ref[b] = 1
        counter_inc("serve_pages_allocated", n)
        return ids

    def share(self, ids) -> None:
        """Bump the refcount of already-owned blocks (prefix-cache sharing):
        each sharer later calls ``free`` once, and the block only returns to
        circulation when the last reference drops. Sharing an unowned id
        raises — a sharer can only piggyback on a live block."""
        for b in ids:
            if b not in self._owned:
                raise RuntimeError(f"PagePool: share of unowned block id {b}")
        for b in ids:
            self._ref[b] += 1
        if ids:
            counter_inc("serve_pages_shared", len(ids))

    def refcount(self, bid: int) -> int:
        """Current reference count of a block (0 = not owned)."""
        return self._ref.get(bid, 0)

    def free(self, ids) -> None:
        """Drop one reference per id; a block returns to the free list when
        its count hits zero. Freeing an unowned id raises (double-free)."""
        released = 0
        for b in ids:
            if b not in self._owned:
                raise RuntimeError(
                    f"PagePool: double-free or foreign block id {b}"
                )
            self._ref[b] -= 1
            if self._ref[b] == 0:
                del self._ref[b]
                self._owned.remove(b)
                self._free.append(b)
                released += 1
        counter_inc("serve_pages_freed", released)

    @property
    def parked_blocks(self) -> int:
        return len(self._parked)

    def park(self, n: int) -> int:
        """Withdraw up to ``n`` FREE blocks from circulation (HBM-pressure
        admission-headroom shrink): parked blocks are invisible to ``alloc``
        so the scheduler's backpressure engages at a smaller resident
        working set. Running sequences keep what they own — only future
        growth is throttled. Returns how many were actually parked (never
        drains the free list completely: one grow-block of headroom stays,
        so a lone running sequence can still finish)."""
        if n < 0:
            raise ValueError(f"park({n})")
        take = max(min(int(n), len(self._free) - 1), 0)
        for _ in range(take):
            self._parked.append(self._free.pop())
        if take:
            counter_inc("serve_pages_parked", take)
        return take

    def unpark(self, n: Optional[int] = None) -> int:
        """Return parked blocks to the free list (pressure cleared)."""
        take = len(self._parked) if n is None else min(int(n), len(self._parked))
        for _ in range(take):
            self._free.append(self._parked.pop())
        if take:
            counter_inc("serve_pages_unparked", take)
        return take

    def damage(self) -> None:
        """Chaos-only (``serve.pool_corrupt`` injection point): deliberately
        break conservation so the next ``free()`` of the damaged block (or
        ``check()``) raises — the engine's crash-containment path must turn
        a corrupt pool into failed-or-requeued handles, never a hang."""
        if self._owned:
            lost = next(iter(self._owned))
            self._owned.discard(lost)
            self._ref.pop(lost, None)
        elif self._free:
            self._free.append(self._free[-1])
        counter_inc("serve_pool_damaged")

    def check(self) -> None:
        """Conservation invariant: every non-trash block is exactly one of
        free, owned, or parked; every owned block carries a refcount >= 1
        and nothing else does (refcounts never leak past ownership)."""
        if len(self._free) + len(self._owned) + len(self._parked) \
                != self.num_blocks - 1:
            raise RuntimeError(
                f"PagePool leak: {len(self._free)} free + "
                f"{len(self._owned)} owned + {len(self._parked)} parked "
                f"!= {self.num_blocks - 1}"
            )
        circulating = set(self._free) | set(self._parked)
        if self._owned & circulating or len(circulating) != (
                len(self._free) + len(self._parked)):
            raise RuntimeError("PagePool: block in two states at once")
        if TRASH_BLOCK in self._owned or TRASH_BLOCK in circulating:
            raise RuntimeError("PagePool: trash block entered circulation")
        if set(self._ref) != self._owned:
            raise RuntimeError(
                "PagePool: refcount bookkeeping diverged from ownership"
            )
        if any(c < 1 for c in self._ref.values()):
            raise RuntimeError("PagePool: owned block with refcount < 1")

    # -- snapshot / restore (serving state durability) ----------------------

    def snapshot(self) -> dict:
        """O(blocks) consistent capture of the allocator bookkeeping.

        Caller contract: taken at a scheduler step boundary (or from a dead
        scheduler's frozen state) — the pool is engine-thread-only, so a
        boundary capture is consistent by construction. The CRC covers the
        canonical encoding; ``restore`` rejects any capture whose fields no
        longer match it (torn or tampered snapshot)."""
        snap = {
            "version": POOL_SNAPSHOT_VERSION,
            "num_blocks": self.num_blocks,
            "free": list(self._free),
            "ref": dict(self._ref),
            "parked": list(self._parked),
        }
        snap["crc"] = _pool_crc(self.num_blocks, self._free, self._ref,
                                self._parked)
        return snap

    @classmethod
    def restore(cls, snap: dict) -> "PagePool":
        """Rebuild a pool from a :meth:`snapshot` capture, or raise
        :class:`SnapshotError`. Validation is the extended ``check()``:
        CRC integrity, id ranges, duplicate detection, conservation, and
        refcount↔ownership agreement all must hold — a capture that fails
        any of them is rejected whole (the restored pool never escapes)."""
        try:
            if snap.get("version") != POOL_SNAPSHOT_VERSION:
                raise SnapshotError(
                    f"pool snapshot version {snap.get('version')!r} "
                    f"!= {POOL_SNAPSHOT_VERSION}"
                )
            num_blocks = int(snap["num_blocks"])
            free = [int(b) for b in snap["free"]]
            ref = {int(b): int(c) for b, c in snap["ref"].items()}
            parked = [int(b) for b in snap["parked"]]
        except SnapshotError:
            raise
        except Exception as e:
            raise SnapshotError(f"malformed pool snapshot: {e!r}") from e
        if _pool_crc(num_blocks, free, ref, parked) != snap.get("crc"):
            raise SnapshotError("pool snapshot CRC mismatch (torn capture)")
        ids = free + list(ref) + parked
        if any(b <= TRASH_BLOCK or b >= num_blocks for b in ids):
            raise SnapshotError("pool snapshot: block id out of range")
        if len(set(ids)) != len(ids):
            raise SnapshotError("pool snapshot: block in two states at once")
        pool = cls(num_blocks)
        pool._free = free
        pool._owned = set(ref)
        pool._ref = ref
        pool._parked = parked
        try:
            pool.check()
        except RuntimeError as e:
            raise SnapshotError(f"pool snapshot failed check(): {e}") from e
        counter_inc("serve_pool_restores")
        return pool
