"""int8-quantized-weights serving path.

Weight-only int8 over the decode weight tree via the existing PTQ machinery
(``quantization.quantize_to_int8``: symmetric per-tensor abs-max, the same
rounding the PTQ pass folds into checkpoints): every float matrix (>= 2-D —
projections, embeddings, the tied/untied head) is stored in HBM as an int8
array plus one f32 scale, ~4x smaller than f32, and dequantized inside the
compiled prefill/decode programs right before use (``q * scale / 127``).
1-D params (biases, norm gains) stay float — they are noise-critical and
tiny.

The tagged-dict encoding keeps the tree a plain pytree, so the same bucket
programs jit over either representation; ``dequantize_tree`` is traced into
the program, where XLA schedules the dequant next to the consuming matmul.

Tensor-parallel serving composes with this path BECAUSE the scale is
per-tensor: ``generation.tp_pack_params`` slices the int8 payload
column-wise per device and carries the single scalar scale to every shard,
so shard-then-dequant is bitwise the same numbers as dequant-then-shard —
weight-only int8 under tp keeps the concat-partitioned bit-identity
contract for free. (Per-channel scales would need slicing too; the tagged
dict keeps that door open.)
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["quantize_params", "dequantize_tree", "attach_int8_head"]

_TAG = "__int8__"


def quantize_params(tree):
    """Quantize every float array of rank >= 2 in a nested dict/list/tuple
    weight tree to ``{_TAG: int8, "scale": f32[]}``."""
    from ..quantization import quantize_to_int8

    def walk(node):
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v) for v in node)
        if hasattr(node, "ndim") and node.ndim >= 2 and \
                jnp.issubdtype(node.dtype, jnp.floating):
            q, scale = quantize_to_int8(node)
            return {_TAG: q._data, "scale": jnp.asarray(scale, jnp.float32)}
        return node

    return walk(tree)


def dequantize_tree(tree, dtype):
    """Inverse of :func:`quantize_params`, traced inside the compiled
    programs: tagged leaves become dense ``dtype`` arrays again. ``dtype``
    is static (closed over by the program), never part of the pytree."""

    def walk(node):
        if isinstance(node, dict):
            if _TAG in node:
                return (node[_TAG].astype(jnp.float32)
                        * (node["scale"] / 127.0)).astype(dtype)
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v) for v in node)
        return node

    return walk(tree)


def attach_int8_head(dense, tagged):
    """Graft the still-quantized LM-head weight onto a dequantized tree as
    ``dense["head_q"] = {"q": int8, "scale": f32[]}`` so the decode head can
    run the weight-only ``ops/kernels/int8_matmul`` kernel on the int8 bytes
    (1/4 the HBM traffic of the dequantized matrix) instead of the dense
    matmul over the dequant. The dense head entry is left in place — GPT's
    ``wte`` doubles as the embedding table, and XLA dead-code-eliminates the
    unused dequant when the kernel path consumes ``head_q``. ``tagged`` is
    the pre-dequant tree from :func:`quantize_params`; a tree whose head was
    never quantized passes through unchanged."""
    key = "head_w" if isinstance(tagged, dict) and "head_w" in tagged else "wte"
    leaf = tagged.get(key) if isinstance(tagged, dict) else None
    if isinstance(leaf, dict) and _TAG in leaf:
        dense = dict(dense)
        dense["head_q"] = {"q": leaf[_TAG], "scale": leaf["scale"]}
    return dense
