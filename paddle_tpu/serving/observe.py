"""Serving SLO observability (PR 20): request-scoped tracing, token-latency
histograms, a live telemetry endpoint, and cost-model drift tracking.

The serving stack's pre-existing telemetry is step-granular — host spans
(``schedule`` → ``admit``/``prefill``/``decode_step``) and flat counters.
This module extends the LazyTensor observable-runtime discipline from steps
to REQUESTS, in four layers (each inert until armed):

* **request tracing** (``FLAGS_serve_trace``) — ``Engine.submit`` assigns
  every request a process-unique trace id that rides the ``_Request``
  object itself.  Because the snapshot phase records, supervisor harvest,
  and handoff queue all carry ``_Request`` objects whole, the id survives
  crash recovery, snapshot re-attach, and engine→engine handoff with no
  extra plumbing; the supervisor's requeue path copies it onto the
  continuation request explicitly.  Scheduler spans that touch requests are
  tagged with a ``traces=(...)`` attr; a span observer
  (:func:`paddle_tpu.profiler.spans.add_span_observer`) routes each
  finished span into the per-request timeline.  Queue wait, shed
  decisions, prefix-cache matches, CoW copies, evictions and relays are
  synthesized directly (no live span needed).  Completed timelines land in
  a bounded ring (:class:`TraceBook`, ``FLAGS_serve_trace_ring``)
  exportable as chrome-trace or JSONL.
* **SLO histograms** — fixed-bucket, native (no deps), keyed by priority
  class: TTFT, inter-token gap, end-to-end latency, queue wait.  Per-token
  timestamps are device-cheap: ONE host clock read at the retire of each
  scheduler step, attributed to the rows that emitted tokens.  They flow
  into ``profiler.export_metrics()`` as proper Prometheus histogram (and a
  derived summary) types via the provider hook in ``profiler/export.py``.
* **telemetry endpoint** (``FLAGS_serve_metrics_port``) — an opt-in stdlib
  ``http.server`` thread serving ``/metrics`` (Prometheus text),
  ``/healthz`` + ``/readyz`` (the existing ``health()``/``ready()`` dicts
  as JSON, 200/503), and ``/debug/requests`` (live in-flight table:
  phase, age, blocks held, trace id).  Port 0 (default) = zero threads.
* **cost-model drift** — predicted-vs-actual for the three deployed
  predictors (shed-ETA step EMA + ``tp_collective`` floor vs measured step
  time; ``FLAGS_hbm_admission`` predicted peak vs post-step census;
  ``CostModel.kernel_estimate`` ordering vs autotune measured timings) as
  |relative-error| EMA gauges plus a ``cost_drift`` span attr — a drifting
  model becomes a dashboard line instead of a silent bad shed decision.

Everything here is O(1) per scheduler step amortized (per emitted token for
the gap histogram — the same order as the per-row work the scheduler
already does) and covered by ``bench_observe_overhead``.
"""
from __future__ import annotations

import collections
import itertools
import json
import threading
import time
from typing import Dict, List, Optional, Tuple

from .. import profiler
from ..framework.flags import flag
from ..profiler import spans as _spans
from ..profiler import export as _export

__all__ = [
    "Histogram", "TraceBook", "MetricsEndpoint",
    "enabled", "trace_book", "slo", "drift", "drift_value", "drift_gauges",
    "percentile", "reset", "start_endpoint",
]


def enabled() -> bool:
    return bool(flag("FLAGS_serve_trace", False))


# -- fixed-bucket histograms --------------------------------------------------

# Bucket upper bounds in SECONDS. Latency-shaped (roughly log-spaced):
# TTFT / end-to-end / queue wait share one layout; the inter-token gap gets
# a finer low end (decode steps are sub-millisecond on a warm engine).
LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
    0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)
GAP_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0,
)

# SLO metric name -> (bucket layout, help string)
SLO_METRICS = {
    "serve_ttft_seconds": (LATENCY_BUCKETS, "submit -> first generated token"),
    "serve_inter_token_seconds": (GAP_BUCKETS, "gap between consecutive tokens of one request"),
    "serve_e2e_seconds": (LATENCY_BUCKETS, "submit -> successful completion"),
    "serve_queue_seconds": (LATENCY_BUCKETS, "submit -> admission (queue wait)"),
}


class Histogram:
    """One fixed-bucket histogram (Prometheus ``histogram`` semantics:
    cumulative ``le`` buckets + ``_sum`` + ``_count``).  ``observe`` is a
    binary search + three integer bumps under a lock — the scheduler thread
    writes, the endpoint/export threads read snapshots."""

    __slots__ = ("buckets", "_counts", "_sum", "_count", "_lock")

    def __init__(self, buckets: Tuple[float, ...]):
        self.buckets = tuple(buckets)
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)  # guarded_by: _lock
        self._sum = 0.0  # guarded_by: _lock
        self._count = 0  # guarded_by: _lock

    def observe(self, value: float) -> None:
        import bisect

        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            s, c = self._sum, self._count
        cum = list(itertools.accumulate(counts))
        return {
            "buckets": list(self.buckets),
            "counts": counts,          # per-bucket (last = +Inf overflow)
            "cumulative": cum,         # Prometheus le-cumulative view
            "sum": s,
            "count": c,
        }


class _Slo:
    """The SLO metric layer: ``(metric, priority class)`` -> Histogram.
    Priority classes are the engine's integer priorities, labeled as
    strings; histograms are created on first observation per class."""

    def __init__(self):
        self._lock = threading.Lock()
        self._hists: Dict[Tuple[str, str], Histogram] = {}  # guarded_by: _lock

    def observe(self, metric: str, priority, value: float) -> None:
        key = (metric, str(int(priority)))
        h = self._hists.get(key)
        if h is None:
            with self._lock:
                h = self._hists.get(key)
                if h is None:
                    h = Histogram(SLO_METRICS[metric][0])
                    self._hists[key] = h
        h.observe(value)

    def snapshot(self) -> dict:
        with self._lock:
            items = list(self._hists.items())
        out: Dict[str, dict] = {}
        for (metric, prio), h in items:
            out.setdefault(metric, {})[prio] = h.snapshot()
        return out


# -- request timelines --------------------------------------------------------

_trace_ids = itertools.count(1)  # GIL-atomic; process-unique trace ids


class TraceBook:
    """Open + completed per-request timelines.  One book per process is
    shared by every traced engine: trace ids are process-unique, and a
    request's timeline must stay in ONE place while the request migrates
    between engines (supervisor restart, handoff).  The completed ring is
    bounded (``capacity``); the oldest timeline is evicted on overflow
    (``serve_trace_evicted``)."""

    def __init__(self, capacity: int = 256):
        self.capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        self._open: Dict[str, dict] = {}  # guarded_by: _lock
        self._done = collections.deque()  # guarded_by: _lock

    # -- lifecycle ---------------------------------------------------------
    def open(self, req_id: int, prompt_len: int, priority: int,
             trace: Optional[str] = None) -> str:
        tid = trace if trace is not None else f"t{next(_trace_ids)}"
        rec = {
            "trace": tid,
            "req_id": int(req_id),
            "prompt_len": int(prompt_len),
            "priority": int(priority),
            "t_open": time.perf_counter_ns(),
            "events": [],
            "outcome": None,
        }
        with self._lock:
            # a recovered request re-opens its original trace id on the new
            # engine: keep the accumulated events, only re-point req_id
            # (the requeue continuation has a fresh engine-local id)
            prev = self._open.get(tid)
            if prev is not None:
                prev["req_id"] = int(req_id)
            else:
                self._open[tid] = rec
        return tid

    def event(self, trace: Optional[str], name: str, t0: int, t1: int,
              **attrs) -> None:
        """Synthesize one timeline event (ns timestamps, the span clock).
        Falls back to the completed ring: a recovery relay lands AFTER the
        continuation already closed the timeline on the new engine."""
        if not trace:
            return
        ev = {"name": name, "t0": int(t0), "t1": int(t1), "attrs": attrs}
        with self._lock:
            tl = self._open.get(trace)
            if tl is None:
                for done in reversed(self._done):
                    if done["trace"] == trace:
                        tl = done
                        break
            if tl is not None:
                tl["events"].append(ev)

    def close(self, trace: Optional[str], outcome: str) -> None:
        if not trace:
            return
        with self._lock:
            tl = self._open.pop(trace, None)
            if tl is None:
                return
            tl["outcome"] = outcome
            tl["t_close"] = time.perf_counter_ns()
            self._done.append(tl)
            if len(self._done) > self.capacity:
                self._done.popleft()
                profiler.counter_inc("serve_trace_evicted")

    # -- span fan-in -------------------------------------------------------
    def span_observer(self, sp) -> None:
        """Registered with ``spans.add_span_observer``: any finished span
        tagged ``traces=(...)`` lands (attrs minus the tag) on every open
        timeline it names."""
        traces = sp.attrs.get("traces")
        if not traces:
            return
        attrs = {k: v for k, v in sp.attrs.items() if k != "traces"}
        ev = {"name": sp.name, "t0": sp.t0, "t1": sp.t1, "attrs": attrs}
        with self._lock:
            for t in traces:
                tl = self._open.get(t)
                if tl is not None:
                    tl["events"].append(ev)

    # -- inspection / export ----------------------------------------------
    def completed(self) -> List[dict]:
        with self._lock:
            return [dict(t, events=list(t["events"])) for t in self._done]

    def open_traces(self) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v, events=list(v["events"]))
                    for k, v in self._open.items()}

    def timeline(self, trace: str) -> Optional[dict]:
        with self._lock:
            tl = self._open.get(trace)
            if tl is None:
                for t in self._done:
                    if t["trace"] == trace:
                        tl = t
                        break
            return None if tl is None else dict(tl, events=list(tl["events"]))

    def chrome_trace(self, path: str) -> None:
        """Completed timelines as a chrome://tracing document — one display
        thread per request so timelines stack instead of interleaving."""
        events = []
        for i, tl in enumerate(self.completed()):
            events.append({
                "name": "thread_name", "ph": "M", "pid": 0, "tid": i,
                "args": {"name": f"{tl['trace']} req={tl['req_id']}"},
            })
            for ev in tl["events"]:
                events.append({
                    "name": ev["name"], "ph": "X", "cat": "request",
                    "ts": ev["t0"] / 1000.0,
                    "dur": max(ev["t1"] - ev["t0"], 0) / 1000.0,
                    "pid": 0, "tid": i,
                    "args": dict(ev["attrs"], trace=tl["trace"]),
                })
        from ..framework.io import atomic_open

        with atomic_open(path, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f,
                      default=str)

    def jsonl(self, path: str) -> None:
        from ..framework.io import atomic_open

        with atomic_open(path, "w") as f:
            for tl in self.completed():
                f.write(json.dumps(tl, default=str) + "\n")


# -- module singletons --------------------------------------------------------
# One book + one SLO layer per process (trace ids are process-unique and
# requests migrate between engines). Created lazily on the first traced
# engine; `reset()` gives tests/benches a clean slate.
_state_lock = threading.Lock()
_book: Optional[TraceBook] = None  # guarded_by: _state_lock
_slo: Optional[_Slo] = None  # guarded_by: _state_lock


def trace_book() -> TraceBook:
    global _book
    b = _book
    if b is None:
        with _state_lock:
            b = _book
            if b is None:
                b = TraceBook(int(flag("FLAGS_serve_trace_ring", 256)))
                _spans.add_span_observer(b.span_observer)
                _book = b
    return b


def slo() -> _Slo:
    global _slo
    s = _slo
    if s is None:
        with _state_lock:
            s = _slo
            if s is None:
                s = _Slo()
                _slo = s
    return s


def reset() -> None:
    """Drop all tracing/SLO/drift state (tests, bench isolation)."""
    global _book, _slo
    with _state_lock:
        if _book is not None:
            _spans.remove_span_observer(_book.span_observer)
        _book = None
        _slo = None
    with _drift_lock:
        _drift.clear()


# -- request lifecycle hooks (called by Engine/ServingSupervisor) -------------
# Every hook is only reached when the engine was constructed with tracing
# armed — the flag-off scheduler never imports or touches this module past
# the one boolean probe at engine construction (inert tripwire).

def on_submit(req, trace: Optional[str] = None) -> None:
    """Assign (or re-attach) the trace id and open the timeline."""
    req.trace = trace_book().open(
        req.id, len(req.prompt), req.priority, trace=trace
    )
    req.t_submit_ns = time.perf_counter_ns()


def on_admit(req) -> None:
    """Queue exit into prefill: synthesize the queue-wait span + observe."""
    now_ns = time.perf_counter_ns()
    trace_book().event(req.trace, "queue", req.t_submit_ns, now_ns)
    slo().observe("serve_queue_seconds", req.priority,
                  max(time.monotonic() - req.t_submit, 0.0))


def on_shed(req, kind: str) -> None:
    """Request shed from the queue (deadline doom/expiry): the queue span
    closes with the shed reason and the timeline completes as shed."""
    b = trace_book()
    b.event(req.trace, "queue", req.t_submit_ns, time.perf_counter_ns(),
            shed=kind)
    b.close(req.trace, "shed")


def on_prefix_match(req, tokens_matched: int, blocks: int) -> None:
    now = time.perf_counter_ns()
    trace_book().event(req.trace, "prefix_match", now, now,
                       tokens=int(tokens_matched), blocks=int(blocks))


def on_cow(trace: Optional[str], blocks: int) -> None:
    now = time.perf_counter_ns()
    trace_book().event(trace, "cow_copy", now, now, blocks=int(blocks))


def on_relay(req, tokens: int, error: Optional[str]) -> None:
    """Supervisor recovery relay stitched a continuation's output into the
    original handle — the last hop of a recovered request's timeline."""
    now = time.perf_counter_ns()
    trace_book().event(req.trace, "relay", now, now, tokens=int(tokens),
                       error=error)


def on_tokens(emitted, now_mono: float) -> None:
    """Per-token latency attribution. ``emitted`` is the list of requests
    that received a token this scheduler step; ``now_mono`` is the ONE host
    clock read taken at step retire."""
    s = slo()
    for req in emitted:
        if req.t_first_tok == 0.0:
            req.t_first_tok = now_mono
            s.observe("serve_ttft_seconds", req.priority,
                      max(now_mono - req.t_submit, 0.0))
        else:
            s.observe("serve_inter_token_seconds", req.priority,
                      max(now_mono - req.t_last_tok, 0.0))
        req.t_last_tok = now_mono


def on_done(req, error) -> None:
    """Terminal state: e2e latency (successes only — shed/cancelled would
    skew the SLO line) and timeline completion."""
    b = trace_book()
    if error is None:
        slo().observe("serve_e2e_seconds", req.priority,
                      max(time.monotonic() - req.t_submit, 0.0))
        b.close(req.trace, "ok")
    else:
        b.close(req.trace, type(error).__name__)


# -- cost-model drift ---------------------------------------------------------
_DRIFT_EMA = 0.8  # same smoothing the engine's step EMA uses

_drift_lock = threading.Lock()
_drift: Dict[str, dict] = {}  # guarded_by: _drift_lock


def drift(name: str, predicted: float, actual: float) -> float:
    """Record one predicted-vs-actual pair: |relative error| against the
    measurement, EMA-smoothed into the ``cost_drift`` gauge family.
    Returns this sample's relative error (the ``cost_drift`` span attr)."""
    denom = max(abs(float(actual)), 1e-12)
    rel = abs(float(predicted) - float(actual)) / denom
    return drift_value(name, rel, predicted=float(predicted),
                       actual=float(actual))


def drift_value(name: str, rel: float, **extra) -> float:
    """Record an already-computed drift sample (the kernel-estimate ORDER
    check has no single predicted/actual pair — its sample is the
    discordant-pair fraction between estimated and measured orderings)."""
    rel = float(rel)
    with _drift_lock:
        g = _drift.get(name)
        if g is None:
            g = {"rel_err": rel, "samples": 0}
            _drift[name] = g
        else:
            g["rel_err"] = _DRIFT_EMA * g["rel_err"] + (1 - _DRIFT_EMA) * rel
        g["samples"] += 1
        g["last_rel_err"] = rel
        g.update(extra)
    return rel


def drift_gauges() -> Dict[str, dict]:
    with _drift_lock:
        return {k: dict(v) for k, v in _drift.items()}


# -- derived views ------------------------------------------------------------

def percentile(metric: str, q: float, priority: Optional[int] = None) -> float:
    """Estimate a quantile from the fixed-bucket histogram (bucket upper
    bound with linear interpolation inside the bucket — the standard
    Prometheus ``histogram_quantile`` estimate). Merges priority classes
    unless one is named. Returns 0.0 with no observations."""
    snap = slo().snapshot().get(metric)
    if not snap:
        return 0.0
    if priority is not None:
        snap = {str(int(priority)): snap.get(str(int(priority)))}
    layouts = [s for s in snap.values() if s]
    if not layouts:
        return 0.0
    buckets = layouts[0]["buckets"]
    counts = [0] * (len(buckets) + 1)
    total = 0
    for s in layouts:
        for i, c in enumerate(s["counts"]):
            counts[i] += c
        total += s["count"]
    if total == 0:
        return 0.0
    rank = q * total
    cum = 0
    lo = 0.0
    for i, c in enumerate(counts):
        nxt = cum + c
        if nxt >= rank and c > 0:
            hi = buckets[i] if i < len(buckets) else buckets[-1]
            frac = (rank - cum) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        cum = nxt
        if i < len(buckets):
            lo = buckets[i]
    return buckets[-1]


def shed_gauges() -> Dict[str, float]:
    """Shed / deadline-miss RATES derived from the lifecycle counters
    (fractions of submitted requests; 0.0 before any traffic)."""
    c = profiler.counters()
    total = max(c.get("serve_requests", 0) + c.get("serve_shed", 0), 1)
    shed = c.get("serve_shed", 0) + c.get("serve_deadline_shed", 0)
    miss = c.get("serve_deadline_shed", 0) + c.get("serve_deadline_expired", 0)
    return {
        "serve_shed_rate": shed / total,
        "serve_deadline_miss_rate": miss / total,
    }


# -- export provider ----------------------------------------------------------

def _prom_lines() -> List[str]:
    lines: List[str] = []
    snap = slo().snapshot() if _slo is not None else {}
    for metric in sorted(snap):
        mn = "paddle_tpu_" + metric
        lines.append(f"# HELP {mn} {SLO_METRICS[metric][1]}")
        lines.append(f"# TYPE {mn} histogram")
        for prio in sorted(snap[metric]):
            s = snap[metric][prio]
            for le, cum in zip(
                [str(b) for b in s["buckets"]] + ["+Inf"], s["cumulative"]
            ):
                lines.append(
                    f'{mn}_bucket{{priority="{prio}",le="{le}"}} {cum}'
                )
            lines.append(f'{mn}_sum{{priority="{prio}"}} {s["sum"]}')
            lines.append(f'{mn}_count{{priority="{prio}"}} {s["count"]}')
    if "serve_e2e_seconds" in snap:
        # derived summary view (bucket-estimate quantiles) so dashboards
        # without histogram_quantile still get the headline percentiles
        mn = "paddle_tpu_serve_e2e_latency"
        lines.append(f"# TYPE {mn} summary")
        tot_sum = sum(s["sum"] for s in snap["serve_e2e_seconds"].values())
        tot_cnt = sum(s["count"] for s in snap["serve_e2e_seconds"].values())
        for q in (0.5, 0.9, 0.99):
            lines.append(
                f'{mn}{{quantile="{q}"}} {percentile("serve_e2e_seconds", q)}'
            )
        lines.append(f"{mn}_sum {tot_sum}")
        lines.append(f"{mn}_count {tot_cnt}")
    for name, g in sorted(drift_gauges().items()):
        mn = "paddle_tpu_cost_drift"
        if not any(line.startswith(f"# TYPE {mn} ") for line in lines):
            lines.append(f"# TYPE {mn} gauge")
        lines.append(f'{mn}{{model="{name}"}} {g["rel_err"]}')
    for name, val in sorted(shed_gauges().items()):
        mn = "paddle_tpu_" + name
        lines.append(f"# TYPE {mn} gauge")
        lines.append(f"{mn} {val}")
    return lines


def _json_snapshot() -> dict:
    return {
        "slo": slo().snapshot() if _slo is not None else {},
        "cost_drift": drift_gauges(),
        "rates": shed_gauges(),
    }


def _provider():
    return _prom_lines(), _json_snapshot()


_export.register_metric_provider("serving", _provider)


# -- telemetry endpoint -------------------------------------------------------

class MetricsEndpoint:
    """Opt-in stdlib HTTP telemetry server (one daemon thread + the
    per-connection threads ``ThreadingHTTPServer`` spawns).  Routes:

    * ``GET /metrics``        — Prometheus text exposition (counters,
      gauges, SLO histograms, drift gauges);
    * ``GET /healthz``        — ``target.health()`` as JSON, 200 when
      ``ok`` else 503 (liveness);
    * ``GET /readyz``         — ``target.ready()`` as JSON, 200 when
      ``ready`` else 503 (traffic admission);
    * ``GET /debug/requests`` — live in-flight request table (phase, age,
      blocks held, trace id) from ``target.debug_requests()``.

    Holds the target (Engine or ServingSupervisor) behind a weakref so the
    endpoint never keeps a closed engine alive; a dead target answers 503.
    """

    def __init__(self, target, port: int, host: str = ""):
        import http.server
        import weakref

        self._target_ref = weakref.ref(target)
        outer = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            # telemetry must never spam the serving process's stderr
            def log_message(self, *args):
                pass

            def _send(self, code: int, body: str,
                      ctype: str = "application/json"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                profiler.counter_inc("serve_http_requests")
                path = self.path.split("?", 1)[0]
                target = outer._target_ref()
                try:
                    if path == "/metrics":
                        self._send(
                            200, profiler.export_metrics(format="prometheus"),
                            ctype="text/plain; version=0.0.4",
                        )
                    elif path in ("/healthz", "/readyz"):
                        if target is None:
                            self._send(503, json.dumps(
                                {"ok": False, "error": "engine gone"}))
                            return
                        if path == "/healthz":
                            h = target.health()
                            ok = bool(h.get("ok"))
                        else:
                            h = target.ready()
                            ok = bool(h.get("ready"))
                        self._send(200 if ok else 503,
                                   json.dumps(h, default=str))
                    elif path == "/debug/requests":
                        rows = [] if target is None else target.debug_requests()
                        self._send(200, json.dumps(rows, default=str))
                    else:
                        self._send(404, json.dumps({"error": "not found"}))
                except BrokenPipeError:
                    pass
                except Exception as e:
                    try:
                        self._send(500, json.dumps({"error": repr(e)}))
                    except Exception:
                        pass

        http.server.ThreadingHTTPServer.allow_reuse_address = True
        self._server = http.server.ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.1},
            daemon=True, name="serve-metrics",
        )
        self._thread.start()

    def close(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:
            pass
        self._thread.join(timeout=5.0)


def start_endpoint(target, port: int):
    """Start the telemetry endpoint, or return None (with a counter bump)
    when the port can't be bound — telemetry must never take serving down."""
    try:
        return MetricsEndpoint(target, int(port))
    except OSError:
        profiler.counter_inc("serve_http_bind_failed")
        return None
