"""paddle.signal — STFT family.

Parity: reference ``python/paddle/signal.py`` (stft:183, istft:326, backed by
frame/overlap_add ops in ``paddle/fluid/operators/``). TPU-native: framing is
a gather, the transform is XLA's FFT HLO, overlap-add is a segment scatter —
all fused under jit.
"""
from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import as_tensor, eager_call


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice overlapping frames along ``axis`` (reference signal.py frame):
    axis=-1 -> (..., frame_length, num_frames); axis=0 -> (num_frames,
    frame_length, ...)."""
    t = as_tensor(x)

    def fn(a, frame_length=0, hop_length=0, axis=-1):
        if axis not in (-1, a.ndim - 1):
            a = jnp.moveaxis(a, axis, -1)
        n = a.shape[-1]
        num = 1 + (n - frame_length) // hop_length
        starts = jnp.arange(num) * hop_length
        idx = starts[:, None] + jnp.arange(frame_length)[None, :]
        out = jnp.moveaxis(a[..., idx], -2, -1)  # (..., frame_length, num)
        if axis not in (-1, out.ndim - 2):
            # frame axis expands to (frame_length, num) at its position
            out = jnp.moveaxis(out, (-2, -1), (axis + 1, axis))
        return out

    return eager_call(
        "signal.frame", fn, [t],
        attrs={"frame_length": int(frame_length), "hop_length": int(hop_length),
               "axis": int(axis)},
    )


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame (reference signal.py overlap_add)."""
    t = as_tensor(x)

    def fn(a, hop_length=0, axis=-1):
        if axis not in (-1, a.ndim - 1):
            a = jnp.moveaxis(a, (axis, axis + 1), (-1, -2))
        # (..., frame_length, num) -> (..., n)
        fl, num = a.shape[-2], a.shape[-1]
        n = (num - 1) * hop_length + fl
        vals = jnp.moveaxis(a, -1, -2).reshape(a.shape[:-2] + (-1,))  # (..., num*fl)
        # scatter-add each frame onto the output line
        idx = (jnp.arange(num)[:, None] * hop_length + jnp.arange(fl)[None, :]).reshape(-1)
        out = jnp.zeros(a.shape[:-2] + (n,), a.dtype)
        out = out.at[..., idx].add(vals)
        if axis not in (-1, out.ndim - 1):
            out = jnp.moveaxis(out, -1, axis)
        return out

    return eager_call(
        "signal.overlap_add", fn, [t],
        attrs={"hop_length": int(hop_length), "axis": int(axis)},
    )


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """Short-time Fourier transform (reference signal.py:183)."""
    t = as_tensor(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    args = [t]
    if window is not None:
        args.append(as_tensor(window))

    def fn(a, *w, n_fft=0, hop=0, win_length=0, center=True, pad_mode="reflect",
           normalized=False, onesided=True):
        if center:
            pad = [(0, 0)] * (a.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            a = jnp.pad(a, pad, mode=pad_mode)
        n = a.shape[-1]
        num = 1 + (n - n_fft) // hop
        starts = jnp.arange(num) * hop
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]
        frames = a[..., idx]  # (..., num, n_fft)
        if w:
            win = w[0]
            if win_length < n_fft:
                lpad = (n_fft - win_length) // 2
                win = jnp.pad(win, (lpad, n_fft - win_length - lpad))
            frames = frames * win
        spec = jnp.fft.rfft(frames, axis=-1) if onesided else jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return jnp.swapaxes(spec, -1, -2)  # (..., freq, num_frames)

    return eager_call(
        "signal.stft", fn, args,
        attrs={"n_fft": int(n_fft), "hop": int(hop_length), "win_length": int(win_length),
               "center": bool(center), "pad_mode": pad_mode,
               "normalized": bool(normalized), "onesided": bool(onesided)},
    )


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False, name=None):
    """Inverse STFT with window-envelope normalization (reference signal.py:326)."""
    t = as_tensor(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    args = [t]
    if window is not None:
        args.append(as_tensor(window))

    def fn(spec, *w, n_fft=0, hop=0, win_length=0, center=True,
           normalized=False, onesided=True, length=0):
        spec = jnp.swapaxes(spec, -1, -2)  # (..., num, freq)
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        frames = jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided else jnp.fft.ifft(spec, axis=-1).real
        if w:
            win = w[0]
            if win_length < n_fft:
                lpad = (n_fft - win_length) // 2
                win = jnp.pad(win, (lpad, n_fft - win_length - lpad))
        else:
            win = jnp.ones((n_fft,), frames.dtype)
        frames = frames * win
        num = frames.shape[-2]
        n = (num - 1) * hop + n_fft
        idx2 = (jnp.arange(num)[:, None] * hop + jnp.arange(n_fft)[None, :]).reshape(-1)
        out = jnp.zeros(frames.shape[:-2] + (n,), frames.dtype)
        out = out.at[..., idx2].add(frames.reshape(frames.shape[:-2] + (-1,)))
        env = jnp.zeros((n,), frames.dtype).at[idx2].add(jnp.tile(win * win, num))
        out = out / jnp.maximum(env, 1e-11)
        if center:
            out = out[..., n_fft // 2: n - n_fft // 2]
        if length:
            out = out[..., :length]
        return out

    return eager_call(
        "signal.istft", fn, args,
        attrs={"n_fft": int(n_fft), "hop": int(hop_length), "win_length": int(win_length),
               "center": bool(center), "normalized": bool(normalized),
               "onesided": bool(onesided), "length": int(length or 0)},
    )


__all__ = ["frame", "overlap_add", "stft", "istft"]
