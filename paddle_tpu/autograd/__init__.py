"""paddle.autograd parity.

Reference: ``python/paddle/autograd/`` — ``backward``, functional ``grad``
(C++ PartialGradEngine, ``paddle/fluid/imperative/partial_grad_engine.cc``)
and ``PyLayer`` custom autograd (``python/paddle/autograd/py_layer.py``).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax

from ..core.engine import run_backward, no_grad, enable_grad, set_grad_enabled  # noqa: F401
from ..core.engine import GradNode
from ..core.tensor import Tensor
from ..core.dispatch import as_tensor


def backward(tensors, grad_tensors=None, retain_graph=False):
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    run_backward(tensors, grad_tensors, retain_graph=retain_graph)


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    only_inputs=True,
    allow_unused=False,
    no_grad_vars=None,
):
    """paddle.grad — partial gradients without touching ``.grad`` slots."""
    outputs = [outputs] if isinstance(outputs, Tensor) else list(outputs)
    inputs = [inputs] if isinstance(inputs, Tensor) else list(inputs)
    if grad_outputs is not None and isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]
    capture = {id(t): t for t in inputs}
    captured = run_backward(
        outputs,
        grad_outputs,
        retain_graph=bool(retain_graph) or create_graph,
        capture=capture,
        accumulate_leaves=False,
        create_graph=create_graph,
    )
    results = []
    for t in inputs:
        g = captured.get(id(t))
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    f"Tensor {t.name} is unreachable from outputs (set allow_unused=True to return None)"
                )
            results.append(None)
        elif isinstance(g, Tensor):
            results.append(g)
        else:
            results.append(Tensor(g, stop_gradient=True))
    return results


class PyLayerContext:
    def __init__(self):
        self._saved = []
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """Custom autograd op (reference py_layer.py:PyLayer).

    Subclass with static ``forward(ctx, *args)`` and ``backward(ctx, *grads)``
    operating on Tensors.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core.engine import grad_enabled

        ctx = PyLayerContext()
        with no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)
        single = isinstance(outputs, Tensor)
        outs = [outputs] if single else list(outputs)

        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        need_grad = grad_enabled() and any(not t.stop_gradient for t in tensor_inputs)
        if not need_grad:
            return outputs

        def vjp_fn(cts):
            if not isinstance(cts, tuple):
                cts = (cts,)
            ct_tensors = [Tensor(c, stop_gradient=True) for c in cts]
            with no_grad():
                grads = cls.backward(ctx, *ct_tensors)
            if isinstance(grads, Tensor) or grads is None:
                grads = (grads,)
            out = []
            gi = iter(grads)
            for a in args:
                if isinstance(a, Tensor):
                    g = next(gi, None)
                    out.append(None if g is None else g._data)
            return tuple(out)

        routes = []
        for t in tensor_inputs:
            if t.stop_gradient:
                routes.append(None)
            elif t._grad_node is not None:
                routes.append(("node", t._grad_node, t._out_index))
            else:
                routes.append(("leaf", t))
        out_avals = [(tuple(o._data.shape), o._data.dtype) for o in outs]
        node = GradNode(cls.__name__, vjp_fn, routes, out_avals, multi=not single)
        import weakref

        refs = []
        for i, o in enumerate(outs):
            o.stop_gradient = False
            o._grad_node = node
            o._out_index = i
            refs.append(weakref.ref(o))
        node.out_tensors = refs
        return outputs


def is_grad_enabled():
    from ..core.engine import grad_enabled

    return grad_enabled()


# Functional jacobian/hessian (reference: paddle.autograd.functional)
def jacobian(func, xs, create_graph=False, allow_unused=False):
    single = isinstance(xs, Tensor)
    xs_l = [xs] if single else list(xs)
    arrays = [t._data for t in xs_l]

    def f(*arrs):
        ts = [Tensor(a, stop_gradient=False) for a in arrs]
        out = func(ts[0] if single else ts)
        return out._data if isinstance(out, Tensor) else out

    jac = jax.jacrev(f, argnums=tuple(range(len(arrays))))(*arrays)
    if single:
        return Tensor(jac[0])
    return tuple(Tensor(j) for j in jac)


def hessian(func, xs, create_graph=False, allow_unused=False):
    single = isinstance(xs, Tensor)
    xs_l = [xs] if single else list(xs)
    arrays = [t._data for t in xs_l]

    def f(*arrs):
        ts = [Tensor(a, stop_gradient=False) for a in arrs]
        out = func(ts[0] if single else ts)
        return out._data if isinstance(out, Tensor) else out

    hess = jax.hessian(f, argnums=tuple(range(len(arrays))))(*arrays)
    if single:
        return Tensor(hess[0][0])
    return tuple(tuple(Tensor(h) for h in row) for row in hess)
