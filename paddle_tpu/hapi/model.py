"""High-level Model API (Keras-style).

Parity: reference ``python/paddle/hapi/model.py:906`` — prepare/fit/evaluate/
predict/save/load + callbacks. The dygraph adapter path (``:247``) maps here
to the eager engine; the perf path runs each batch through a compiled train
step (paddle_tpu.jit) — the analogue of the reference's static adapter.
"""
from __future__ import annotations

import os
import time
from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..framework.io import load as fload
from ..framework.io import save as fsave
from ..io import DataLoader
from ..metric import Metric
from .callbacks import Callback, ProgBarLogger, config_callbacks


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._loss = None
        self._optimizer = None
        self._metrics: List[Metric] = []
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, (list, tuple)):
            self._metrics = list(metrics)
        else:
            self._metrics = [metrics]

    # -- single-batch ops --------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) else [labels]
        outputs = self.network(*[Tensor(i) if not isinstance(i, Tensor) else i for i in inputs])
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        loss = self._loss(*(outs + [l if isinstance(l, Tensor) else Tensor(l) for l in labels]))
        loss_t = loss if isinstance(loss, Tensor) else loss[0]
        loss_t.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = []
        for m in self._metrics:
            m.update(m.compute(outs[0], *labels))
            metrics.append(m.accumulate())
        return ([float(loss_t.item())], metrics) if metrics else [float(loss_t.item())]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) else [labels]
        from ..core.engine import no_grad

        with no_grad():
            outputs = self.network(*[Tensor(i) if not isinstance(i, Tensor) else i for i in inputs])
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        results = []
        if self._loss is not None and labels:
            loss = self._loss(*(outs + [l if isinstance(l, Tensor) else Tensor(l) for l in labels]))
            loss_t = loss if isinstance(loss, Tensor) else loss[0]
            results.append(float(loss_t.item()))
        metrics = []
        for m in self._metrics:
            m.update(m.compute(outs[0], *labels))
            metrics.append(m.accumulate())
        return (results, metrics) if metrics else results

    def predict_batch(self, inputs):
        self.network.eval()
        from ..core.engine import no_grad

        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        with no_grad():
            outputs = self.network(*[Tensor(i) if not isinstance(i, Tensor) else i for i in inputs])
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        return [o.numpy() for o in outs]

    # -- loops -------------------------------------------------------------
    def fit(
        self,
        train_data=None,
        eval_data=None,
        batch_size=1,
        epochs=1,
        eval_freq=1,
        log_freq=10,
        save_dir=None,
        save_freq=1,
        verbose=2,
        drop_last=False,
        shuffle=True,
        num_workers=0,
        callbacks=None,
        accumulate_grad_batches=1,
        num_iters=None,
        device_prefetch=0,
        stability=None,
    ):
        # Training stability sentinel (fault/sentinel.py): `stability` is a
        # configured StabilitySentinel, True (build one from the
        # FLAGS_stability_* registry), or None — in which case the flag
        # registry decides. Disabled cost: this one probe per fit() call.
        sentinel = self._resolve_sentinel(stability)
        # device_prefetch=N stages the next N batches ON DEVICE while the
        # current step runs (the PR 6 DevicePrefetcher double-buffering,
        # plumbed through to the fit loop — ROADMAP item 2 leftover). 0 = off.
        device_prefetch = int(device_prefetch or 0)
        wrap_prefetch = False
        if not isinstance(train_data, DataLoader):
            train_loader = DataLoader(
                train_data, batch_size=batch_size, shuffle=shuffle,
                drop_last=drop_last, num_workers=num_workers,
                device_prefetch=device_prefetch,
            )
        else:
            train_loader = train_data
            # a loader built with its own device_prefetch already returns a
            # prefetching iterator — don't double-buffer the double-buffer
            wrap_prefetch = (device_prefetch > 0
                             and not getattr(train_loader, "device_prefetch", 0))
        eval_loader = None
        if eval_data is not None:
            eval_loader = eval_data if isinstance(eval_data, DataLoader) else DataLoader(eval_data, batch_size=batch_size)

        cbks = config_callbacks(
            callbacks, model=self, epochs=epochs, steps=len(train_loader),
            log_freq=log_freq, save_freq=save_freq, save_dir=save_dir,
            verbose=verbose, metrics=self._metrics_name(),
        )
        if sentinel is not None:
            if device_prefetch:
                # the sentinel loop manages the loader position directly for
                # rollback replay and does not wrap a DevicePrefetcher; warn
                # rather than silently dropping the requested double-buffer
                import warnings

                warnings.warn(
                    "Model.fit: device_prefetch is not supported together "
                    "with the stability sentinel yet; training proceeds "
                    "without device-side input double-buffering"
                )
            return self._fit_sentinel_loop(
                sentinel, train_loader, eval_loader, cbks, epochs=epochs,
                batch_size=batch_size, eval_freq=eval_freq,
                save_dir=save_dir, save_freq=save_freq, num_iters=num_iters,
            )
        cbks.on_begin("train")
        steps_done = 0
        for epoch in range(epochs):
            for m in self._metrics:
                m.reset()
            cbks.on_epoch_begin(epoch)
            logs = {}
            epoch_iter = train_loader
            if wrap_prefetch:
                from ..io import device_prefetch as _device_prefetch

                epoch_iter = _device_prefetch(
                    train_loader, buffer_size=device_prefetch)
            try:
                for step, batch in enumerate(epoch_iter):
                    cbks.on_batch_begin("train", step, logs)
                    ins, labs = self._split_batch(batch)
                    result = self.train_batch(ins, labs)
                    logs = self._make_logs(result)
                    logs["step"] = step
                    logs["batch_size"] = batch_size
                    cbks.on_batch_end("train", step, logs)
                    steps_done += 1
                    if num_iters is not None and steps_done >= num_iters:
                        break
            finally:
                if epoch_iter is not train_loader:
                    # an early break must not leave the prefetch thread
                    # staging batches against an abandoned epoch
                    epoch_iter.close()
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, str(epoch)))
            if self.stop_training or (num_iters is not None and steps_done >= num_iters):
                break
        cbks.on_end("train", logs)
        if save_dir:
            self.save(os.path.join(save_dir, "final"))

    # -- training stability sentinel wiring --------------------------------
    def _resolve_sentinel(self, stability):
        from ..framework import flags as _flags

        if stability is None:
            if not _flags.flag("FLAGS_stability_enable", False):
                return None
        elif not stability:
            return None  # explicit opt-out (False/0) overrides the flag
        from ..fault.sentinel import StabilitySentinel

        if isinstance(stability, StabilitySentinel):
            return stability
        s = StabilitySentinel.from_flags()
        s._auto = True  # fit owns it: closed (tap disarmed) when fit returns
        return s

    def _fit_sentinel_loop(self, sentinel, train_loader, eval_loader, cbks,
                           epochs, batch_size, eval_freq, save_dir, save_freq,
                           num_iters):
        """The fit loop with the stability sentinel in the step path: per
        batch — chaos-spike consult, backward, device-side signal pack,
        verdict handling (skip discards the update and quarantines the
        batch; rollback restores model+optimizer+LR+RNG+loader from the
        anchor and replays with quarantined batches skipped at the index
        level; halt raises StabilityError after a flight post-mortem) — plus
        periodic anchor checkpoints keyed by global step."""
        from ..core.random import program_rng

        opt = self._optimizer
        state = {
            "model": self.network, "optimizer": opt,
            "loader": train_loader, "rng": program_rng,
        }
        params = [p for p in self.network.parameters() if not p.stop_gradient]
        try:
            self._fit_sentinel_body(
                sentinel, train_loader, eval_loader, cbks, epochs, batch_size,
                eval_freq, save_dir, save_freq, num_iters, state, params,
            )
        finally:
            if getattr(sentinel, "_auto", False):
                sentinel.close()

    def _fit_sentinel_body(self, sentinel, train_loader, eval_loader, cbks,
                           epochs, batch_size, eval_freq, save_dir, save_freq,
                           num_iters, state, params):
        from ..fault import inject as _inject

        cbks.on_begin("train")
        global_step = 0  # steps that reached a verdict (trained or skipped)
        epoch0 = train_loader._epoch
        logs = {}
        cur_epoch = None
        done = False
        while not done and train_loader._epoch - epoch0 < epochs:
            if cur_epoch != train_loader._epoch:
                cur_epoch = train_loader._epoch
                for m in self._metrics:
                    m.reset()
                cbks.on_epoch_begin(cur_epoch - epoch0)
            it = train_loader._stateful_iter()
            restarted = False
            while True:
                pos = (train_loader._epoch, train_loader._batch_idx)
                if sentinel.is_quarantined(pos=pos):
                    if not it.skip_batch():
                        break  # quarantined batch was the epoch's last
                    global_step += 1
                    continue
                try:
                    batch = next(it)
                except StopIteration:
                    break
                cbks.on_batch_begin("train", pos[1], logs)
                ins, labs = self._split_batch(batch)
                result, verdict = self._sentinel_train_batch(
                    sentinel, global_step, pos, ins, labs, params,
                    train_loader, _inject,
                )
                if verdict is not None and verdict.action == "rollback":
                    anchor_step = sentinel.rollback(verdict, state)
                    global_step = anchor_step + 1
                    restarted = True
                    break
                if verdict is not None and verdict.action == "halt":
                    sentinel.halt(verdict)
                if result is not None:
                    logs = self._make_logs(result)
                    logs["step"] = pos[1]
                    logs["batch_size"] = batch_size
                    cbks.on_batch_end("train", pos[1], logs)
                global_step += 1
                sentinel.maybe_anchor(global_step - 1, state)
                if num_iters is not None and global_step >= num_iters:
                    done = True
                    break
            if restarted:
                cur_epoch = None  # re-enter at the restored loader position
                continue
            if done or self.stop_training:
                break
            # epoch completed (the _StatefulIter rolled the loader forward)
            ep = cur_epoch - epoch0
            if eval_loader is not None and (ep + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbks.on_epoch_end(ep, logs)
            if save_dir and (ep + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, str(ep)))
        cbks.on_end("train", logs)
        if save_dir:
            self.save(os.path.join(save_dir, "final"))

    def _sentinel_train_batch(self, sentinel, step, pos, inputs, labels,
                              params, loader, _inject):
        """One sentinel-guarded train step. Returns ``(result, verdict)`` —
        ``result`` is None when the update was withheld (skip/rollback/halt
        verdicts; the optimizer never ran)."""
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) else [labels]
        outputs = self.network(
            *[Tensor(i) if not isinstance(i, Tensor) else i for i in inputs]
        )
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        loss = self._loss(
            *(outs + [l if isinstance(l, Tensor) else Tensor(l) for l in labels])
        )
        loss_t = loss if isinstance(loss, Tensor) else loss[0]
        if _inject.armed():
            s = _inject.spike("loss.spike", step=step)
            if s is not None:
                loss_t = loss_t * s
        loss_t.backward()
        if _inject.armed():
            s = _inject.spike("grad.spike", step=step)
            if s is not None:
                for p in params:
                    if p.grad is not None:
                        p.grad._set_data((p.grad * s)._data)
        verdict = sentinel.observe(
            step,
            loss=loss_t,
            grads=[p.grad for p in params if p.grad is not None],
            params=params,
            lr=self._optimizer.get_lr(),
            pos=pos,
            indices_fn=lambda e=pos[0], b=pos[1]: loader.batch_indices(e, b),
        )
        if verdict is not None:
            # any verdict withholds this step's update: a same-step skip by
            # policy; a late rollback/halt because the half-finished step is
            # discarded with the poisoned timeline anyway
            self._optimizer.clear_grad()
            return None, verdict
        self._optimizer.step()
        self._optimizer.clear_grad()
        metrics = []
        for m in self._metrics:
            m.update(m.compute(outs[0], *labels))
            metrics.append(m.accumulate())
        result = ([float(loss_t.item())], metrics) if metrics else [float(loss_t.item())]
        return result, None

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2, num_workers=0, callbacks=None, num_samples=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else DataLoader(eval_data, batch_size=batch_size)
        for m in self._metrics:
            m.reset()
        logs = {}
        for step, batch in enumerate(loader):
            ins, labs = self._split_batch(batch)
            result = self.eval_batch(ins, labs)
            logs = self._make_logs(result)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False, verbose=1, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else DataLoader(test_data, batch_size=batch_size)
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch, has_label=False)
            outputs.append(self.predict_batch(ins))
        if stack_outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs]) for i in range(n_out)]
        return outputs

    # -- persistence -------------------------------------------------------
    def save(self, path, training=True):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = fload(path + ".pdparams")
        self.network.set_state_dict(state)
        if not reset_optimizer and self._optimizer is not None and os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fload(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        return summary(self.network, input_size, dtype)

    # -- helpers -----------------------------------------------------------
    def _split_batch(self, batch, has_label=True):
        if isinstance(batch, (list, tuple)):
            if has_label and len(batch) >= 2:
                return list(batch[:-1]), [batch[-1]]
            return list(batch), []
        return [batch], []

    def _metrics_name(self):
        names = ["loss"]
        for m in self._metrics:
            names.extend(m.name() if isinstance(m.name(), list) else [m.name()])
        return names

    def _make_logs(self, result):
        logs = {}
        if isinstance(result, tuple):
            losses, metrics = result
            logs["loss"] = losses[0]
            for m, v in zip(self._metrics, metrics):
                names = m.name() if isinstance(m.name(), list) else [m.name()]
                vals = v if isinstance(v, list) else [v]
                for n, val in zip(names, vals):
                    logs[n] = val
        else:
            logs["loss"] = result[0]
        return logs


def summary(net, input_size=None, dtypes=None, input=None):
    """paddle.summary parity — parameter table."""
    rows = []
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = p.size
        total += n
        if not p.stop_gradient:
            trainable += n
        rows.append((name, tuple(p.shape), n))
    width = max([len(r[0]) for r in rows], default=20) + 2
    lines = [f"{'Layer (param)':{width}s} {'Shape':20s} {'Param #':>12s}"]
    lines.append("-" * (width + 34))
    for name, shape, n in rows:
        lines.append(f"{name:{width}s} {str(shape):20s} {n:12,d}")
    lines.append("-" * (width + 34))
    lines.append(f"Total params: {total:,d}")
    lines.append(f"Trainable params: {trainable:,d}")
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}
