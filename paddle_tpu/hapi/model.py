"""High-level Model API (Keras-style).

Parity: reference ``python/paddle/hapi/model.py:906`` — prepare/fit/evaluate/
predict/save/load + callbacks. The dygraph adapter path (``:247``) maps here
to the eager engine; the perf path runs each batch through a compiled train
step (paddle_tpu.jit) — the analogue of the reference's static adapter.
"""
from __future__ import annotations

import os
import time
from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..framework.io import load as fload
from ..framework.io import save as fsave
from ..io import DataLoader
from ..metric import Metric
from .callbacks import Callback, ProgBarLogger, config_callbacks


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._loss = None
        self._optimizer = None
        self._metrics: List[Metric] = []
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, (list, tuple)):
            self._metrics = list(metrics)
        else:
            self._metrics = [metrics]

    # -- single-batch ops --------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) else [labels]
        outputs = self.network(*[Tensor(i) if not isinstance(i, Tensor) else i for i in inputs])
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        loss = self._loss(*(outs + [l if isinstance(l, Tensor) else Tensor(l) for l in labels]))
        loss_t = loss if isinstance(loss, Tensor) else loss[0]
        loss_t.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = []
        for m in self._metrics:
            m.update(m.compute(outs[0], *labels))
            metrics.append(m.accumulate())
        return ([float(loss_t.item())], metrics) if metrics else [float(loss_t.item())]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) else [labels]
        from ..core.engine import no_grad

        with no_grad():
            outputs = self.network(*[Tensor(i) if not isinstance(i, Tensor) else i for i in inputs])
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        results = []
        if self._loss is not None and labels:
            loss = self._loss(*(outs + [l if isinstance(l, Tensor) else Tensor(l) for l in labels]))
            loss_t = loss if isinstance(loss, Tensor) else loss[0]
            results.append(float(loss_t.item()))
        metrics = []
        for m in self._metrics:
            m.update(m.compute(outs[0], *labels))
            metrics.append(m.accumulate())
        return (results, metrics) if metrics else results

    def predict_batch(self, inputs):
        self.network.eval()
        from ..core.engine import no_grad

        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        with no_grad():
            outputs = self.network(*[Tensor(i) if not isinstance(i, Tensor) else i for i in inputs])
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        return [o.numpy() for o in outs]

    # -- loops -------------------------------------------------------------
    def fit(
        self,
        train_data=None,
        eval_data=None,
        batch_size=1,
        epochs=1,
        eval_freq=1,
        log_freq=10,
        save_dir=None,
        save_freq=1,
        verbose=2,
        drop_last=False,
        shuffle=True,
        num_workers=0,
        callbacks=None,
        accumulate_grad_batches=1,
        num_iters=None,
        device_prefetch=0,
    ):
        # device_prefetch=N stages the next N batches ON DEVICE while the
        # current step runs (the PR 6 DevicePrefetcher double-buffering,
        # plumbed through to the fit loop — ROADMAP item 2 leftover). 0 = off.
        device_prefetch = int(device_prefetch or 0)
        wrap_prefetch = False
        if not isinstance(train_data, DataLoader):
            train_loader = DataLoader(
                train_data, batch_size=batch_size, shuffle=shuffle,
                drop_last=drop_last, num_workers=num_workers,
                device_prefetch=device_prefetch,
            )
        else:
            train_loader = train_data
            # a loader built with its own device_prefetch already returns a
            # prefetching iterator — don't double-buffer the double-buffer
            wrap_prefetch = (device_prefetch > 0
                             and not getattr(train_loader, "device_prefetch", 0))
        eval_loader = None
        if eval_data is not None:
            eval_loader = eval_data if isinstance(eval_data, DataLoader) else DataLoader(eval_data, batch_size=batch_size)

        cbks = config_callbacks(
            callbacks, model=self, epochs=epochs, steps=len(train_loader),
            log_freq=log_freq, save_freq=save_freq, save_dir=save_dir,
            verbose=verbose, metrics=self._metrics_name(),
        )
        cbks.on_begin("train")
        steps_done = 0
        for epoch in range(epochs):
            for m in self._metrics:
                m.reset()
            cbks.on_epoch_begin(epoch)
            logs = {}
            epoch_iter = train_loader
            if wrap_prefetch:
                from ..io import device_prefetch as _device_prefetch

                epoch_iter = _device_prefetch(
                    train_loader, buffer_size=device_prefetch)
            try:
                for step, batch in enumerate(epoch_iter):
                    cbks.on_batch_begin("train", step, logs)
                    ins, labs = self._split_batch(batch)
                    result = self.train_batch(ins, labs)
                    logs = self._make_logs(result)
                    logs["step"] = step
                    logs["batch_size"] = batch_size
                    cbks.on_batch_end("train", step, logs)
                    steps_done += 1
                    if num_iters is not None and steps_done >= num_iters:
                        break
            finally:
                if epoch_iter is not train_loader:
                    # an early break must not leave the prefetch thread
                    # staging batches against an abandoned epoch
                    epoch_iter.close()
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, str(epoch)))
            if self.stop_training or (num_iters is not None and steps_done >= num_iters):
                break
        cbks.on_end("train", logs)
        if save_dir:
            self.save(os.path.join(save_dir, "final"))

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2, num_workers=0, callbacks=None, num_samples=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else DataLoader(eval_data, batch_size=batch_size)
        for m in self._metrics:
            m.reset()
        logs = {}
        for step, batch in enumerate(loader):
            ins, labs = self._split_batch(batch)
            result = self.eval_batch(ins, labs)
            logs = self._make_logs(result)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False, verbose=1, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else DataLoader(test_data, batch_size=batch_size)
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch, has_label=False)
            outputs.append(self.predict_batch(ins))
        if stack_outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs]) for i in range(n_out)]
        return outputs

    # -- persistence -------------------------------------------------------
    def save(self, path, training=True):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = fload(path + ".pdparams")
        self.network.set_state_dict(state)
        if not reset_optimizer and self._optimizer is not None and os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fload(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        return summary(self.network, input_size, dtype)

    # -- helpers -----------------------------------------------------------
    def _split_batch(self, batch, has_label=True):
        if isinstance(batch, (list, tuple)):
            if has_label and len(batch) >= 2:
                return list(batch[:-1]), [batch[-1]]
            return list(batch), []
        return [batch], []

    def _metrics_name(self):
        names = ["loss"]
        for m in self._metrics:
            names.extend(m.name() if isinstance(m.name(), list) else [m.name()])
        return names

    def _make_logs(self, result):
        logs = {}
        if isinstance(result, tuple):
            losses, metrics = result
            logs["loss"] = losses[0]
            for m, v in zip(self._metrics, metrics):
                names = m.name() if isinstance(m.name(), list) else [m.name()]
                vals = v if isinstance(v, list) else [v]
                for n, val in zip(names, vals):
                    logs[n] = val
        else:
            logs["loss"] = result[0]
        return logs


def summary(net, input_size=None, dtypes=None, input=None):
    """paddle.summary parity — parameter table."""
    rows = []
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = p.size
        total += n
        if not p.stop_gradient:
            trainable += n
        rows.append((name, tuple(p.shape), n))
    width = max([len(r[0]) for r in rows], default=20) + 2
    lines = [f"{'Layer (param)':{width}s} {'Shape':20s} {'Param #':>12s}"]
    lines.append("-" * (width + 34))
    for name, shape, n in rows:
        lines.append(f"{name:{width}s} {str(shape):20s} {n:12,d}")
    lines.append("-" * (width + 34))
    lines.append(f"Total params: {total:,d}")
    lines.append(f"Trainable params: {trainable:,d}")
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}
