"""paddle.flops — model FLOPs report.

Parity: reference ``python/paddle/hapi/dynamic_flops.py`` (per-layer-type op
counting tables). TPU-native: ask the COMPILER — the model forward is traced
and XLA's ``cost_analysis`` returns exact flops/bytes for the optimized
program, covering every op (no per-layer table to maintain).
"""
from __future__ import annotations

import numpy as np
import jax

from ..core.engine import no_grad
from ..core.tensor import Tensor


def flops(net, input_size, custom_ops=None, print_detail=False):
    """FLOPs of one forward pass at ``input_size`` (list, with batch dim)."""
    shape = tuple(int(s) for s in input_size)
    params = list(net.parameters())
    buffers = list(net.buffers())

    def fwd(x, *param_arrays):
        saved = [(p, p._data) for p in params + buffers]
        try:
            for p, a in zip(params, param_arrays):
                p._data = a
            with no_grad():
                out = net(Tensor(x, stop_gradient=True))
            return out._data if isinstance(out, Tensor) else out
        finally:
            for p, a in saved:
                p._data = a

    x = np.zeros(shape, np.float32)
    compiled = jax.jit(fwd).lower(x, *[p._data for p in params]).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    total = float(cost.get("flops", 0.0))
    n_params = sum(p.size for p in params)
    if print_detail:
        print(f"Total Flops: {total:,.0f}  Total Params: {n_params:,}")
        print(f"Bytes accessed: {float(cost.get('bytes accessed', 0)):,.0f}")
    return int(total)
