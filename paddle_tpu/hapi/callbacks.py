"""hapi callbacks (reference python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import os
import time


__all__ = ['Callback', 'CallbackList', 'ProgBarLogger', 'ModelCheckpoint', 'EarlyStopping', 'LRScheduler', 'config_callbacks', 'ReduceLROnPlateau', 'VisualDL']


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_begin(self, mode, logs=None):
        pass

    def on_end(self, mode, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_batch_begin(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_begin", lambda *a: None)(step, logs)

    def on_batch_end(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_end", lambda *a: None)(step, logs)


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = callbacks

    def __iter__(self):
        return iter(self.callbacks)

    def _call(self, name, *args):
        for cb in self.callbacks:
            getattr(cb, name)(*args)

    def on_begin(self, mode, logs=None):
        self._call("on_begin", mode, logs)

    def on_end(self, mode, logs=None):
        self._call("on_end", mode, logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        self._call("on_batch_begin", mode, step, logs)

    def on_batch_end(self, mode, step, logs=None):
        self._call("on_batch_end", mode, step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose
        self._t0 = None

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                for k, v in (logs or {}).items()
                if k not in ("batch_size",)
            )
            print(f"Epoch {self.epoch} step {step}: {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - (self._t0 or time.time())
            items = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}" for k, v in (logs or {}).items()
            )
            print(f"Epoch {epoch} done in {dt:.1f}s: {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1, min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = min_delta
        self.best = None
        self.wait = 0
        self.mode = "min" if mode in ("auto", "min") else "max"

    def on_epoch_end(self, epoch, logs=None):
        val = (logs or {}).get(self.monitor)
        if val is None:
            return
        better = (
            self.best is None
            or (self.mode == "min" and val < self.best - self.min_delta)
            or (self.mode == "max" and val > self.best + self.min_delta)
        )
        if better:
            self.best = val
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience and self.model is not None:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        from ..optimizer.lr import LRScheduler as Sched

        if opt is not None and isinstance(opt._learning_rate, Sched):
            return opt._learning_rate
        return None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if s is not None and self.by_step:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if s is not None and self.by_epoch:
            s.step()


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None, steps=None, log_freq=2, verbose=2, save_freq=1, save_dir=None, metrics=None, mode="train"):
    cbks = list(callbacks) if callbacks else []
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks.append(LRScheduler())
    cl = CallbackList(cbks)
    for c in cbks:
        c.set_model(model)
        c.set_params(
            {"epochs": epochs, "steps": steps, "verbose": verbose, "metrics": metrics or []}
        )
    return cl


class ReduceLROnPlateau(Callback):
    """Reference hapi/callbacks.py ReduceLROnPlateau:958 — shrink the
    optimizer LR when the monitored metric plateaus."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.mode = "min" if mode in ("auto", "min") else "max"
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.wait = 0
        self.cooldown_counter = 0

    def on_epoch_end(self, epoch, logs=None):
        val = (logs or {}).get(self.monitor)
        opt = getattr(self.model, "_optimizer", None) if self.model else None
        if val is None or opt is None:
            return
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        better = (
            self.best is None
            or (self.mode == "min" and val < self.best - self.min_delta)
            or (self.mode == "max" and val > self.best + self.min_delta)
        )
        if better:
            self.best = val
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                from ..optimizer.lr import LRScheduler as _Sched

                if isinstance(getattr(opt, "_learning_rate", None), _Sched):
                    # reference raises here: set_lr on a scheduler-driven
                    # optimizer would silently kill the schedule
                    raise TypeError(
                        "ReduceLROnPlateau cannot adjust an optimizer driven "
                        "by an LRScheduler; use optimizer.lr.ReduceOnPlateau "
                        "as the scheduler instead"
                    )
                old = float(opt.get_lr())
                new = max(old * self.factor, self.min_lr)
                if new < old:
                    opt.set_lr(new)
                    if self.verbose:
                        print(f"ReduceLROnPlateau: lr {old:g} -> {new:g}")
                self.cooldown_counter = self.cooldown
                self.wait = 0


class VisualDL(Callback):
    """Reference hapi/callbacks.py VisualDL:843. The visualdl service isn't
    available here (zero egress), so scalars stream to
    ``<log_dir>/scalars.jsonl`` — same callback surface, greppable output."""

    def __init__(self, log_dir):
        super().__init__()
        self.log_dir = log_dir
        self._fh = None
        self._step = 0

    def _write(self, tag, logs):
        import json
        import time

        if self._fh is None:
            os.makedirs(self.log_dir, exist_ok=True)
            self._fh = open(os.path.join(self.log_dir, "scalars.jsonl"), "a")
        # global_step is ours (monotonic across epochs); logs may carry its
        # own per-epoch 'step' key, which must not clobber it
        rec = {"global_step": self._step, "tag": tag, "ts": time.time()}
        for k, v in (logs or {}).items():
            if k in ("global_step", "tag", "ts"):
                continue
            try:
                rec[k] = float(v)
            except (TypeError, ValueError):
                pass
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        if self._step % 10 == 0:
            self._write("train", logs)

    def on_epoch_end(self, epoch, logs=None):
        self._write("train_epoch", logs)

    def on_end(self, mode, logs=None):
        # the harness delivers end-of-run as on_end(mode, logs)
        if mode == "eval":
            self._write("eval", logs)
        if mode == "train" and self._fh is not None:
            self._fh.close()
            self._fh = None
