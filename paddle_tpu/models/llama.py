"""Llama model family (BASELINE: Llama-7B TP×PP hybrid).

TPU-first: RMSNorm + SwiGLU + RoPE with Megatron-shardable weights; uniform
decoder stack (pipeline-stageable); rotary embedding computed inside the
traced step (no host-side caches).
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from .. import nn
from ..core.dispatch import as_tensor, eager_call
from ..core.tensor import Tensor
from ..nn import functional as F
from ..distributed.fleet.meta_parallel.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
)


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: Optional[int] = None
    intermediate_size: Optional[int] = None
    max_position_embeddings: int = 2048
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    initializer_range: float = 0.02

    @property
    def ffn_size(self):
        if self.intermediate_size is not None:
            return self.intermediate_size
        return int(2 * (4 * self.hidden_size) / 3 + 255) // 256 * 256

    @property
    def kv_heads(self):
        return self.num_kv_heads or self.num_heads


class RMSNorm(nn.Layer):
    def __init__(self, hidden_size, eps=1e-6):
        super().__init__()
        self.weight = self.create_parameter([hidden_size], default_initializer=nn.initializer.Constant(1.0))
        self.eps = eps

    def forward(self, x):
        return eager_call(
            "rms_norm",
            lambda a, w, eps: (a * jax.lax.rsqrt(jnp.mean(jnp.square(a.astype(jnp.float32)), -1, keepdims=True) + eps)).astype(a.dtype) * w,
            [as_tensor(x), self.weight],
            {"eps": self.eps},
        )


def apply_rope(q, k, theta=10000.0):
    """Rotary embedding as one traced op over (B, T, H, D) q/k."""

    def fn(qa, ka, theta):
        B, T, H, D = qa.shape
        pos = jnp.arange(T, dtype=jnp.float32)
        inv = 1.0 / (theta ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
        ang = pos[:, None] * inv[None, :]  # (T, D/2)
        cos = jnp.cos(ang)[None, :, None, :]
        sin = jnp.sin(ang)[None, :, None, :]

        def rot(x):
            x1, x2 = x[..., ::2], x[..., 1::2]
            o1 = x1 * cos - x2 * sin
            o2 = x2 * cos + x1 * sin
            # angles are f32: cast back so bf16 q/k stay bf16 (a silent f32
            # upcast here forced the whole attention out of the MXU-native
            # dtype and crashed the Pallas path on mixed-dtype operands)
            return jnp.stack([o1, o2], axis=-1).reshape(x.shape).astype(x.dtype)

        return rot(qa), rot(ka)

    out = eager_call("rope", fn, [as_tensor(q), as_tensor(k)], {"theta": theta})
    return out[0], out[1]


class LlamaAttention(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_heads
        self.kv_heads = config.kv_heads
        self.head_dim = h // config.num_heads
        self.q_proj = ColumnParallelLinear(h, h, has_bias=False, gather_output=False)
        self.k_proj = ColumnParallelLinear(h, self.kv_heads * self.head_dim, has_bias=False, gather_output=False)
        self.v_proj = ColumnParallelLinear(h, self.kv_heads * self.head_dim, has_bias=False, gather_output=False)
        self.o_proj = RowParallelLinear(h, h, has_bias=False, input_is_parallel=True)
        self.theta = config.rope_theta

    def forward(self, x, attn_mask=None):
        B, T = x.shape[0], x.shape[1]
        q = self.q_proj(x)
        k = self.k_proj(x)
        v = self.v_proj(x)
        lh = q.shape[-1] // self.head_dim
        lkv = k.shape[-1] // self.head_dim
        q = q.reshape([B, T, lh, self.head_dim])
        k = k.reshape([B, T, lkv, self.head_dim])
        v = v.reshape([B, T, lkv, self.head_dim])
        q, k = apply_rope(q, k, self.theta)
        if lkv != lh:  # grouped-query attention: repeat kv heads
            from ..ops.manipulation import repeat_interleave

            k = repeat_interleave(k, lh // lkv, axis=2)
            v = repeat_interleave(v, lh // lkv, axis=2)
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask, is_causal=attn_mask is None, training=self.training)
        return self.o_proj(out.reshape([B, T, lh * self.head_dim]))


class LlamaMLP(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        h, f = config.hidden_size, config.ffn_size
        self.gate_proj = ColumnParallelLinear(h, f, has_bias=False, gather_output=False)
        self.up_proj = ColumnParallelLinear(h, f, has_bias=False, gather_output=False)
        self.down_proj = RowParallelLinear(f, h, has_bias=False, input_is_parallel=True)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = RMSNorm(config.hidden_size, config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = RMSNorm(config.hidden_size, config.rms_norm_eps)
        self.mlp = LlamaMLP(config)

    def forward(self, x, attn_mask=None):
        x = x + self.self_attn(self.input_layernorm(x), attn_mask)
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        init = nn.initializer.Normal(std=config.initializer_range)
        self.embed_tokens = VocabParallelEmbedding(config.vocab_size, config.hidden_size, weight_attr=init)
        self.layers = nn.LayerList([LlamaDecoderLayer(config) for _ in range(config.num_layers)])
        self.norm = RMSNorm(config.hidden_size, config.rms_norm_eps)

    def forward(self, input_ids, attn_mask=None):
        x = self.embed_tokens(input_ids)
        for layer in self.layers:
            x = layer(x, attn_mask)
        return self.norm(x)


class LlamaForCausalLM(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.model = LlamaModel(config)
        self.lm_head = ColumnParallelLinear(config.hidden_size, config.vocab_size, has_bias=False, gather_output=True)

    def forward(self, input_ids, attn_mask=None):
        return self.lm_head(self.model(input_ids, attn_mask))

    def loss(self, input_ids, labels):
        logits = self(input_ids)
        return F.cross_entropy(logits.reshape([-1, logits.shape[-1]]), labels.reshape([-1]))

    def generate(self, input_ids, max_new_tokens=32, temperature=1.0, top_k=0,
                 top_p=1.0, eos_token_id=None, do_sample=True):
        """KV-cached compiled decode (models/generation.py Llama path: RoPE
        at absolute cache positions, GQA caches only KV heads)."""
        from .generation import generate_llama

        return generate_llama(
            self, input_ids, max_new_tokens=max_new_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p,
            eos_token_id=eos_token_id, do_sample=do_sample,
        )


def llama_tiny(**kw):
    return LlamaConfig(vocab_size=1024, hidden_size=128, num_layers=4, num_heads=4, max_position_embeddings=256, **kw)


def llama_7b(**kw):
    return LlamaConfig(vocab_size=32000, hidden_size=4096, num_layers=32, num_heads=32, **kw)
